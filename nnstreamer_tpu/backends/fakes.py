"""Deterministic fake backends for tests.

Reference analog: the custom-filter scaffolding subplugins used as fake
backends throughout the reference test suite
(``tests/nnstreamer_example/``: passthrough, scaler, average, framecounter)
so element behavior is testable without any NN framework.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.resilience import FAULTS, DeviceLostError, DeviceOomError
from ..core.types import StreamSpec, TensorSpec
from .base import FilterBackend, register_backend


class Passthrough(FilterBackend):
    """Identity model (≙ nnstreamer_customfilter_example_passthrough)."""

    NAME = "passthrough"

    def framework_info(self):
        info = super().framework_info()
        info.run_without_model = True
        return info

    def set_input_info(self, in_spec: StreamSpec) -> StreamSpec:
        return in_spec

    def invoke(self, inputs: List[Any]) -> List[Any]:
        return list(inputs)

    def invoke_batch(self, inputs: List[Any]) -> List[Any]:
        return list(inputs)


class Scaler(FilterBackend):
    """Multiply by a constant from custom props ("factor:2") — the analog of
    the reference scaler example used to check option plumbing."""

    NAME = "scaler"

    def framework_info(self):
        info = super().framework_info()
        info.run_without_model = True
        return info

    @property
    def factor(self) -> float:
        return float(self.custom_props.get("factor", "2"))

    def set_input_info(self, in_spec: StreamSpec) -> StreamSpec:
        return in_spec

    def invoke(self, inputs: List[Any]) -> List[Any]:
        return [np.asarray(a) * np.asarray(a).dtype.type(self.factor) for a in inputs]

    def invoke_batch(self, inputs: List[Any]) -> List[Any]:
        return self.invoke(inputs)


class Average(FilterBackend):
    """Reduce each tensor to its scalar mean (float32, shape (1,))
    (≙ nnstreamer_customfilter_example_average)."""

    NAME = "average"

    def framework_info(self):
        info = super().framework_info()
        info.run_without_model = True
        return info

    def set_input_info(self, in_spec: StreamSpec) -> StreamSpec:
        return StreamSpec(
            tuple(TensorSpec((1,), np.float32, t.name) for t in in_spec.tensors),
            in_spec.fmt,
            in_spec.framerate,
        )

    def invoke(self, inputs: List[Any]) -> List[Any]:
        return [np.asarray([np.asarray(a).mean()], np.float32) for a in inputs]


class FrameCounter(FilterBackend):
    """Emit a running frame counter (tests ordering/liveness)."""

    NAME = "framecounter"

    def __init__(self):
        super().__init__()
        self._n = 0

    def framework_info(self):
        info = super().framework_info()
        info.run_without_model = True
        return info

    def set_input_info(self, in_spec: StreamSpec) -> StreamSpec:
        return StreamSpec(
            (TensorSpec((1,), np.int64, "count"),), in_spec.fmt, in_spec.framerate
        )

    def invoke(self, inputs: List[Any]) -> List[Any]:
        self._n += 1
        return [np.asarray([self._n], np.int64)]


class FakeDeviceArray:
    """A numpy value masquerading as an ASYNC device buffer.

    Models the accelerator contract the async feed is built against:
    ``is_ready()`` reflects device-side completion, ``copy_to_host_async``
    is a prefetch *hint* (over the dev tunnel it buys nothing — matching
    the worst case), and ``__array__`` (materialization) blocks until
    completion and then pays the transfer cost ON THE CALLING THREAD.
    Every pre-completion blocking sync is recorded with the calling
    thread's name, so tests can pin "the dispatch thread never sat inside
    device_get" structurally instead of by timing.

    ``done`` may be a tuple of events — a MESH-sharded value whose shards
    complete independently: the buffer is ready only when EVERY shard is
    (the contract the sharded CompletionWindow rides — readiness means
    all shards, never just shard 0).
    """

    __slots__ = ("_value", "_done", "_transfer_s", "_sim", "_host")

    def __init__(self, value: np.ndarray, done,
                 transfer_s: float, sim: "AsyncSim"):
        self._value = value
        self._done = done if isinstance(done, tuple) else (done,)
        self._transfer_s = transfer_s
        self._sim = sim
        self._host: Optional[np.ndarray] = None  # transfer paid once

    @property
    def shape(self):
        return self._value.shape

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def ndim(self):
        return self._value.ndim

    def is_ready(self) -> bool:
        return all(ev.is_set() for ev in self._done)

    def copy_to_host_async(self) -> None:
        self._sim.copy_hints += 1  # hint only; no overlap (tunnel-real)

    def _materialize(self) -> np.ndarray:
        if self._host is None:
            if not self.is_ready():
                self._sim.note_blocking_sync()
                for ev in self._done:
                    ev.wait()
            if self._transfer_s > 0:
                time.sleep(self._transfer_s)  # transfer occupies the caller
            self._host = self._value
        return self._host

    def __array__(self, dtype=None, copy=None):
        host = self._materialize()
        return host if dtype is None else host.astype(dtype, copy=False)

    def __getitem__(self, idx):
        return self._materialize()[idx]

    def __len__(self) -> int:
        return len(self._value)


class AsyncSim(FilterBackend):
    """Deterministic async-device simulator: affine ``y = 2x + 1`` with a
    single-server device worker (one batch in service at a time) and
    tunable costs, for CPU-proxy evidence of the async feed's structure.

    Custom props (milliseconds unless noted):

    * ``compute_ms``  — device service time per batch (single server).
    * ``transfer_ms`` — device->host materialization cost paid on the
      SYNCING thread (the ``device_get`` analog).
    * ``dispatch_ms`` — invoke-dispatch cost paid on the dispatch thread
      (the stack-jit + XLA-dispatch analog).
    * ``h2d_ms``      — ``to_device`` cost paid on the staging-lane thread.
    * ``manual``      — "1": batches complete only via :meth:`release_one`
      / :meth:`release_all` (deterministic window unit tests).
    * ``mesh_dp``     — N > 1: a SIMULATED dp mesh — N independent device
      servers, each serving its 1/N batch shard concurrently (per-shard
      service = compute_ms / N, the compute-bound split), outputs ready
      only when ALL shards are.  This is the deterministic twin the
      sharded-dataplane perf floor drives: on a single-core box the real
      XLA CPU proxy mesh cannot exhibit dp parallelism (both virtual
      devices share the one core), so the ≥1.5x dp:2 aggregate floor
      measures the FEED/dispatch structure over sleeping shard servers —
      the PR-9 SimSlotModel discipline.  Distinct from the jax-xla
      ``mesh=`` prop (a real jax.sharding.Mesh).

    Device-resource chaos (the typed taxonomy, core/resilience.py —
    deterministic twins of the chip failing, so the OOM/lost recovery
    ladders are testable chip-free):

    * ``oom_at``   — invoke_batch index K (0-based) raises
      :class:`~..core.resilience.DeviceOomError` ONCE (the injected OOM
      burst: the shrink-retry ladder must redeliver every frame).
    * ``oom_every``— every Nth invoke_batch raises DeviceOomError
      (sustained pressure; N >= 2 or the retry itself would OOM forever).
    * ``lost_at``  — invoke_batch index K raises
      :class:`~..core.resilience.DeviceLostError` ONCE (mesh-member
      death) and marks the backend degraded.

    The process-wide ``device.oom`` / ``device.lost`` fault sites fire
    here too, mirroring the jax-xla backend's sites.
    """

    NAME = "async-sim"
    SUPPORTS_STAGING = True  # to_device really copies off the staging buf

    def __init__(self):
        super().__init__()
        # one FIFO + one serve thread per simulated device server
        # (mesh_dp sizes the list; the default is the single server)
        self._pending: List["deque[threading.Event]"] = [deque()]
        self._cv = threading.Condition()
        self._workers: List[Optional[threading.Thread]] = [None]
        self._closed = False
        # census (inspected by tests; written under locks / GIL-atomic)
        self.blocking_syncs: List[str] = []
        self.copy_hints = 0
        self.dispatched = 0
        self._attempts = 0  # includes faulted attempts (chaos knobs)
        self.busy_s = 0.0  # actual device-service wall time (not nominal)

    # -- knobs ---------------------------------------------------------------
    def _ms(self, key: str, default: float = 0.0) -> float:
        return float(self.custom_props.get(key, default)) / 1000.0

    @property
    def manual(self) -> bool:
        return self.custom_props.get("manual", "") in ("1", "true")

    @property
    def mesh_dp(self) -> int:
        return max(1, int(self.custom_props.get("mesh_dp", "1")))

    def note_blocking_sync(self) -> None:
        self.blocking_syncs.append(threading.current_thread().name)

    # -- framework info -------------------------------------------------------
    def framework_info(self):
        info = super().framework_info()
        info.run_without_model = True
        return info

    def set_input_info(self, in_spec: StreamSpec) -> StreamSpec:
        return in_spec

    # -- device workers -------------------------------------------------------
    def _ensure_servers(self) -> None:
        nsrv = self.mesh_dp
        with self._cv:
            while len(self._pending) < nsrv:
                self._pending.append(deque())
                self._workers.append(None)
        if self.manual:
            return
        for i in range(nsrv):
            w = self._workers[i]
            if w is None or not w.is_alive():
                self._closed = False
                self._workers[i] = threading.Thread(
                    target=self._serve, args=(i,),
                    name=f"async-sim-device-{i}" if nsrv > 1
                    else "async-sim-device",
                    daemon=True)
                self._workers[i].start()

    def _serve(self, idx: int) -> None:
        # per-shard service: a dp mesh splits the batch, so each server
        # pays its 1/N share of the whole-batch compute knob
        service = self._ms("compute_ms") / self.mesh_dp
        while True:
            with self._cv:
                while not self._pending[idx]:
                    if self._closed:
                        return
                    self._cv.wait()
                ev = self._pending[idx].popleft()
            if service > 0:
                t0 = time.perf_counter()
                time.sleep(service)  # per server: its batches serialize
                # sleep() overshoots by timer granularity: record the
                # ACTUAL service time so overlap ratios divide by what
                # the device really spent, not the nominal knob
                self.busy_s += time.perf_counter() - t0
            ev.set()

    def release_one(self, server: int = 0) -> bool:
        """manual mode: complete ``server``'s oldest in-service shard
        (the single-server default keeps the pre-mesh signature)."""
        with self._cv:
            if server >= len(self._pending) or not self._pending[server]:
                return False
            self._pending[server].popleft().set()
            return True

    def release_all(self) -> int:
        n = 0
        with self._cv:
            for dq in self._pending:
                while dq:
                    dq.popleft().set()
                    n += 1
        return n

    def close(self):
        with self._cv:
            self._closed = True
            for dq in self._pending:
                for ev in dq:
                    ev.set()  # never strand a parked batch at teardown
                dq.clear()
            self._cv.notify_all()
            workers = [w for w in self._workers if w is not None]
            self._workers = [None] * len(self._workers)
        for worker in workers:
            if worker.is_alive():
                worker.join(timeout=2.0)

    # -- execution ------------------------------------------------------------
    def to_device(self, arrays: List[Any]) -> List[Any]:
        h2d = self._ms("h2d_ms")
        if h2d > 0:
            time.sleep(h2d)  # transfer occupies the lane thread
        # a real placement COPIES off the staging buffer (the lane's
        # buffer-reuse contract relies on it)
        return [np.array(a, copy=True) for a in arrays]

    def invoke(self, inputs: List[Any]) -> List[Any]:
        return [np.asarray(a) * 2 + 1 for a in inputs]

    def _maybe_device_fault(self, idx: int) -> None:
        """Deterministic device-resource chaos at invoke index ``idx``
        (see the class docstring knobs), plus the process-wide fault
        sites the jax-xla backend also instruments."""
        if FAULTS.is_armed():
            FAULTS.check("device.oom")
            FAULTS.check("device.lost")
        cp = self.custom_props
        lost_at = cp.get("lost_at")
        if lost_at is not None and idx == int(lost_at):
            self.degraded = True
            raise DeviceLostError(
                "async-sim: simulated mesh-member death", device_ids=(0,))
        oom_at = cp.get("oom_at")
        if oom_at is not None and idx == int(oom_at):
            raise DeviceOomError("async-sim: simulated HBM exhaustion")
        every = int(cp.get("oom_every", "0") or 0)
        if every >= 2 and idx > 0 and (idx % every) == 0:
            raise DeviceOomError("async-sim: simulated sustained HBM pressure")

    def invoke_batch(self, inputs: List[Any]) -> List[Any]:
        dispatch = self._ms("dispatch_ms")
        if dispatch > 0:
            time.sleep(dispatch)  # dispatch cost on the calling thread
        # faults key off the ATTEMPT index (advances even when the
        # attempt faults): "oom_at:K" fires exactly once and the
        # element's retry — a fresh attempt — proceeds
        idx = self._attempts
        self._attempts += 1
        self._maybe_device_fault(idx)
        self.dispatched += 1
        nsrv = self.mesh_dp
        # one completion event per dp shard, each queued on its own
        # server: the output is ready only when EVERY shard completed
        done = tuple(threading.Event() for _ in range(nsrv))
        outs = [
            FakeDeviceArray(
                np.asarray(a) * 2 + 1, done, self._ms("transfer_ms"), self)
            for a in inputs
        ]
        self._ensure_servers()  # grows queues/workers to nsrv (one owner)
        with self._cv:
            for i, ev in enumerate(done):
                self._pending[i].append(ev)
            self._cv.notify_all()
        return outs


for _cls in (Passthrough, Scaler, Average, FrameCounter, AsyncSim):
    register_backend(_cls)
