"""Deterministic fake backends for tests.

Reference analog: the custom-filter scaffolding subplugins used as fake
backends throughout the reference test suite
(``tests/nnstreamer_example/``: passthrough, scaler, average, framecounter)
so element behavior is testable without any NN framework.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.types import StreamSpec, TensorSpec
from .base import FilterBackend, register_backend


class Passthrough(FilterBackend):
    """Identity model (≙ nnstreamer_customfilter_example_passthrough)."""

    NAME = "passthrough"

    def framework_info(self):
        info = super().framework_info()
        info.run_without_model = True
        return info

    def set_input_info(self, in_spec: StreamSpec) -> StreamSpec:
        return in_spec

    def invoke(self, inputs: List[Any]) -> List[Any]:
        return list(inputs)

    def invoke_batch(self, inputs: List[Any]) -> List[Any]:
        return list(inputs)


class Scaler(FilterBackend):
    """Multiply by a constant from custom props ("factor:2") — the analog of
    the reference scaler example used to check option plumbing."""

    NAME = "scaler"

    def framework_info(self):
        info = super().framework_info()
        info.run_without_model = True
        return info

    @property
    def factor(self) -> float:
        return float(self.custom_props.get("factor", "2"))

    def set_input_info(self, in_spec: StreamSpec) -> StreamSpec:
        return in_spec

    def invoke(self, inputs: List[Any]) -> List[Any]:
        return [np.asarray(a) * np.asarray(a).dtype.type(self.factor) for a in inputs]

    def invoke_batch(self, inputs: List[Any]) -> List[Any]:
        return self.invoke(inputs)


class Average(FilterBackend):
    """Reduce each tensor to its scalar mean (float32, shape (1,))
    (≙ nnstreamer_customfilter_example_average)."""

    NAME = "average"

    def framework_info(self):
        info = super().framework_info()
        info.run_without_model = True
        return info

    def set_input_info(self, in_spec: StreamSpec) -> StreamSpec:
        return StreamSpec(
            tuple(TensorSpec((1,), np.float32, t.name) for t in in_spec.tensors),
            in_spec.fmt,
            in_spec.framerate,
        )

    def invoke(self, inputs: List[Any]) -> List[Any]:
        return [np.asarray([np.asarray(a).mean()], np.float32) for a in inputs]


class FrameCounter(FilterBackend):
    """Emit a running frame counter (tests ordering/liveness)."""

    NAME = "framecounter"

    def __init__(self):
        super().__init__()
        self._n = 0

    def framework_info(self):
        info = super().framework_info()
        info.run_without_model = True
        return info

    def set_input_info(self, in_spec: StreamSpec) -> StreamSpec:
        return StreamSpec(
            (TensorSpec((1,), np.int64, "count"),), in_spec.fmt, in_spec.framerate
        )

    def invoke(self, inputs: List[Any]) -> List[Any]:
        self._n += 1
        return [np.asarray([self._n], np.int64)]


for _cls in (Passthrough, Scaler, Average, FrameCounter):
    register_backend(_cls)
