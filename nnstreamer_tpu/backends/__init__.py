"""Filter backends (≙ L2 filter subplugins, ext/nnstreamer/tensor_filter/).

Importing registers the built-in backends; heavyweight ones (jax-xla, torch)
register lazily so importing the package stays light.
"""

from ..core import registry
from .base import FilterBackend, FrameworkInfo, find_backend, parse_accelerator, register_backend  # noqa: F401
from . import fakes  # noqa: F401 — registers passthrough/scaler/average/framecounter
from .custom_easy import CustomEasy, register_custom_easy, unregister_custom_easy  # noqa: F401

registry.register_lazy(registry.KIND_FILTER, "jax-xla", "nnstreamer_tpu.backends.jax_xla:JaxXla")
registry.register_lazy(registry.KIND_FILTER, "python3", "nnstreamer_tpu.backends.python3:Python3Backend")
registry.register_lazy(registry.KIND_FILTER, "torch", "nnstreamer_tpu.backends.torch_cpu:TorchBackend")
registry.register_lazy(registry.KIND_FILTER, "tflite", "nnstreamer_tpu.backends.tflite_import:TFLiteBackend")
registry.register_lazy(registry.KIND_FILTER, "onnx", "nnstreamer_tpu.backends.onnx_import:OnnxBackend")
registry.register_lazy(registry.KIND_FILTER, "custom", "nnstreamer_tpu.backends.custom_native:CustomNative")
