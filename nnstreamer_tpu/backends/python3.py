"""python3 filter backend: user-scripted model in a .py file.

Reference: ``ext/nnstreamer/tensor_filter/tensor_filter_python3.cc`` +
``extra/nnstreamer_python3_helper.cc`` — the user script defines a class
with ``getInputDim/getOutputDim`` (static shapes) or ``setInputDim``
(shape-polymorphic) plus ``invoke`` (:285-302, :651-672).

Contract here: ``model=<script.py>`` where the script defines a class
``CustomFilter`` with:

- ``invoke(self, inputs: list[np.ndarray]) -> list[np.ndarray]`` (required)
- ``get_model_info(self) -> (in_spec, out_spec)`` — StreamSpecs or
  "type:dim" string lists (optional)
- ``set_input_info(self, in_spec) -> out_spec`` (optional)
- ``set_options(self, custom: dict)`` (optional; receives custom props)

or module-level ``invoke(inputs)`` for the simplest case.
"""

from __future__ import annotations

import importlib.util
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from .base import FilterBackend


def _coerce_spec(obj) -> Optional[StreamSpec]:
    if obj is None or isinstance(obj, StreamSpec):
        return obj
    if isinstance(obj, (list, tuple)):  # e.g. ["float32:3:224:224", ...]
        return StreamSpec(
            tuple(TensorSpec.from_string(s) if isinstance(s, str) else s
                  for s in obj),
            FORMAT_STATIC,
        )
    if isinstance(obj, str):
        return StreamSpec.from_string(obj)
    raise TypeError(f"cannot interpret {obj!r} as a StreamSpec")


class Python3Backend(FilterBackend):
    NAME = "python3"

    def __init__(self):
        super().__init__()
        self._impl = None
        self._fn = None

    def framework_info(self):
        info = super().framework_info()
        info.hw_list = ("cpu",)
        return info

    def open(self, model_path: Optional[str], props: Dict[str, Any]) -> None:
        super().open(model_path, props)
        if not model_path or not os.path.isfile(model_path):
            raise FileNotFoundError(
                f"python3 backend needs model=<script.py>, got {model_path!r}")
        name = "nns_tpu_filter_" + os.path.splitext(os.path.basename(model_path))[0]
        spec = importlib.util.spec_from_file_location(name, model_path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        if hasattr(mod, "CustomFilter"):
            self._impl = mod.CustomFilter()
            if hasattr(self._impl, "set_options"):
                self._impl.set_options(dict(self.custom_props))
        elif hasattr(mod, "invoke"):
            self._fn = mod.invoke
        else:
            raise ValueError(
                f"{model_path}: defines neither CustomFilter nor invoke()")

    def close(self) -> None:
        self._impl = self._fn = None

    def get_model_info(self) -> Tuple[Optional[StreamSpec], Optional[StreamSpec]]:
        if self._impl is not None and hasattr(self._impl, "get_model_info"):
            i, o = self._impl.get_model_info()
            return _coerce_spec(i), _coerce_spec(o)
        return None, None

    def set_input_info(self, in_spec: StreamSpec) -> StreamSpec:
        if self._impl is not None and hasattr(self._impl, "set_input_info"):
            return _coerce_spec(self._impl.set_input_info(in_spec))
        # shape-polymorphic default: probe with zeros (≙ setInputDim)
        if in_spec.is_static:
            zeros = [np.zeros(t.shape, t.dtype) for t in in_spec.tensors]
            outs = self.invoke(zeros)
            return StreamSpec(
                tuple(TensorSpec(o.shape, o.dtype) for o in outs), FORMAT_STATIC,
                in_spec.framerate,
            )
        raise NotImplementedError(f"{self.NAME}: cannot derive output schema")

    def invoke(self, inputs: List[Any]) -> List[Any]:
        arrays = [np.asarray(a) for a in inputs]
        out = (self._impl.invoke(arrays) if self._impl is not None
               else self._fn(arrays))
        if not isinstance(out, (list, tuple)):
            out = [out]
        return [np.asarray(o) for o in out]
