"""Shared machinery for importer backends (tflite / onnx).

Both backends lower a foreign graph to a ``lowering.run(params, *xs)``
callable with fixed per-frame input ranks; the JaxXla plumbing then
needs (a) a model fn that vmaps the whole graph when it receives
micro-batched frames (one extra leading axis) and (b) StreamSpecs built
from the file's declared shapes with dynamic dims falling back to
stream-derived negotiation.  One implementation here so the two
importers cannot drift.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..core.types import FORMAT_STATIC, StreamSpec, TensorSpec


def batching_model_fn(run: Callable, in_ranks: Sequence[int]) -> Callable:
    """Wrap ``run(params, *xs)`` as ``fn(params, xs)``: per-frame calls
    pass through; micro-batched calls (every input one rank higher than
    declared) vmap the whole graph — still a single XLA program.  A
    declared rank of -1 (unknown) disables batch detection for that
    input."""
    import jax

    def fn(params, xs: List[Any]) -> List[Any]:
        if all(r >= 0 and x.ndim == r + 1 for x, r in zip(xs, in_ranks)):
            return list(jax.vmap(lambda *a: run(params, *a))(*xs))
        return list(run(params, *xs))

    return fn


def spec_from_shapes(
    entries: Sequence[Tuple[Optional[Sequence[Optional[int]]], Optional[str]]],
) -> Optional[StreamSpec]:
    """(shape, dtype) pairs -> StreamSpec; None when any shape/dtype is
    unknown or has dynamic dims (negotiation derives it from the stream
    instead)."""
    tensors = []
    for shape, dtype in entries:
        if shape is None or dtype is None or any(
                d is None or (isinstance(d, int) and d < 0) for d in shape):
            return None
        tensors.append(TensorSpec(tuple(int(d) for d in shape),
                                  np.dtype(dtype)))
    return StreamSpec(tuple(tensors), FORMAT_STATIC)
