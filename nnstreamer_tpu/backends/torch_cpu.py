"""torch filter backend: TorchScript models on CPU.

Reference: ``ext/nnstreamer/tensor_filter/tensor_filter_pytorch.cc`` (774
LoC) — loads a TorchScript archive, maps tensors in/out, optional GPU via
ini.  Here: CPU-only (the image ships torch-cpu; TPU compute belongs to the
jax-xla backend — use torch for importing legacy models, not the hot path).

``model=<file.pt>`` must be a ``torch.jit.save`` archive.  Output schema is
derived by probing with zeros (≙ the reference requiring input caps and
running shape inference).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from .base import FilterBackend


class TorchBackend(FilterBackend):
    NAME = "torch"

    def __init__(self):
        super().__init__()
        self._module = None

    def framework_info(self):
        info = super().framework_info()
        info.hw_list = ("cpu",)
        return info

    def open(self, model_path: Optional[str], props: Dict[str, Any]) -> None:
        super().open(model_path, props)
        import torch

        if not model_path:
            raise ValueError("torch backend requires model=<file.pt>")
        self._module = torch.jit.load(model_path, map_location="cpu")
        self._module.eval()

    def close(self) -> None:
        self._module = None

    def set_input_info(self, in_spec: StreamSpec) -> StreamSpec:
        zeros = [np.zeros(t.shape, t.dtype) for t in in_spec.tensors]
        outs = self.invoke(zeros)
        return StreamSpec(
            tuple(TensorSpec(o.shape, o.dtype) for o in outs),
            FORMAT_STATIC,
            in_spec.framerate,
        )

    def invoke(self, inputs: List[Any]) -> List[Any]:
        import torch

        with torch.inference_mode():
            ins = [torch.from_numpy(np.ascontiguousarray(np.asarray(a)))
                   for a in inputs]
            out = self._module(*ins)
        if isinstance(out, (list, tuple)):
            outs = list(out)
        else:
            outs = [out]
        return [o.detach().cpu().numpy() for o in outs]

    def invoke_batch(self, inputs: List[Any]) -> List[Any]:
        # TorchScript modules are batch-polymorphic on the leading dim
        return self.invoke(inputs)
