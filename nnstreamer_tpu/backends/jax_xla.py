"""jax-xla: the flagship filter backend — JIT-compiles models to XLA TPU
executables.

This is the TPU-native answer to the reference's backend zoo
(``ext/nnstreamer/tensor_filter/``, e.g. ``tensor_filter_tensorflow_lite.cc``
TFLiteCore open/invoke, ``tensor_filter_edgetpu.cc`` device binding): one
backend, any JAX-expressible model, compiled once per shape bucket and
dispatched as a single XLA call per micro-batch.

Model resolution (the ``model=`` property):

* a name registered in-process via :func:`register_jax_model`
  (≙ custom-easy, but jit-compiled);
* a ``.py`` file defining ``get_model() -> (fn, params)`` where
  ``fn(params, inputs: list[Array]) -> list[Array]``
  (≙ the python3 subplugin, but the function is traced, not interpreted);
* a ``.msgpack`` flax-serialized params file with custom prop
  ``arch:<zoo-name>`` naming a model family from ``nnstreamer_tpu.models``;
* an Orbax checkpoint directory with the same ``arch:`` prop.

TPU-first design:

* **shape-bucketed compilation** — XLA needs static shapes; batches are
  padded up to the next power of two and sliced back, so a steady stream
  compiles exactly once per bucket (the "flexible tensors vs static XLA"
  policy from SURVEY §7 hard-part (b)).
* **native invoke_batch** — one XLA call per micro-batch (dispatch
  amortization; the ≥1000 fps lever).
* **donation** — input device buffers are donated to the executable where
  safe, letting XLA reuse HBM (≙ allocate-in-invoke).
* **device residency** — outputs stay on device (jax.Array); chained
  jax-xla filters never bounce through host (≙ zero-copy GstMemory).
* optional ``dtype:bfloat16`` custom prop casts params/compute to bf16
  (MXU-native).
* **sharded serving** — the ``mesh=`` prop (``mesh=tp:4`` /
  ``mesh=dp:2,tp:2``; legacy custom props ``mesh_dp:2,mesh_tp:4`` still
  accepted) runs ONE logical filter across a device mesh: params sharded
  by the parallel layer's rules (``parallel/sharding.py``) and staged
  across the WHOLE mesh before serving, ``invoke``/``invoke_batch``/
  ``invoke_batch_donated`` compiled under explicit ``NamedSharding``
  in/out specs (batch scattered over ``dp``, replicated over ``tp``),
  host-staged batches placed directly in the sharded layout by the
  ingest lane, XLA SPMD inserts the collectives.  The reference's only
  multi-device story is stream fan-out over nnstreamer-edge transports
  (SURVEY §2.3); intra-model sharding of a *serving* pipeline is
  TPU-native net-new.
"""

from __future__ import annotations

import importlib.util
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core.config import EXPORTED_MODEL_EXTS
from ..core.resilience import DeviceLostError, device_call
from ..core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from .base import FilterBackend, register_backend

_registry_lock = threading.Lock()
_model_registry: Dict[str, Tuple[Callable, Any, Optional[StreamSpec], Optional[StreamSpec]]] = {}


def register_jax_model(
    name: str,
    fn: Callable[[Any, List[Any]], List[Any]],
    params: Any = None,
    in_spec: Optional[StreamSpec] = None,
    out_spec: Optional[StreamSpec] = None,
) -> None:
    """Register an in-process JAX model under `name`.

    ``fn(params, inputs) -> outputs`` must be jit-traceable. Single-array
    models may return a bare array.
    """
    with _registry_lock:
        _model_registry[name] = (fn, params, in_spec, out_spec)


def unregister_jax_model(name: str) -> bool:
    with _registry_lock:
        return _model_registry.pop(name, None) is not None


def export_model(fn, params, frame_specs, path: str,
                 batch_polymorphic: bool = True) -> None:
    """Serialize ``fn(params, inputs) -> outputs`` as a ``.jaxexport``
    artifact (params baked in as StableHLO constants).

    ``frame_specs``: one ``(shape, dtype)`` pair per input tensor, for a
    SINGLE frame (no batch dim).  With ``batch_polymorphic`` (default) a
    symbolic leading batch dim is prepended, so the artifact serves both
    per-frame and micro-batched invokes natively — export this way unless
    the model genuinely cannot be batched.
    """
    import jax
    from jax import export as jax_export

    def call(*xs):
        out = fn(params, list(xs))
        return tuple(out) if isinstance(out, (list, tuple)) else (out,)

    specs = []
    batch = jax_export.symbolic_shape("b")[0] if batch_polymorphic else None
    for shape, dtype in frame_specs:
        full = ((batch,) + tuple(shape)) if batch_polymorphic else tuple(shape)
        specs.append(jax.ShapeDtypeStruct(full, np.dtype(dtype)))
    exported = jax_export.export(jax.jit(call))(*specs)
    with open(path, "wb") as f:
        f.write(exported.serialize())


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


# accelerator wish name -> candidate jax platform names, in probe order.
# On this hardware "tpu" may surface as platform "tpu" or "axon"; "npu"
# wishes (reference edgetpu/srnpu parlance) map to the TPU too.
_WISH_PLATFORMS = {
    "auto": (None,),
    "default": (None,),
    "tpu": ("tpu", "axon"),
    "npu": ("tpu", "axon"),
    "npu.edgetpu": ("tpu", "axon"),
    "gpu": ("gpu", "cuda", "rocm"),
    "cpu": ("cpu",),
    "cpu.simd": ("cpu",),
}

# the wish vocabulary is owned by base.KNOWN_ACCELERATORS (the parse
# side); this mapping must cover it so parse/placement cannot drift.
# Explicit raise (not assert): must survive python -O
from .base import KNOWN_ACCELERATORS as _KNOWN

if set(_WISH_PLATFORMS) != set(_KNOWN):
    raise ImportError(
        "accelerator vocabulary drift between base.KNOWN_ACCELERATORS and "
        f"jax_xla._WISH_PLATFORMS: {sorted(set(_WISH_PLATFORMS) ^ set(_KNOWN))}"
    )
del _KNOWN


def pick_device(wishes):
    """Resolve an accelerator wish list to a concrete jax.Device.

    Honors the reference's ordered-wish semantics
    (``tensor_filter_common.c:2719-2878``: first available hardware in
    the list wins) plus a TPU-native extension: a ``.N`` suffix pins a
    specific device ordinal — ``accelerator=true:tpu.1,cpu`` means
    "second TPU chip, else CPU".  ``auto``/``default`` take the process
    default device.  Unknown / unavailable wishes fall through to the
    next; an exhausted list falls back to the default device.
    """
    import jax

    from ..core.log import get_logger

    family_fallback = None  # first wish whose PLATFORM exists at all
    for wish in wishes:
        name = wish.strip().lower()
        idx = 0
        # trailing .N = device ordinal (distinct from variant suffixes
        # like cpu.simd / npu.edgetpu, which are non-numeric)
        head, _, tail = name.rpartition(".")
        if tail.isdigit() and head:
            name, idx = head, int(tail)
        platforms = _WISH_PLATFORMS.get(name)
        if platforms is None:
            continue
        for plat in platforms:
            try:
                devs = jax.devices(plat) if plat else jax.devices()
            except RuntimeError:
                continue
            if idx < len(devs):
                return devs[idx]
            if family_fallback is None and devs:
                family_fallback = devs[0]
    if family_fallback is not None:
        # an ordinal overshot but the requested platform FAMILY exists:
        # stay in that family rather than silently inverting an explicit
        # cpu-only (or tpu-only) request onto the process default
        get_logger("jax-xla").warning(
            "accelerator wish list %s unsatisfiable as written; using %s",
            wishes, family_fallback)
        return family_fallback
    return jax.devices()[0]


def probe_device_ids(ids):
    """Per-device liveness probe: a tiny transfer+sync against each of
    the given ordinals, returning the ids that FAILED (the dead set).
    The re-mesh ladder calls this when a :class:`DeviceLostError`
    carries no ordinals — real XLA status strings usually name no chip,
    and guessing wrong would re-place the rebuilt backend on the dead
    one.  A probe that cannot even enumerate devices returns ``None``
    (the caller falls back to its conservative guess); ``()`` means
    every probed member ANSWERED — the loss did not reproduce, and the
    caller must not condemn a healthy chip."""
    import jax

    from ..core.log import get_logger

    try:
        by_id = {int(d.id): d for d in jax.devices()}
    except Exception as e:  # noqa: BLE001 — runtime may be wedged
        get_logger("jax-xla").warning("device probe: enumeration failed (%s)", e)
        return None
    dead = []
    for i in ids:
        d = by_id.get(int(i))
        try:
            if d is None:
                raise RuntimeError("no longer enumerated")
            jax.device_put(np.zeros((1,), np.float32), d).block_until_ready()
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:  # noqa: BLE001 — dead chip detection
            get_logger("jax-xla").warning("device probe: id %d dead (%s)", i, e)
            dead.append(int(i))
    return tuple(dead)


class JaxXla(FilterBackend):
    NAME = "jax-xla"

    #: host-staged batches are really copied to device (device_put), so
    #: the filter's staging lane may reuse its host buffers after emission
    SUPPORTS_STAGING = True

    #: honors the ``mesh=`` prop (sharded serving across a device mesh)
    SUPPORTS_MESH = True

    def __init__(self):
        super().__init__()
        self._fn: Optional[Callable] = None
        self._params: Any = None
        self._in_spec: Optional[StreamSpec] = None
        self._out_spec: Optional[StreamSpec] = None
        self._device = None
        # compile cache, LRU-bounded (core/slots.lru_bucket — the shared
        # compile-bucket discipline): a mesh-shape / flexible-shape sweep
        # mints a fresh (donate, nargs, shapes) key per configuration and
        # each entry pins a compiled XLA program, so unbounded growth is
        # a slow leak on long-lived servers (evicted keys just retrace)
        from collections import OrderedDict

        self._jit_cache: "OrderedDict[Tuple, Any]" = OrderedDict()
        self._cache_lock = threading.Lock()
        self._reload_lock = threading.Lock()  # double-buffered hot reload
        self._posts: List[Callable[[List[Any]], List[Any]]] = []
        # sharded serving (mesh= prop / legacy mesh_* custom props)
        self._mesh = None
        self._mesh_axes: Dict[str, int] = {}
        self._dp = 1
        self._batch_sharding = None
        self._replicated = None
        self.mesh_scatters = 0  # host batches scattered onto the mesh

    # -- framework info -----------------------------------------------------
    def framework_info(self):
        info = super().framework_info()
        info.verify_model_path = False  # may be a registry key
        info.hw_list = ("tpu", "cpu")
        return info

    # -- model loading ------------------------------------------------------
    def _resolve_model(self, model_path: Optional[str]):
        if not model_path:
            raise ValueError("jax-xla requires model= (registry key or file)")
        with _registry_lock:
            entry = _model_registry.get(model_path)
        if entry is not None:
            return entry
        if model_path.endswith(EXPORTED_MODEL_EXTS):
            if not os.path.isfile(model_path):
                raise FileNotFoundError(
                    f"exported-model file not found: {model_path}")
            return self._load_exported(model_path)
        if model_path.endswith(".py") and os.path.isfile(model_path):
            spec = importlib.util.spec_from_file_location(
                f"_nns_jax_model_{abs(hash(model_path))}", model_path
            )
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            if not hasattr(mod, "get_model"):
                raise ValueError(f"{model_path}: must define get_model()")
            got = mod.get_model()
            fn, params = got[0], got[1]
            return (fn, params) + tuple(got[2:4]) + (None,) * (2 - len(got[2:4]))
        arch = self.custom_props.get("arch")
        if arch:
            from .. import models as zoo

            fn, params, in_spec, out_spec = zoo.build(arch, self.custom_props)
            if os.path.isfile(model_path):  # msgpack flax params
                from flax import serialization

                with open(model_path, "rb") as f:
                    params = serialization.from_bytes(params, f.read())
            elif os.path.isdir(model_path):  # orbax checkpoint
                import orbax.checkpoint as ocp

                ckptr = ocp.StandardCheckpointer()
                params = ckptr.restore(os.path.abspath(model_path), params)
            return fn, params, in_spec, out_spec
        raise FileNotFoundError(
            f"jax-xla cannot resolve model {model_path!r} "
            "(not registered; for files pass custom=arch:<zoo-name>)"
        )

    @staticmethod
    def _load_exported(model_path: str):
        """Load a serialized ``jax.export`` artifact (StableHLO): the
        TPU-native model interchange format.  Any jitted JAX function
        ``jax.export.export(jit_fn)(specs).serialize()``-d to a file runs
        here with schemas derived from the embedded avals — the XLA
        answer to the reference's "drop a model file in" flow (its
        subplugins each embed a vendor interpreter;
        ``tensor_filter_tensorflow_lite.cc:158``).  Constants live inside
        the StableHLO module, so there is no separate params pytree.

        Batch handling: artifacts from :func:`export_model` carry a
        symbolic leading batch dim, so per-frame invokes add/strip a
        length-1 axis and micro-batches run natively (one XLA call).
        Fixed-shape artifacts invoke per-frame exactly; a batched call
        against one unrolls inside the trace (correct, but export
        batch-polymorphic for speed — ``call_exported`` has no batching
        rule, so vmap is not an option)."""
        import jax
        from jax import export as jax_export

        with open(model_path, "rb") as f:
            blob = f.read()
        try:
            exported = jax_export.deserialize(blob)
        except Exception as e:  # noqa: BLE001 — loader boundary
            raise ValueError(
                f"{model_path}: not a jax.export artifact (produce one "
                "with nnstreamer_tpu.backends.jax_xla.export_model, or "
                "jax.export.export(jit_fn)(specs).serialize()); raw "
                f"StableHLO text/bytecode is not loadable directly: {e}"
            ) from e

        in_ranks = [len(a.shape) for a in exported.in_avals]
        symbolic = any(
            not isinstance(d, int)
            for a in exported.in_avals for d in a.shape
        )

        normalize = JaxXla._normalize_out

        def fn(params, xs: List[Any]) -> List[Any]:
            if symbolic:
                if all(x.ndim == r - 1 for x, r in zip(xs, in_ranks)):
                    # per-frame invoke of a batch-polymorphic artifact
                    out = normalize(exported.call(*[x[None] for x in xs]))
                    return [o[0] for o in out]
                return normalize(exported.call(*xs))
            if all(x.ndim == r + 1 for x, r in zip(xs, in_ranks)):
                # micro-batch against a fixed-shape artifact: lax.map
                # traces the body ONCE (vmap has no call_exported
                # batching rule; a python unroll would inline the whole
                # module per bucket row)
                from jax import lax

                outs = lax.map(
                    lambda row: tuple(normalize(exported.call(*row))),
                    tuple(xs))
                return list(outs)
            return normalize(exported.call(*xs))

        def spec_of(avals) -> Optional[StreamSpec]:
            dims = [d for a in avals for d in a.shape]
            if any(not isinstance(d, int) for d in dims):
                return None  # symbolic: schema derives from the stream
            return StreamSpec(
                tuple(TensorSpec(tuple(a.shape), np.dtype(a.dtype))
                      for a in avals),
                FORMAT_STATIC,
            )

        return (fn, None, spec_of(exported.in_avals),
                spec_of(exported.out_avals))

    def _mesh_axes_from_props(self, props: Dict[str, Any]) -> Dict[str, int]:
        """The serving mesh config: the first-class ``mesh=`` prop
        (``mesh=tp:4`` / ``mesh=dp:2,tp:2`` — parallel/mesh.py grammar)
        merged over legacy ``mesh_<axis>:<size>`` custom props.  Empty
        dict = unsharded.  A degraded re-shard's survivor spec
        (``mesh_remesh_override``) REPLACES the configured mesh
        entirely — legacy ``mesh_*`` custom props included: a shrunk
        config must never re-merge axes the survivors can no longer
        satisfy."""
        from ..parallel.mesh import parse_mesh_spec

        spec = str(props.get("mesh") or "")
        if props.get("mesh_remesh_override"):
            return dict(parse_mesh_spec(spec)) if spec else {}
        axes = {}
        for k, v in self.custom_props.items():
            if k.startswith("mesh_"):
                axes[k[len("mesh_"):]] = int(v)
        if spec:
            axes.update(parse_mesh_spec(spec))
        return axes

    def open(self, model_path, props):
        super().open(model_path, props)
        import jax

        from ..core.compile_cache import enable as enable_compile_cache

        self._fn, self._params, self._in_spec, self._out_spec = self._resolve_model(
            model_path
        )
        self._device = pick_device(props.get("accelerators") or ["auto"])
        dead = {int(i) for i in (props.get("mesh_exclude_ids") or ())}
        if dead and int(self._device.id) in dead:
            # degraded re-shard bottomed out at unsharded: the default
            # pick may be the very chip that died — place on a survivor
            # (same platform preferred) instead of crash-looping on it
            alive = [d for d in jax.devices()
                     if d.platform == self._device.platform
                     and int(d.id) not in dead] or [
                d for d in jax.devices() if int(d.id) not in dead]
            if not alive:
                raise DeviceLostError(
                    "no surviving device to place on",
                    device_ids=tuple(sorted(dead)))
            self._device = alive[0]
        # cache keyed off the device we will actually compile for (on CPU
        # the auto-enabled cache only emits AOT feature-mismatch noise)
        enable_compile_cache(platform=self._device.platform)
        dtype = self.custom_props.get("dtype")
        if dtype in ("bfloat16", "float16", "float32"):
            import jax.numpy as jnp

            target = jnp.dtype(dtype)
            self._params = jax.tree.map(
                lambda a: a.astype(target)
                if hasattr(a, "dtype") and np.issubdtype(a.dtype, np.floating)
                else a,
                self._params,
            )
        mesh_axes = self._mesh_axes_from_props(props)
        if mesh_axes:
            from ..parallel.mesh import claim_devices, make_mesh
            from ..parallel.sharding import (
                batch_sharding,
                replicated,
                shard_params,
                transformer_rules,
            )

            # degraded re-shard (element recovery ladder): lost device
            # ordinals are excluded from the claimable pool, so a
            # rebuilt backend lands only on survivors
            self._mesh = make_mesh(
                mesh_axes,
                devices=claim_devices(
                    mesh_axes,
                    exclude=props.get("mesh_exclude_ids") or ()))
            self._mesh_axes = {k: self._mesh.shape[k] for k in mesh_axes}
            self._dp = self._mesh.shape.get("dp", 1)
            if self._params is not None:
                # rule misses fall back to replicated — safe for any family
                self._params = shard_params(
                    self._params, self._mesh, transformer_rules(tp_axis="tp")
                )
                # every shard LANDED on its device before this backend is
                # declared open: a hot swap's pointer exchange must never
                # activate a half-staged mesh (the staging thread pays
                # this wait, not the serving thread)
                jax.block_until_ready(self._params)
            self._batch_sharding = batch_sharding(self._mesh, "dp")
            self._replicated = replicated(self._mesh)
        elif self._params is not None:
            self._params = jax.device_put(self._params, self._device)

    def close(self):
        self._jit_cache.clear()
        self._fn = None
        self._params = None

    def reload(self, model_path):
        """Hot reload: build the new params fully, then swap under the lock
        (≙ double-buffered interpreter reload,
        tensor_filter_tensorflow_lite.cc:274)."""
        import jax

        fn, params, in_spec, out_spec = self._resolve_model(model_path)
        if params is not None:
            if self._mesh is not None:
                from ..parallel.sharding import shard_params, transformer_rules

                params = shard_params(
                    params, self._mesh, transformer_rules(tp_axis="tp")
                )
                # fully staged across the mesh BEFORE the pointer swap
                # below — the serving thread never sees a torn half-mesh
                jax.block_until_ready(params)
            else:
                params = jax.device_put(params, self._device)
        with self._reload_lock:
            self._fn, self._params = fn, params
            self._in_spec = in_spec or self._in_spec
            self._out_spec = out_spec or self._out_spec
            self._jit_cache.clear()
            self.model_path = model_path

    # -- model info ---------------------------------------------------------
    def get_model_info(self):
        return self._in_spec, self._out_spec

    @staticmethod
    def _normalize_out(out) -> List[Any]:
        if isinstance(out, (list, tuple)):
            return list(out)
        return [out]

    # -- device-fused postprocess -------------------------------------------
    def append_postprocess(self, fn: Callable[[List[Any]], List[Any]]) -> None:
        """Fold a jit-traceable postprocess (e.g. a decoder's device half)
        into the compiled program: outputs = fn(model outputs).

        The TPU-native replacement for the reference's host-side decoder
        hop (tensordec-*.c operate on mapped CPU memory after invoke): XLA
        fuses the postprocess into the same program, so only its (usually
        tiny) result ever crosses PCIe.  Used by the pipeline's device-
        fusion pass; survives hot reload (applied outside the model fn).

        Postprocess fns that take a ``platform`` keyword get the platform
        of THIS backend's device (not the process default) so they can
        pick device-specific kernels (e.g. Pallas top-1 on tpu only).
        """
        import inspect

        try:
            takes_platform = "platform" in inspect.signature(fn).parameters
        except (TypeError, ValueError):
            takes_platform = False
        if takes_platform:
            wrapped = lambda outs, _fn=fn: _fn(  # noqa: E731
                outs, platform=self._device.platform
            )
        else:
            wrapped = fn
        self._posts.append(wrapped)
        with self._cache_lock:
            self._jit_cache.clear()

    def _apply_posts(self, outs: List[Any]) -> List[Any]:
        for post in self._posts:
            outs = self._normalize_out(post(outs))
        return outs

    def set_input_info(self, in_spec: StreamSpec) -> StreamSpec:
        import jax

        if not in_spec.is_static:
            raise ValueError("jax-xla needs a static input schema to trace")
        dummies = [
            jax.ShapeDtypeStruct(t.shape, t.dtype) for t in in_spec.tensors
        ]
        outs = jax.eval_shape(
            lambda p, xs: self._apply_posts(self._normalize_out(self._fn(p, xs))),
            self._params, dummies,
        )
        spec = StreamSpec(
            tuple(TensorSpec(tuple(o.shape), np.dtype(o.dtype)) for o in outs),
            FORMAT_STATIC,
            in_spec.framerate,
        )
        self._out_spec = spec
        return spec

    # -- compilation --------------------------------------------------------
    def _donation_forced(self) -> Optional[bool]:
        """The legacy custom prop "donate:true|false" pins donation for
        EVERY invoke (the caller takes responsibility for input privacy);
        None = decide per call path."""
        forced = self.custom_props.get("donate", "").lower()
        if forced in ("1", "true"):
            return True
        if forced in ("0", "false"):
            return False
        return None

    def _donation_ok(self) -> bool:
        """Donation for a caller-private batch (invoke_batch_donated):
        on by default except on CPU, where XLA ignores donation and warns
        per compile — custom prop donate: overrides either way."""
        forced = self._donation_forced()
        if forced is not None:
            return forced
        return self._device is not None and self._device.platform != "cpu"

    #: live compiled programs kept per backend (LRU; evicted keys retrace)
    JIT_CACHE_MAX = 64

    def _compiled(self, key: Tuple, donate: bool = False,
                  batched: bool = False):
        from ..core.slots import lru_bucket

        cache_key = (donate, batched) + key

        def build(_key):
            import jax

            model = self._fn
            out_sharding = None
            if self._mesh is not None:
                # mesh mode: outputs carry explicit NamedSharding specs —
                # batch-carrying leaves stay scattered on dp, everything
                # else replicated — so a chained consumer (pool, window,
                # next filter) sees a committed placement, not whatever
                # GSPMD happened to infer
                bucket = key[1][0][0] if batched else None

                def out_sharding(o):  # noqa: F811 — trace-time closure
                    if (batched and getattr(o, "ndim", 0) >= 1
                            and o.shape[0] == bucket):
                        return self._batch_sharding
                    return self._replicated

            def call(params, *xs):
                outs = self._normalize_out(model(params, list(xs)))
                outs = self._apply_posts(outs)
                if out_sharding is not None:
                    outs = [
                        jax.lax.with_sharding_constraint(o, out_sharding(o))
                        for o in outs
                    ]
                return tuple(outs)

            # donation: XLA reuses the input arrays' HBM for outputs
            # (zero per-batch device allocations in steady state).
            # Only ever set for inputs the CALLER declared private —
            # the filter's freshly stacked/staged batches — or when
            # the "donate:true" custom prop pins it; upstream-shared
            # arrays (tee fan-out, pre-batched blocks) never donate.
            donate_nums = tuple(range(1, 1 + key[0])) if donate else ()
            if self._mesh is None:
                return jax.jit(call, donate_argnums=donate_nums)
            # mesh mode: inputs compiled under explicit NamedSharding in
            # specs — params at their rule-derived placements, the data
            # args scattered on dp (batch) or replicated (per-frame)
            in_sh = self._batch_sharding if batched else self._replicated
            param_sh = (
                jax.tree.map(lambda a: a.sharding, self._params)
                if self._params is not None else None
            )
            return jax.jit(
                call, donate_argnums=donate_nums,
                in_shardings=(param_sh,) + (in_sh,) * key[0],
            )

        with self._cache_lock:
            return lru_bucket(
                self._jit_cache, cache_key, build, self.JIT_CACHE_MAX)

    def _device_call(self, fn, *args, inject=True):
        """Every compiled-program execution funnels through the shared
        classification boundary (``core/resilience.device_call``: the
        deterministic ``device.oom`` / ``device.lost`` fault sites plus
        raw-runtime-error typing) so the element-side recovery ladders —
        shrink-retry, slot shed, degraded re-mesh — key on types, never
        on XLA status strings.  Transfer/staging paths pass
        ``inject=False``: they still get the typed classification (a
        transfer-time ``RESOURCE_EXHAUSTED`` engages the same OOM
        ladder) but armed fault counters keep firing at compiled-call
        boundaries only.  A lost device marks this backend degraded
        until it is replaced."""
        try:
            return device_call(fn, *args, inject=inject)
        except DeviceLostError:
            self.degraded = True
            raise

    def trim_caches(self) -> int:
        """Memory-pressure relief: drop the OLDEST half of the live
        compiled programs (they retrace on next use; the hot bucket —
        most recently used — survives, so the steady-state stream pays
        nothing).  Called by the filter's OOM recovery and the
        watermark monitor."""
        with self._cache_lock:
            drop = len(self._jit_cache) // 2
            for _ in range(drop):
                self._jit_cache.popitem(last=False)
        return drop

    def mesh_device_ids(self) -> Tuple[int, ...]:
        """Ordinals of the devices this backend serves on (empty when
        unsharded) — the survivors calculation of the re-mesh ladder."""
        if self._mesh is None:
            return ()
        return tuple(int(d.id) for d in self._mesh.devices.flat)

    def remesh_spec_after_loss(self, lost_ids):
        """``(spec, dead_ids)`` to rebuild with after a device loss
        (``parallel/mesh.remesh_after_loss``: dp gives way first, then
        tp halves, then unsharded).  When the runtime did not name the
        lost ordinals (real XLA status strings usually don't),
        :func:`probe_device_ids` finds them with a per-device liveness
        probe; only if the probe is UNAVAILABLE is the LAST member
        conservatively assumed dead.  A probe that reaches every member
        (the loss did not reproduce) yields ``None`` just like an
        unsharded backend: no re-mesh story — the caller escalates to
        supervision, whose plain retry may cure a transient, rather
        than condemning a healthy chip.  ``dead_ids`` is never empty
        when a pair IS returned — the caller excludes them from every
        future claim, so the rebuilt backend cannot land back on the
        dead chip."""
        if self._mesh is None:
            return None
        from ..parallel.mesh import remesh_after_loss

        dead, _axes, spec = remesh_after_loss(
            self.mesh_device_ids(), self._mesh_axes, lost_ids,
            probe=probe_device_ids)
        if not dead:
            return None
        return spec, dead

    def dead_ordinals_after_loss(self, lost_ids):
        """Exclusion ordinals when there is no re-mesh story: reported
        ids win; an UNSHARDED backend probes its own serving device —
        the only chip the loss could implicate — so the supervision
        restart places on a survivor instead of crash-looping on the
        dead ordinal.  A probe that answers "alive" yields ``()`` (a
        spurious loss condemns nobody); a probe that cannot even
        enumerate condemns the lone chip conservatively."""
        ids = tuple(int(i) for i in (lost_ids or ()))
        if ids or self._mesh is not None or self._device is None:
            return ids
        own = int(self._device.id)
        probed = probe_device_ids((own,))
        if probed is None:
            return (own,)
        return tuple(int(i) for i in probed)

    def _put(self, a, sharding=None) -> Any:
        # classification-only boundary (inject=False): a transfer-time
        # RESOURCE_EXHAUSTED surfaces typed so the element-side OOM
        # ladder (shrink-retry, trim) engages, without the armed fault
        # sites firing mid-staging
        return self._device_call(self._put_raw, a, sharding, inject=False)

    def _put_raw(self, a, sharding=None) -> Any:
        import jax

        if self._mesh is not None:
            # mesh placement: a bare put means "replicate" (per-frame
            # invoke), never a single-device gather.  Resharding an
            # already-placed array is a device-side scatter/collective,
            # not a host bounce; an array already carrying the target
            # sharding passes through untouched.
            target = sharding if sharding is not None else self._replicated
            if isinstance(a, jax.Array) and a.sharding == target:
                return a
            return jax.device_put(
                a if isinstance(a, jax.Array) else np.asarray(a), target)
        if sharding is not None:
            return jax.device_put(a, sharding)
        if isinstance(a, jax.Array):
            # zero-copy pass-through only when the array already lives on
            # THIS filter's device; a chained upstream filter pinned to a
            # different chip hands us its residents — move them (device-
            # to-device, no host bounce) or jit would raise incompatible-
            # devices / silently ignore the pin
            if a.devices() == {self._device}:
                return a
            return jax.device_put(a, self._device)
        return jax.device_put(np.asarray(a), self._device)

    def _bucket(self, n: int) -> int:
        """Compile-bucket size for a batch of ``n``: next power of two,
        rounded up to a dp multiple so the mesh scatter is always even."""
        bucket = _next_pow2(n)
        if bucket % self._dp:
            bucket = ((bucket + self._dp - 1) // self._dp) * self._dp
        return bucket

    @staticmethod
    def _pad_rows(arr, bucket: int, xp=np):
        """THE pad-to-bucket rule (edge-repeat rows on dim 0), shared by
        every staging/dispatch site; ``xp`` picks host np or device
        jnp.  Identity when already at the bucket."""
        n = int(arr.shape[0])
        if bucket == n:
            return arr
        return xp.pad(
            arr, [(0, bucket - n)] + [(0, 0)] * (arr.ndim - 1),
            mode="edge")

    @property
    def _mesh_on_cpu(self) -> bool:
        return (self._mesh is not None
                and next(iter(self._mesh.devices.flat)).platform == "cpu")

    # -- placement identity / mesh observability ----------------------------
    def staging_placement(self):
        """Hashable placement-domain token for the staging-buffer pool:
        buffers staged for one mesh/device must never be pooled into
        another's ring (core.buffer.DeviceBufferPool keys on it)."""
        if self._mesh is not None:
            from ..parallel.mesh import mesh_spec_str

            return ("mesh", mesh_spec_str(self._mesh_axes),
                    tuple(d.id for d in self._mesh.devices.flat))
        if self._device is not None:
            return ("dev", self._device.platform, self._device.id)
        return None

    def mesh_info(self) -> Dict[str, Any]:
        """Serving-mesh facts for health()/the metrics registry
        (``nns.mesh.*``): empty when unsharded."""
        if self._mesh is None:
            return {}
        from ..parallel.mesh import mesh_health_info

        info = mesh_health_info(self._mesh, self._mesh_axes)
        info["mesh_scatters"] = int(self.mesh_scatters)
        return info

    # -- execution ----------------------------------------------------------
    def invoke(self, inputs: List[Any]) -> List[Any]:
        with self._reload_lock:
            # single frame has no batch dim to scatter: replicate on a mesh
            xs = [self._put(a, self._replicated) for a in inputs]
            key = (len(xs),) + tuple((tuple(x.shape), str(x.dtype)) for x in xs)
            out = self._device_call(
                self._compiled(key, donate=bool(self._donation_forced())),
                self._params, *xs)
        return list(out)

    def _stage_sharded(self, arrays: List[Any]) -> List[Any]:
        """Lane-thread hook body for a mesh backend: pad each host batch
        to the dp-divisible compile bucket and scatter it STRAIGHT into
        the batch NamedSharding — each dp shard lands on its owning
        device from here, so the transfer overlaps the previous batch's
        compute exactly like the single-device lane path (the scatter
        never re-runs on the dispatch thread)."""
        return self._device_call(
            self._stage_sharded_raw, arrays, inject=False)

    def _stage_sharded_raw(self, arrays: List[Any]) -> List[Any]:
        import jax

        n = int(arrays[0].shape[0])
        bucket = self._bucket(n)
        staged = []
        for a in arrays:
            arr = np.asarray(a)
            if bucket != n:
                arr = self._pad_rows(arr, bucket)  # pad copies
            elif self._mesh_on_cpu:
                # XLA's CPU client zero-copies aligned host arrays into
                # device_put shards: hand it a private copy or the staged
                # jax.Array aliases the pooled staging buffer the lane is
                # about to overwrite (same bug class as the single-device
                # path below; regression-pinned there)
                arr = np.array(arr)
            staged.append(jax.device_put(arr, self._batch_sharding))
        jax.block_until_ready(staged)
        self.mesh_scatters += 1
        return staged

    def to_device(self, arrays: List[Any]) -> List[Any]:
        """Staging-lane hook: place host-staged batches on this filter's
        device.  Runs ON THE LANE THREAD, so the ``block_until_ready``
        below IS the overlapped transfer — it orders the copy strictly
        before return, which is the lane's buffer-reuse contract (the
        staging buffers go back to the pool the moment this returns).
        On a mesh the lane stages straight to the sharded layout
        (:meth:`_stage_sharded`): dp shards land on their owning devices
        from the lane thread, so the scatter overlaps compute too."""
        if self._batch_sharding is not None:
            # mesh backend: the lane thread scatters straight to the
            # sharded layout (overlap preserved; dispatch never re-puts)
            return self._stage_sharded(arrays)
        return self._device_call(self._to_device_raw, arrays, inject=False)

    def _to_device_raw(self, arrays: List[Any]) -> List[Any]:
        import jax

        if self._device is None or self._device.platform == "cpu":
            # XLA's CPU client ZERO-COPIES suitably-aligned host arrays:
            # device_put returns a jax.Array that ALIASES the staging
            # buffer, and the lane overwrites that buffer with the next
            # batch the moment this returns.  Hand jax a private copy —
            # the memcpy is this platform's "transfer", still paid on
            # the lane thread, and jax owns the copy outright.
            arrays = [np.array(a) for a in arrays]
        out = [jax.device_put(a, self._device) for a in arrays]
        jax.block_until_ready(out)
        return out

    def invoke_batch_donated(self, inputs: List[Any]) -> List[Any]:
        """Caller-private micro-batch: donate the input buffers to the
        executable so XLA reuses their HBM for outputs — zero per-batch
        device allocations in steady state (skipped on CPU, where XLA
        ignores donation and would warn per compile)."""
        donate = self._donation_ok()
        if donate:
            self.stats.record_donation_applied()
        return self._invoke_batch_impl(inputs, donate)

    def invoke_batch(self, inputs: List[Any]) -> List[Any]:
        return self._invoke_batch_impl(
            inputs, bool(self._donation_forced()))

    def _invoke_batch_impl(self, inputs: List[Any], donate: bool) -> List[Any]:
        """One XLA call for the whole micro-batch, bucket-padded so each
        bucket size compiles exactly once (and, on a mesh, stays divisible
        by the dp axis so the scatter is even)."""
        n = int(inputs[0].shape[0])
        bucket = self._bucket(n)
        with self._reload_lock:
            import jax

            xs = []
            scattered = False
            for a in inputs:
                if self._batch_sharding is not None and not isinstance(
                    a, jax.Array
                ):
                    # host batch onto a mesh: pad host-side, then scatter
                    # each dp shard straight to its owning device (no
                    # whole-batch bounce through device 0)
                    arr = self._pad_rows(np.asarray(a), bucket)
                    arr = self._put(arr, self._batch_sharding)
                    scattered = True
                    xs.append(arr)
                    continue
                if self._batch_sharding is not None:
                    # device-resident batch on a mesh (chained filter /
                    # lane-staged): pad on device, commit the batch
                    # sharding (no-op when the lane already placed it)
                    import jax.numpy as jnp

                    arr = self._pad_rows(a, bucket, xp=jnp)
                    xs.append(self._put(arr, self._batch_sharding))
                    continue
                import jax.numpy as jnp

                xs.append(self._pad_rows(self._put(a), bucket, xp=jnp))
            if scattered:
                self.mesh_scatters += 1
            key = (len(xs),) + tuple((tuple(x.shape), str(x.dtype)) for x in xs)
            out = self._device_call(
                self._compiled(key, donate=donate, batched=True),
                self._params, *xs)
        if bucket != n:
            out = [o[:n] for o in out]
        return list(out)


register_backend(JaxXla)
