"""onnx filter backend: run .onnx models on TPU by lowering to XLA.

Reference capability: the reference runs ONNX through vendor subplugins
(``ext/nnstreamer/tensor_filter/tensor_filter_openvino.cc``,
``tensor_filter_snpe.cc``, TensorRT's onnx parser) — each embeds a
closed runtime.  Here the protobuf is parsed in-process
(``importers/onnx_reader.py``, no ``onnx`` package) and the graph lowers
to ONE jit-traced JAX function (``importers/onnx_lower.py``), so a
third-party .onnx file runs on the MXU with the same machinery as
native JAX models.

Subclasses :class:`JaxXla` — shape-bucketed compilation, vmapped
``invoke_batch``, donation, device residency, ``dtype:bfloat16``
casting, ``mesh_*`` sharded serving, double-buffered reload all
inherited (same shape as the tflite importer backend).
"""

from __future__ import annotations

from typing import Optional

from .jax_xla import JaxXla
from .base import register_backend


class OnnxBackend(JaxXla):
    NAME = "onnx"

    @staticmethod
    def available() -> bool:
        return True

    def framework_info(self):
        info = super().framework_info()
        info.verify_model_path = True
        return info

    def _resolve_model(self, model_path: Optional[str]):
        from ..importers.onnx_reader import read_onnx
        from ..importers.onnx_lower import _Lowering
        from ._importer_common import batching_model_fn, spec_from_shapes

        if not model_path:
            raise ValueError("onnx backend requires model=<file.onnx>")
        model = read_onnx(model_path)
        lowering = _Lowering(model)
        params = lowering.params()
        lowering.drop_host_consts()
        in_ranks = tuple(
            len(vi.shape) if vi.shape is not None else -1
            for vi in model.inputs)
        return (
            batching_model_fn(lowering.run, in_ranks),
            params,
            spec_from_shapes([(vi.shape, vi.dtype) for vi in model.inputs]),
            spec_from_shapes([(vi.shape, vi.dtype) for vi in model.outputs]),
        )


register_backend(OnnxBackend)
