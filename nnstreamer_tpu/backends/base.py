"""Filter-backend ABI: the pluggable model-runner contract.

Reference: ``GstTensorFilterFramework`` v1
(``nnstreamer_plugin_api_filter.h:418-494``: ``open/close``, ``invoke``,
``getFrameworkInfo``, ``getModelInfo(GET_IN_OUT_INFO | SET_INPUT_INFO)``,
``eventHandler``) and the C++ author class
``nnstreamer::tensor_filter_subplugin``
(``include/nnstreamer_cppplugin_api_filter.hh:54-180``).

TPU-native deltas:

* ``invoke`` takes/returns a *list of arrays per frame*, and backends may
  additionally implement ``invoke_batch`` (arrays with a leading batch dim)
  — the micro-batching hook the filter element uses to amortize dispatch
  into one XLA call (the reference has no batching; this is the ≥1000 fps
  lever, SURVEY §7 stage 4).
* device placement is real: ``accelerator`` wish lists resolve to a
  concrete ``jax.Device`` in wish order, with a ``.N`` ordinal extension
  (``jax_xla.pick_device``) — two filters can pin two different chips.
* backends may keep outputs on device (jax.Array) — zero-copy between
  chained filters (≙ allocate-in-invoke + GstMemory mapping).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..core import registry
from ..core.types import StreamSpec

# hardware wish-list names (reference accl_hw enum,
# nnstreamer_plugin_api_filter.h:80-102); on TPU most map to "tpu"
KNOWN_ACCELERATORS = (
    "auto",
    "default",
    "cpu",
    "cpu.simd",
    "gpu",
    "npu",
    "tpu",
    "npu.edgetpu",
)


def parse_accelerator(text: Optional[str]) -> Tuple[bool, List[str]]:
    """Parse "true:tpu,cpu" / "false" accelerator strings.

    Reference: regex parsing in ``tensor_filter_common.c:2719-2878``.
    Returns (enabled, ordered wish list).
    """
    if not text:
        return True, ["auto"]
    head, _, rest = text.strip().partition(":")
    enabled = head.strip().lower() not in ("false", "0", "no", "off")
    wishes = [w.strip() for w in rest.split(",") if w.strip()] or ["auto"]
    return enabled, wishes


@dataclass
class FrameworkInfo:
    """≙ getFrameworkInfo."""

    name: str
    allow_in_place: bool = False
    allocate_in_invoke: bool = True  # backends return fresh arrays
    run_without_model: bool = False
    verify_model_path: bool = True
    hw_list: Tuple[str, ...] = ("tpu", "cpu")


@dataclass
class InvokeStats:
    """Per-backend invoke statistics (≙ GstTensorFilterFrameworkStatistics,
    nnstreamer_plugin_api_filter.h:170-175)."""

    total_invoke_num: int = 0
    total_invoke_latency_s: float = 0.0
    # async-feed counters: invokes routed through the donated entry point
    # (caller guaranteed input privacy) and invokes where buffer donation
    # was actually applied to the compiled call (platform-dependent)
    donated_calls: int = 0
    donated_applied: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def record(self, dt: float) -> None:
        with self._lock:
            self.total_invoke_num += 1
            self.total_invoke_latency_s += dt

    # donated-path counters under the same lock as the rest — a shared
    # backend's stats are written from several dispatch threads
    def record_donated(self) -> None:
        with self._lock:
            self.donated_calls += 1

    def record_donation_applied(self) -> None:
        with self._lock:
            self.donated_applied += 1

    @property
    def avg_latency_s(self) -> float:
        with self._lock:
            if not self.total_invoke_num:
                return 0.0
            return self.total_invoke_latency_s / self.total_invoke_num


class FilterBackend:
    """Base class for filter backends (≙ tensor_filter_subplugin).

    Lifecycle: ``open(model, props)`` once → ``invoke``/``invoke_batch`` per
    frame/batch → ``close()``.  ``reload(model)`` hot-swaps weights without
    dropping frames (≙ RELOAD_MODEL event / is-updatable,
    tensor_filter_tensorflow_lite.cc:274 double-buffered reload).
    """

    NAME = "base"

    #: True when :meth:`to_device` performs a real host->device placement
    #: (a COPY off the staging buffer) — the filter's host-ingest staging
    #: lane only engages then.  Host-resident backends keep the default:
    #: their "device arrays" would alias the reusable staging memory.
    SUPPORTS_STAGING = False

    #: True when the backend honors the filter's ``mesh=`` prop (serving
    #: one logical model sharded across a device mesh).  The filter
    #: REFUSES ``mesh=`` on backends that would silently ignore it.
    SUPPORTS_MESH = False

    def __init__(self):
        self.stats = InvokeStats()
        self.model_path: Optional[str] = None
        self.custom_props: Dict[str, str] = {}
        #: set by the device-loss recovery ladder: this backend saw a
        #: device vanish and is (or is being replaced while) serving in
        #: a reduced configuration — health reports it, the discovery
        #: plane deprioritizes the owning server
        self.degraded = False

    # -- framework info -----------------------------------------------------
    def framework_info(self) -> FrameworkInfo:
        return FrameworkInfo(name=self.NAME)

    # -- lifecycle ----------------------------------------------------------
    def open(self, model_path: Optional[str], props: Dict[str, Any]) -> None:
        self.model_path = model_path
        custom = props.get("custom") or ""
        # "key1:val1,key2:val2" custom-prop dialect (reference `custom` prop)
        for part in str(custom).split(","):
            if ":" in part:
                k, _, v = part.partition(":")
                self.custom_props[k.strip()] = v.strip()

    def close(self) -> None:
        pass

    def reload(self, model_path: str) -> None:
        """Hot model reload; default = close+open."""
        props = {"custom": ",".join(f"{k}:{v}" for k, v in self.custom_props.items())}
        self.close()
        self.open(model_path, props)

    # -- model info ---------------------------------------------------------
    def get_model_info(self) -> Tuple[Optional[StreamSpec], Optional[StreamSpec]]:
        """(input schema, output schema); either may be None if the backend
        derives it from the incoming stream (≙ GET_IN_OUT_INFO)."""
        return None, None

    def set_input_info(self, in_spec: StreamSpec) -> StreamSpec:
        """Given the negotiated input schema, return the output schema
        (≙ SET_INPUT_INFO for shape-polymorphic models)."""
        raise NotImplementedError(f"{self.NAME}: cannot derive output schema")

    # -- execution ----------------------------------------------------------
    def invoke(self, inputs: List[Any]) -> List[Any]:
        """Run one frame: list of per-tensor arrays -> list of arrays."""
        raise NotImplementedError

    def invoke_batch(self, inputs: List[Any]) -> List[Any]:
        """Run a micro-batch: each array has a leading batch dim.  Default
        falls back to per-frame invoke."""
        import numpy as np

        batch = inputs[0].shape[0]
        outs: List[List[Any]] = []
        for b in range(batch):
            outs.append(self.invoke([a[b] for a in inputs]))
        return [np.stack([o[i] for o in outs]) for i in range(len(outs[0]))]

    def invoke_batch_donated(self, inputs: List[Any]) -> List[Any]:
        """Run a micro-batch whose input arrays are PRIVATE to the caller
        and may be consumed by the backend (XLA buffer donation: the
        executable reuses the inputs' device memory for outputs — zero
        per-batch device allocations in steady state).  The filter routes
        here only for batches it freshly stacked/staged itself; anything
        that might still be referenced upstream (pre-batched blocks, tee
        fan-out payloads) goes through :meth:`invoke_batch`.  Default:
        plain invoke_batch (donation is an optimization, not a semantic)."""
        return self.invoke_batch(inputs)

    def to_device(self, arrays: List[Any]) -> List[Any]:
        """Place host-staged arrays onto this backend's device — the hook
        the filter's host-ingest staging lane calls from the LANE thread.
        Contract (when :attr:`SUPPORTS_STAGING` is True): return only
        after the contents of ``arrays`` are fully copied/staged, because
        the caller reuses those buffers immediately.  The default is the
        identity (host backends consume host arrays directly) and is why
        the base class keeps ``SUPPORTS_STAGING = False``."""
        return list(arrays)

    def trim_caches(self) -> int:
        """Release memory the backend can recreate on demand (compiled-
        program caches, device scratch) — the memory-pressure relief
        hook the filter's OOM recovery and the watermark monitor call.
        Returns the number of entries released; the default backend
        holds nothing trimmable."""
        return 0

    def remesh_spec_after_loss(self, lost_ids: Sequence[int]):
        """``(spec, dead_ids)`` this backend should be rebuilt with
        after losing ``lost_ids`` (device ordinals; may be empty when
        the runtime did not name them — the backend then identifies the
        dead members itself, e.g. by probing), or ``None`` when the
        backend has no re-mesh story (unsharded / not a device backend)
        — the caller then falls back to supervision.  ``spec`` of
        ``""`` means "rebuild unsharded"; ``dead_ids`` is never empty
        and the caller excludes them from every future device claim."""
        return None

    def dead_ordinals_after_loss(self, lost_ids: Sequence[int]):
        """Ordinals provably dead after a :class:`DeviceLostError`, for
        the caller's exclusion list even when there is NO re-mesh story
        (:meth:`remesh_spec_after_loss` returned ``None``): without the
        exclusion a supervision restart would deterministically re-pick
        the dead chip and crash-loop on it.  The default backend knows
        only what the runtime reported; device backends may probe their
        own serving device.  ``()`` = nothing provably dead (a spurious
        loss — restart freely)."""
        return tuple(int(i) for i in (lost_ids or ()))

    def staging_placement(self):
        """Hashable token naming WHERE :meth:`to_device` places staged
        batches (a device ordinal, a mesh spec, ...).  The staging-buffer
        pool keys its rings on it so buffers sized/warmed for one
        placement domain are never handed to a caller staging for
        another (``core.buffer.DeviceBufferPool``).  ``None`` = the
        backend has no placement identity (host backends)."""
        return None

    @property
    def supports_batch(self) -> bool:
        """True if invoke_batch is native (not the per-frame fallback)."""
        return type(self).invoke_batch is not FilterBackend.invoke_batch

    # -- events -------------------------------------------------------------
    def handle_event(self, name: str, data: Dict[str, Any]) -> None:
        pass

    # -- timed wrappers (stats) --------------------------------------------
    def timed_invoke(self, inputs: List[Any]) -> List[Any]:
        t0 = time.perf_counter()
        out = self.invoke(inputs)
        self.stats.record(time.perf_counter() - t0)
        return out

    def timed_invoke_batch(self, inputs: List[Any]) -> List[Any]:
        t0 = time.perf_counter()
        out = self.invoke_batch(inputs)
        self.stats.record(time.perf_counter() - t0)
        return out

    def timed_invoke_batch_donated(self, inputs: List[Any]) -> List[Any]:
        t0 = time.perf_counter()
        out = self.invoke_batch_donated(inputs)
        self.stats.record_donated()
        self.stats.record(time.perf_counter() - t0)
        return out


def register_backend(cls_or_name, cls=None) -> None:
    """Register a FilterBackend class (≙ nnstreamer_filter_probe,
    tensor_filter_common.c:611)."""
    if cls is None:
        cls, name = cls_or_name, cls_or_name.NAME
    else:
        name = cls_or_name
    registry.register(registry.KIND_FILTER, name, cls)


def find_backend(name: str) -> type:
    """≙ nnstreamer_filter_find (tensor_filter_common.c:697)."""
    return registry.get(registry.KIND_FILTER, name)
