"""custom-easy backend: register a plain Python callable as a model.

Reference: ``tensor_filter_custom_easy.c`` /
``include/tensor_filter_custom_easy.h`` — register an in-process C function
under a name and run it via ``framework=custom-easy model=<name>``.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..core.types import StreamSpec
from .base import FilterBackend, register_backend

_table_lock = threading.Lock()
_table: Dict[str, Tuple[Callable, Optional[StreamSpec], Optional[StreamSpec]]] = {}


def register_custom_easy(
    name: str,
    fn: Callable[[List[Any]], List[Any]],
    in_spec: Optional[StreamSpec] = None,
    out_spec: Optional[StreamSpec] = None,
) -> None:
    """≙ NNS_custom_easy_register."""
    with _table_lock:
        _table[name] = (fn, in_spec, out_spec)


def unregister_custom_easy(name: str) -> bool:
    """≙ NNS_custom_easy_unregister."""
    with _table_lock:
        return _table.pop(name, None) is not None


class CustomEasy(FilterBackend):
    NAME = "custom-easy"

    def __init__(self):
        super().__init__()
        self._fn: Optional[Callable] = None
        self._in: Optional[StreamSpec] = None
        self._out: Optional[StreamSpec] = None

    def open(self, model_path, props):
        super().open(model_path, props)
        with _table_lock:
            entry = _table.get(model_path or "")
        if entry is None:
            raise FileNotFoundError(
                f"custom-easy function {model_path!r} is not registered"
            )
        self._fn, self._in, self._out = entry

    def framework_info(self):
        info = super().framework_info()
        info.verify_model_path = False  # model is a registry key, not a file
        return info

    def get_model_info(self):
        return self._in, self._out

    def set_input_info(self, in_spec: StreamSpec) -> StreamSpec:
        if self._out is not None:
            return self._out
        return in_spec  # untyped callables default to same-schema

    def invoke(self, inputs: List[Any]) -> List[Any]:
        assert self._fn is not None
        return self._fn(list(inputs))


register_backend(CustomEasy)
