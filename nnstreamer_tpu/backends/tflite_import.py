"""tflite filter backend: run .tflite models on TPU by lowering to XLA.

Reference capability: ``ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_lite.cc``
(TFLiteInterpreter/TFLiteCore — open a .tflite, expose tensor info, invoke,
double-buffered reload).  The reference wraps the TFLite CPU interpreter;
here the flatbuffer is parsed in-process (``importers/tflite_reader.py``,
no TensorFlow dependency) and the whole graph is lowered to ONE jit-traced
JAX function (``importers/tflite_lower.py``), so a third-party model file
runs on the MXU with the same machinery as native JAX models.

Subclasses :class:`JaxXla`, inheriting the TPU-first runtime behaviors:
shape-bucketed compilation, native ``invoke_batch`` (one XLA call per
micro-batch), input donation, device-resident outputs, ``dtype:bfloat16``
param casting, ``mesh_*`` sharded serving, and double-buffered hot reload.

Custom props (beyond JaxXla's):

* ``fake_quant:false`` — skip per-tensor requantization simulation for
  quantized models (faster; activations stay float between ops; the
  range clamps — which encode fused ReLU6 — are kept).  Default on
  (reproduces the integer kernels' saturation/rounding to within one
  quantum).
* ``int8:true`` — quantized conv/depthwise/dense execute as TRUE int8
  integer arithmetic (int8×int8→int32, the MXU's double-rate path, with
  the standard zero-point expansion) instead of dequantized float.  The
  perf mode for quantized imports on TPU.

Batch semantics: TFLite graphs bake a leading batch dim (usually 1) into
their shapes.  Per-frame ``invoke`` matches the declared shapes; the
micro-batched path stacks frames on a new leading axis and the model fn
vmaps over it, so the MXU still sees one large batched program.
"""

from __future__ import annotations

from typing import Optional

from .jax_xla import JaxXla
from .base import register_backend


class TFLiteBackend(JaxXla):
    NAME = "tflite"

    @staticmethod
    def available() -> bool:
        return True

    def framework_info(self):
        info = super().framework_info()
        info.verify_model_path = True
        return info

    def _resolve_model(self, model_path: Optional[str]):
        from ..importers.tflite_reader import read_tflite
        from ..importers.tflite_lower import _Lowering
        from ._importer_common import batching_model_fn, spec_from_shapes

        if not model_path:
            raise ValueError("tflite backend requires model=<file.tflite>")
        model = read_tflite(model_path)
        fake_quant = self.custom_props.get(
            "fake_quant", "true").lower() not in ("0", "false", "no")
        int8_compute = self.custom_props.get(
            "int8", "").lower() in ("1", "true", "yes")
        lowering = _Lowering(model, fake_quant=fake_quant,
                             int8_compute=int8_compute)
        params = lowering.params()
        lowering.drop_host_consts()  # run() always gets the params pytree
        in_ranks = tuple(len(model.tensors[i].shape) for i in model.inputs)
        return (
            batching_model_fn(lowering.run, in_ranks),
            params,
            spec_from_shapes([(model.tensors[i].shape,
                               model.tensors[i].dtype)
                              for i in model.inputs]),
            spec_from_shapes([(model.tensors[i].shape,
                               model.tensors[i].dtype)
                              for i in model.outputs]),
        )


# Back-compat alias (the pre-round-4 gated shim's class name)
TFLiteImportBackend = TFLiteBackend

register_backend(TFLiteBackend)
