"""tflite filter backend (gated): run .tflite models via an available
TFLite runtime.

Reference: ``ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_lite.cc``
(1677 LoC — TFLiteInterpreter/TFLiteCore, delegates, double-buffered
reload).  This image ships no TensorFlow/TFLite runtime, so this backend
*gates*: it registers (so ``framework=auto`` extension priority works and
pipelines fail with a clear message) and activates only when
``tflite_runtime`` or ``tensorflow.lite`` is importable — mirroring the
reference's practice of skipping gracefully when a subplugin .so is absent
(SURVEY §4: tests skip if the .so or model is missing).

For TPU execution of converted models, export to a jax callable and use
``framework=jax-xla`` instead.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from .base import FilterBackend


def _find_interpreter():
    try:
        from tflite_runtime.interpreter import Interpreter  # type: ignore
        return Interpreter
    except ImportError:
        pass
    try:
        # attribute access, not `from tensorflow.lite import ...`: tf
        # exposes the lite namespace through a lazy loader that defeats
        # direct from-imports
        import tensorflow as tf  # type: ignore

        return tf.lite.Interpreter
    except (ImportError, AttributeError):
        return None


class TFLiteImportBackend(FilterBackend):
    NAME = "tflite"

    def __init__(self):
        super().__init__()
        self._interp = None

    @staticmethod
    def available() -> bool:
        return _find_interpreter() is not None

    def open(self, model_path: Optional[str], props: Dict[str, Any]) -> None:
        super().open(model_path, props)
        Interpreter = _find_interpreter()
        if Interpreter is None:
            raise RuntimeError(
                "tflite backend: no TFLite runtime in this environment "
                "(install tflite_runtime, or convert the model and use "
                "framework=jax-xla)")
        self._interp = Interpreter(model_path=model_path)
        self._interp.allocate_tensors()

    def close(self) -> None:
        self._interp = None

    def _specs(self, details) -> StreamSpec:
        return StreamSpec(
            tuple(TensorSpec(tuple(int(x) for x in d["shape"]), d["dtype"])
                  for d in details),
            FORMAT_STATIC,
        )

    def get_model_info(self) -> Tuple[Optional[StreamSpec], Optional[StreamSpec]]:
        return (self._specs(self._interp.get_input_details()),
                self._specs(self._interp.get_output_details()))

    def invoke(self, inputs: List[Any]) -> List[Any]:
        ins = self._interp.get_input_details()
        for d, a in zip(ins, inputs):
            self._interp.set_tensor(d["index"], np.asarray(a, d["dtype"]))
        self._interp.invoke()
        return [self._interp.get_tensor(d["index"])
                for d in self._interp.get_output_details()]
