"""custom filter backend: native .so subplugins over the C ABI.

Reference: ``gst/nnstreamer/tensor_filter/tensor_filter_custom.c`` (338 LoC)
— dlopens a user shared object implementing ``tensor_filter_custom.h`` and
runs it as a model.  Here the ABI is ``native/include/nns_tpu_custom_filter.h``
and the loader is ctypes (no pybind11 in this image); buffers cross the
boundary zero-copy as raw pointers into numpy arrays.

``model=<path.so>`` selects the library; the element's ``custom=`` property
string is passed verbatim to ``nns_custom_open``.
"""

from __future__ import annotations

import ctypes
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from .base import FilterBackend

RANK_LIMIT = 16
TENSOR_LIMIT = 16

# nns_tensor_type enum order (native/include/nns_tpu_custom_filter.h,
# matching the reference tensor_typedef.h)
_TYPE_ORDER = (
    np.int32, np.uint32, np.int16, np.uint16, np.int8, np.uint8,
    np.float64, np.float32, np.int64, np.uint64, np.float16,
)
_DTYPE_TO_CODE = {np.dtype(t): i for i, t in enumerate(_TYPE_ORDER)}


class _CSpec(ctypes.Structure):
    _fields_ = [
        ("dtype", ctypes.c_uint32),
        ("rank", ctypes.c_uint32),
        ("dims", ctypes.c_uint64 * RANK_LIMIT),
    ]


class _CMem(ctypes.Structure):
    _fields_ = [
        ("data", ctypes.c_void_p),
        ("nbytes", ctypes.c_uint64),
    ]


def _spec_from_c(c: _CSpec) -> TensorSpec:
    shape = tuple(int(c.dims[i]) for i in range(c.rank))
    return TensorSpec(shape, np.dtype(_TYPE_ORDER[c.dtype]))


def _spec_to_c(spec: TensorSpec) -> _CSpec:
    c = _CSpec()
    c.dtype = _DTYPE_TO_CODE[np.dtype(spec.dtype)]
    c.rank = len(spec.shape)
    for i, d in enumerate(spec.shape):
        c.dims[i] = int(d)
    return c


class CustomNative(FilterBackend):
    NAME = "custom"

    def __init__(self):
        super().__init__()
        self._lib: Optional[ctypes.CDLL] = None
        self._handle: Optional[ctypes.c_void_p] = None
        self._in_spec: Optional[StreamSpec] = None
        self._out_spec: Optional[StreamSpec] = None

    def framework_info(self):
        info = super().framework_info()
        info.hw_list = ("cpu",)
        info.allocate_in_invoke = False  # framework pre-allocates outputs
        return info

    # -- lifecycle ----------------------------------------------------------
    def open(self, model_path: Optional[str], props: Dict[str, Any]) -> None:
        super().open(model_path, props)
        if not model_path or not os.path.isfile(model_path):
            raise FileNotFoundError(
                f"custom backend needs model=<subplugin.so>, got {model_path!r}")
        lib = ctypes.CDLL(os.path.abspath(model_path))
        lib.nns_custom_open.restype = ctypes.c_void_p
        lib.nns_custom_open.argtypes = [ctypes.c_char_p]
        lib.nns_custom_invoke.restype = ctypes.c_int
        lib.nns_custom_invoke.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(_CMem), ctypes.c_uint32,
            ctypes.POINTER(_CMem), ctypes.c_uint32]
        lib.nns_custom_close.restype = None
        lib.nns_custom_close.argtypes = [ctypes.c_void_p]
        lib.nns_custom_get_model_info.restype = ctypes.c_int
        lib.nns_custom_get_model_info.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(_CSpec), ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(_CSpec), ctypes.POINTER(ctypes.c_uint32)]
        custom = str(props.get("custom") or "")
        handle = lib.nns_custom_open(custom.encode())
        if not handle:
            raise RuntimeError(f"{model_path}: nns_custom_open failed")
        self._lib, self._handle = lib, ctypes.c_void_p(handle)
        self._query_model_info()

    def _query_model_info(self) -> None:
        ins = (_CSpec * TENSOR_LIMIT)()
        outs = (_CSpec * TENSOR_LIMIT)()
        n_in = ctypes.c_uint32(0)
        n_out = ctypes.c_uint32(0)
        rc = self._lib.nns_custom_get_model_info(
            self._handle, ins, ctypes.byref(n_in), outs, ctypes.byref(n_out))
        if rc == 0:
            self._in_spec = StreamSpec(
                tuple(_spec_from_c(ins[i]) for i in range(n_in.value)),
                FORMAT_STATIC)
            self._out_spec = StreamSpec(
                tuple(_spec_from_c(outs[i]) for i in range(n_out.value)),
                FORMAT_STATIC)

    def close(self) -> None:
        if self._lib is not None and self._handle is not None:
            self._lib.nns_custom_close(self._handle)
        self._lib = self._handle = None

    # -- model info ----------------------------------------------------------
    def get_model_info(self) -> Tuple[Optional[StreamSpec], Optional[StreamSpec]]:
        return self._in_spec, self._out_spec

    def set_input_info(self, in_spec: StreamSpec) -> StreamSpec:
        if not hasattr(self._lib, "nns_custom_set_input_info"):
            raise NotImplementedError(
                "custom subplugin lacks nns_custom_set_input_info")
        fn = self._lib.nns_custom_set_input_info
        fn.restype = ctypes.c_int
        fn.argtypes = [ctypes.c_void_p, ctypes.POINTER(_CSpec),
                       ctypes.c_uint32, ctypes.POINTER(_CSpec),
                       ctypes.POINTER(ctypes.c_uint32)]
        ins = (_CSpec * TENSOR_LIMIT)()
        for i, t in enumerate(in_spec.tensors):
            ins[i] = _spec_to_c(t)
        outs = (_CSpec * TENSOR_LIMIT)()
        n_out = ctypes.c_uint32(0)
        rc = fn(self._handle, ins, len(in_spec.tensors), outs,
                ctypes.byref(n_out))
        if rc != 0:
            raise RuntimeError(f"nns_custom_set_input_info failed (rc={rc})")
        self._out_spec = StreamSpec(
            tuple(_spec_from_c(outs[i]) for i in range(n_out.value)),
            FORMAT_STATIC, in_spec.framerate)
        self._in_spec = in_spec
        return self._out_spec

    # -- execution -----------------------------------------------------------
    def invoke(self, inputs: List[Any]) -> List[Any]:
        arrays = [np.ascontiguousarray(np.asarray(a)) for a in inputs]
        if self._out_spec is None:
            # negotiation never saw a static schema (e.g. appsrc): derive it
            # from the first frame, like the reference's setInputDimension
            self.set_input_info(StreamSpec(
                tuple(TensorSpec(a.shape, a.dtype) for a in arrays),
                FORMAT_STATIC))
        c_in = (_CMem * len(arrays))()
        for i, a in enumerate(arrays):
            c_in[i].data = a.ctypes.data_as(ctypes.c_void_p)
            c_in[i].nbytes = a.nbytes
        outs = [np.empty(t.shape, t.dtype) for t in self._out_spec.tensors]
        c_out = (_CMem * len(outs))()
        for i, a in enumerate(outs):
            c_out[i].data = a.ctypes.data_as(ctypes.c_void_p)
            c_out[i].nbytes = a.nbytes
        rc = self._lib.nns_custom_invoke(
            self._handle, c_in, len(arrays), c_out, len(outs))
        if rc != 0:
            raise RuntimeError(f"nns_custom_invoke failed (rc={rc})")
        return outs
