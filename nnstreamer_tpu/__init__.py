"""nnstreamer_tpu — a TPU-native streaming-AI framework.

A ground-up re-design of the NNStreamer capability set (typed tensor streams,
negotiated schemas, composable pipeline elements, pluggable model backends,
among-device offload, in-pipeline training) around JAX/XLA/pjit/Pallas instead
of GStreamer.  See SURVEY.md for the blueprint and the reference mapping.
"""

__version__ = "0.1.0"

from .core import (  # noqa: F401
    StreamSpec,
    TensorSpec,
    TensorFrame,
)


def __getattr__(name):  # lazy: avoid importing jax at package import
    if name == "SingleShot":
        from .elements.filter import SingleShot

        return SingleShot
    if name == "parse_pipeline":
        from .pipeline import parse_pipeline

        return parse_pipeline
    raise AttributeError(name)
