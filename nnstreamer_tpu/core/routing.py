"""Fleet routing primitives: consistent-hash affinity + policy ranking.

The query client's multi-server fan-out started as blind rotation
(``tensor_query_client.c`` picks its one server statically; the TPU
build's ``hosts=`` list round-robins).  That collapses under skewed
load: one slow or drowning server keeps receiving its full share while
idle capacity elsewhere goes unused — throughput left on the table by
the roofline framing.  This module holds the two pure, deterministic
pieces of the fix, separated from the element so they unit-test on
plain data:

* **Rendezvous (HRW) consistent hashing** for session affinity
  (``affinity-key``): every (key, endpoint) pair gets an independent
  deterministic weight; the key's owner is the endpoint with the
  highest weight.  Membership changes remap the provable minimum —
  a joining server steals only the keys it now wins (≈ K/(N+1)), a
  leaving server's keys (≈ K/N) redistribute evenly, and every other
  key keeps its owner.  No ring state, no virtual-node tuning, and the
  ownership map is a pure function of the endpoint set.

* **Routing-policy ranking** (``rotate`` | ``least-inflight`` |
  ``ewma``): given the per-remote availability tiers and live load
  signals, produce the order in which the client should try remotes.
  The tier partition encodes the selection-side guard the breakers
  need: a remote whose breaker is OPEN (or that announced it is
  degraded or draining) is NEVER ranked ahead of a closed-breaker,
  healthy alternative — load scores only reorder remotes *within* a
  tier.  A DEGRADED remote (lost a device, serving on a shrunk mesh)
  still serves correctly, so it ranks above draining/down — it just
  never wins while a whole server exists.

Everything here is allocation-light and clock-free; the element owns
locks, clocks, and sockets.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

#: the routing policies the query client accepts (element prop `routing`)
ROUTING_POLICIES = ("rotate", "least-inflight", "ewma")

#: availability tiers, best first — ranking never promotes across tiers
TIER_OK = 0        # serving, breaker closed, no cooldown
TIER_DEGRADED = 1  # announced degraded (lost a device; serving reduced)
TIER_DRAINING = 2  # announced draining (discovery hint / GOAWAY cooldown)
TIER_DOWN = 3      # cooldown active or breaker open


def rendezvous_owner(key: str, targets: Sequence[Tuple[str, int]]) -> int:
    """Index of ``key``'s owner among ``targets`` (highest-random-weight
    hashing, deterministic across processes and runs).

    blake2b is used for speed and stable cross-platform output; the
    weight is the first 8 bytes of ``H(host:port|key)`` as a big-endian
    integer, ties broken by endpoint order (deterministic — ties are a
    2^-64 event anyway)."""
    if not targets:
        raise ValueError("rendezvous_owner needs at least one target")
    kb = key.encode()
    best_i = 0
    best_w = -1
    for i, (host, port) in enumerate(targets):
        h = hashlib.blake2b(digest_size=8)
        h.update(f"{host}:{port}|".encode())
        h.update(kb)
        w = int.from_bytes(h.digest(), "big")
        if w > best_w:
            best_w, best_i = w, i
    return best_i


def ownership_map(keys: Sequence[str],
                  targets: Sequence[Tuple[str, int]]) -> Dict[str, int]:
    """{key: owner index} for a whole key set (tests + capacity planning)."""
    return {k: rendezvous_owner(k, targets) for k in keys}


def ewma_scores(
    idxs: Sequence[int],
    addrs: Sequence[str],
    spans: Dict[str, Dict[str, Optional[float]]],
) -> Dict[int, float]:
    """Per-index latency score for the ``ewma`` policy.

    ``spans`` is the client's per-remote EWMA aggregation keyed by
    ``"host:port"`` (element health ``remotes``); ``addrs`` the current
    pool's address strings.  Only rows for the CURRENT addresses are
    consulted — rows for endpoints evicted by ``_rediscover`` are
    unreachable by construction (lookup is by address, never by
    iterating the dict), which pins the frozen-EWMA bugfix at the API
    level.  Endpoints with no row yet (a server that just joined) score
    the MEAN of the known rows: a fresh server is neither flooded
    (score 0 would win every race before one request completes) nor
    starved (score inf would never let it build a signal)."""
    known: Dict[int, float] = {}
    for i in idxs:
        agg = spans.get(addrs[i])
        if agg:
            v = agg.get("e2e_ms")
            if v is not None and agg.get("requests", 0) > 0:
                known[i] = float(v)
    neutral = (sum(known.values()) / len(known)) if known else 0.0
    return {i: known.get(i, neutral) for i in idxs}


def rank_tier(
    policy: str,
    idxs: List[int],
    first: int,
    n: int,
    inflight: Optional[Dict[int, int]] = None,
    scores: Optional[Dict[int, float]] = None,
) -> List[int]:
    """Order one availability tier's indices by routing policy.

    ``first``/``n`` define the rotation base every policy shares (the
    tie-break, and the whole ordering for ``rotate``): index distances
    from ``first`` modulo ``n``.  ``least-inflight`` sorts by the live
    per-remote in-flight count; ``ewma`` sorts by latency score with
    in-flight count as the first tie-break (two equally-fast servers
    split load instead of dog-piling the rotation winner)."""
    if policy == "rotate" or len(idxs) <= 1:
        return sorted(idxs, key=lambda i: (i - first) % n)
    infl = inflight or {}
    if policy == "least-inflight":
        # rotation distance as the last key: equal in-flight counts
        # keep rotating instead of always dog-piling the lowest index
        return sorted(
            idxs, key=lambda i: (infl.get(i, 0), (i - first) % n))
    if policy == "ewma":
        sc = scores or {}
        return sorted(
            idxs,
            key=lambda i: (sc.get(i, 0.0), infl.get(i, 0),
                           (i - first) % n))
    raise ValueError(
        f"unknown routing policy {policy!r} (want one of {ROUTING_POLICIES})")


def order_remotes(
    policy: str,
    tiers: Dict[int, int],
    first: int,
    n: int,
    inflight: Optional[Dict[int, int]] = None,
    scores: Optional[Dict[int, float]] = None,
    affinity_owner: Optional[int] = None,
) -> List[int]:
    """The full routing decision: every index of the pool, best first.

    ``tiers`` maps index -> TIER_* (availability partition computed by
    the element from cooldowns, breaker peeks, and discovery hints).
    Tier boundaries are absolute: no load score ever ranks a
    :data:`TIER_DOWN` (breaker-open / cooled-down) remote ahead of a
    :data:`TIER_OK` one while any exists — the selection-side guard.
    ``affinity_owner`` (consistent-hash stickiness) is promoted to the
    very front of ITS tier only: an affinity owner that is draining or
    breaker-open still waits behind every healthy alternative, so
    stickiness can never pin a session to a dead host."""
    out: List[int] = []
    for tier in (TIER_OK, TIER_DEGRADED, TIER_DRAINING, TIER_DOWN):
        idxs = [i for i, t in tiers.items() if t == tier]
        if not idxs:
            continue
        ranked = rank_tier(policy, idxs, first, n, inflight, scores)
        if affinity_owner is not None and affinity_owner in ranked:
            ranked.remove(affinity_owner)
            ranked.insert(0, affinity_owner)
        out.extend(ranked)
    return out
