"""Pipeline-wide tracing: the GstShark-analog observability layer.

The reference delegates pipeline profiling to GStreamer ecosystem tracers —
GstShark's proctime / interlatency / framerate / queuelevel / bitrate
hooks (SURVEY §5.1, ``tools/tracing/README.md`` in the reference) — plus
per-filter latency/throughput props.  Here the same five measurements are
a built-in: the pipeline calls ``frame_in``/``frame_out`` around every
element's processing when a tracer is attached (one ``is not None`` test
per frame when disabled).

Measurements per element:
  * **proctime** — wall time inside the element's handler (µs; avg/p50/p99
    over a bounded ring).
  * **framerate** — logical frames/sec out of the element (micro-batches
    count as their batch size).
  * **interlatency** — source-to-here latency: elements see the wall-clock
    stamp the tracer put on the frame when it left its source.
  * **queuelevel** — mailbox depth sampled at dequeue (backpressure view).
  * **bitrate** — payload bytes/sec through the element.

``report()`` returns plain dicts; ``summary_lines()`` renders the
gst-shark-style table.  For device-level detail this composes with the
XLA profiler (``core/profiler.py`` — tensor_filter ``trace`` prop).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from .telemetry import TRACE_ID_META, Log2Histogram, new_trace_id

META_SRC_TS = "_nns_trace_src_ts"  # wall stamp set when a frame leaves a source


class _ElementStats:
    __slots__ = (
        "frames", "calls", "proc_ring", "t_first", "t_last",
        "inter_sum", "inter_max", "inter_n", "bytes", "q_sum", "q_max",
        "q_n", "q_cap", "sched_ring", "t_prev_in", "lat_hist",
    )

    def __init__(self) -> None:
        self.frames = 0
        self.calls = 0
        self.proc_ring: deque = deque(maxlen=1024)  # seconds per call
        # full-history fixed-memory handle-latency distribution (the
        # proc ring keeps only the last 1024 calls; percentile EVIDENCE
        # needs every observation) — lock-free: frame_out is
        # single-writer per element by the scheduler's threading model
        self.lat_hist = Log2Histogram()
        self.t_first: Optional[float] = None
        self.t_last: Optional[float] = None
        self.inter_sum = 0.0
        self.inter_max = 0.0
        self.inter_n = 0
        self.bytes = 0
        self.q_sum = 0
        self.q_max = 0
        self.q_n = 0
        self.q_cap = 0
        # scheduletime: gap between consecutive call starts (GstShark's
        # scheduling-jitter view)
        self.sched_ring: deque = deque(maxlen=1024)
        self.t_prev_in: Optional[float] = None


class PipelineTracer:
    """Attach via ``Pipeline(..., tracer=PipelineTracer())`` or
    ``pipeline.enable_tracing()``; read ``report()`` any time (thread-safe,
    including while the pipeline runs).

    A :class:`~.telemetry.FlightRecorder` may ride along (``recorder``
    attr, set by ``Pipeline.enable_flight_recorder``): the scheduler's
    single ``tracer is not None`` branch then also feeds the incident
    ring — the disabled path still costs exactly one branch per frame."""

    def __init__(self, detail: bool = False, recorder=None) -> None:
        self._stats: Dict[str, _ElementStats] = {}
        # mailbox queue-wait distributions (enqueue -> dequeue), one per
        # consuming element; single-writer: each mailbox has exactly one
        # consumer thread
        self._qwait: Dict[str, Log2Histogram] = {}
        self._lock = threading.Lock()
        self.t_started = time.perf_counter()
        # cpuusage: process CPU time vs wall time over the traced window
        self._cpu_started = time.process_time()
        # detail mode additionally keeps per-call spans (bounded ring) so
        # export_chrome_trace renders a real timeline, not just aggregates
        self._detail = detail
        self._spans: deque = deque(maxlen=200_000)
        # optional flight recorder (core/telemetry.py)
        self.recorder = recorder

    # -- hot-path hooks (called from element worker threads) ---------------
    def stamp_source(self, frame) -> None:
        """Stamp a frame leaving a source element (interlatency origin);
        with a flight recorder attached, also mint the frame's trace id
        (it propagates through meta copies — and across the query wire,
        see core/telemetry.py)."""
        frame.meta.setdefault(META_SRC_TS, time.perf_counter())
        if self.recorder is not None:
            frame.meta.setdefault(TRACE_ID_META, new_trace_id())

    def frame_begin(self, name: str, frame) -> None:
        """Mark a frame ENTERING an element's handler.  Only meaningful
        with a flight recorder attached (a frame stuck inside a hung
        element is identified by its open span); otherwise a no-op."""
        if self.recorder is not None:
            self.recorder.begin(name, frame)

    def queue_wait(self, name: str, wait_s: float) -> None:
        """One frame's mailbox wait, recorded by the consuming streaming
        thread.  The origin stamp is the producer's handoff ATTEMPT
        (``_push``/``_put_many``), so time spent blocked on a full
        mailbox counts too — backpressure IS queue pressure; p99 here
        can therefore exceed capacity x service time.  On a fan-out pad
        the shared stamp yields ONE observation per frame, attributed to
        whichever consumer dequeues first."""
        h = self._qwait.get(name)
        if h is None:
            with self._lock:
                h = self._qwait.setdefault(name, Log2Histogram())
        h.record(wait_s)

    def queue_level(self, name: str, depth: int, cap: int) -> None:
        st = self._get(name)
        st.q_sum += depth
        st.q_n += 1
        st.q_cap = cap
        if depth > st.q_max:
            st.q_max = depth

    def frame_out(
        self, name: str, t_in: float, t_out: float,
        nframes: int, nbytes: int, src_ts: Optional[float],
        frame=None,
    ) -> None:
        if self._detail:
            self._spans.append((name, t_in, t_out, nframes))
        if self.recorder is not None:
            self.recorder.end(name, frame, t_in, t_out, nframes)
        st = self._get(name)
        st.calls += 1
        st.frames += nframes
        st.proc_ring.append(t_out - t_in)
        st.lat_hist.record(t_out - t_in)
        if st.t_prev_in is not None:
            st.sched_ring.append(t_in - st.t_prev_in)
        st.t_prev_in = t_in
        if st.t_first is None:
            st.t_first = t_out
        st.t_last = t_out
        st.bytes += nbytes
        if src_ts is not None:
            lat = t_out - src_ts
            st.inter_sum += lat
            st.inter_n += 1
            if lat > st.inter_max:
                st.inter_max = lat

    def _get(self, name: str) -> _ElementStats:
        st = self._stats.get(name)
        if st is None:
            with self._lock:
                st = self._stats.setdefault(name, _ElementStats())
        return st

    # -- reporting ----------------------------------------------------------
    def latency_histograms(self):
        """``[(element, metric_name, Log2Histogram)]`` for the always-on
        log2 instruments: per-element handle latency
        (``nns.element.handle_seconds``) and mailbox queue-wait
        (``nns.element.queue_wait_seconds``).  The telemetry collector
        exports these (buckets + derived p50/p95/p99 gauges) at scrape
        time."""
        with self._lock:
            stats = list(self._stats.items())
            qwait = list(self._qwait.items())
        out = [
            (name, "nns.element.handle_seconds", st.lat_hist)
            for name, st in stats
        ]
        out.extend(
            (name, "nns.element.queue_wait_seconds", h)
            for name, h in qwait
        )
        return out

    def cpu_usage(self) -> float:
        """Process CPU seconds per wall second since tracing began
        (GstShark cpuusage analog; >1.0 = more than one busy core)."""
        wall = time.perf_counter() - self.t_started
        if wall <= 0:
            return 0.0
        return (time.process_time() - self._cpu_started) / wall

    @staticmethod
    def _snap(dq: deque) -> list:
        """Copy a ring that worker threads append to without locks: a
        full ring's append also evicts, which makes a concurrent
        list(deque) raise — retry, then settle for empty."""
        for _ in range(4):
            try:
                return list(dq)
            except RuntimeError:
                continue
        return []

    def report(self) -> Dict[str, Dict[str, Any]]:
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:  # _get() inserts concurrently from worker threads
            items = list(self._stats.items())
        for name, st in items:
            ring = self._snap(st.proc_ring)
            span = (
                (st.t_last - st.t_first)
                if st.t_first is not None and st.t_last != st.t_first
                else 0.0
            )
            proc = np.asarray(ring) if ring else np.zeros(1)
            sched = self._snap(st.sched_ring)
            out[name] = {
                "frames": st.frames,
                "calls": st.calls,
                "proctime_us_avg": float(proc.mean()) * 1e6,
                "proctime_us_p50": float(np.percentile(proc, 50)) * 1e6,
                "proctime_us_p99": float(np.percentile(proc, 99)) * 1e6,
                "scheduletime_us_avg": (
                    float(np.mean(sched)) * 1e6 if sched else None
                ),
                "framerate_fps": (st.frames / span) if span else 0.0,
                "interlatency_ms_avg": (
                    st.inter_sum / st.inter_n * 1e3 if st.inter_n else None
                ),
                "interlatency_ms_max": (
                    st.inter_max * 1e3 if st.inter_n else None
                ),
                "bitrate_mbps": (st.bytes * 8 / 1e6 / span) if span else 0.0,
                "queuelevel_avg": (st.q_sum / st.q_n) if st.q_n else 0.0,
                "queuelevel_max": st.q_max,
                "queue_capacity": st.q_cap,
            }
        return out

    def summary_lines(self) -> List[str]:
        rows = self.report()
        lines = [
            f"{'element':<20} {'frames':>8} {'fps':>9} {'proc µs':>9} "
            f"{'p99 µs':>9} {'inter ms':>9} {'Mb/s':>8} {'queue':>7}"
        ]
        for name, r in rows.items():
            inter = (
                f"{r['interlatency_ms_avg']:.2f}"
                if r["interlatency_ms_avg"] is not None else "-"
            )
            lines.append(
                f"{name:<20} {r['frames']:>8} {r['framerate_fps']:>9.1f} "
                f"{r['proctime_us_avg']:>9.1f} {r['proctime_us_p99']:>9.1f} "
                f"{inter:>9} {r['bitrate_mbps']:>8.2f} "
                f"{r['queuelevel_avg']:>4.1f}/{r['queue_capacity']}"
            )
        lines.append(f"cpu usage: {self.cpu_usage():.2f} cores")
        return lines


    def export_chrome_trace(self, path: str) -> None:
        """Write a Chrome-trace JSON (``chrome://tracing`` / Perfetto) so
        pipeline timing sits next to ``jax.profiler`` device traces — the
        GstShark→tracing-UI hop the reference gets from HawkTracer
        (SURVEY §5.1).  With ``detail=True`` every element call becomes a
        real timeline span (one lane per element); otherwise one summary
        span per element plus fps counters."""
        import json

        t0 = self.t_started
        with self._lock:
            names = list(self._stats)
        lanes = {name: i for i, name in enumerate(names)}
        events = [
            {
                "name": "process_name", "ph": "M", "pid": 0,
                "args": {"name": "nnstreamer_tpu pipeline"},
            }
        ] + [
            {
                "name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
                "args": {"name": name},
            }
            for name, tid in lanes.items()
        ]
        if self._detail and self._spans:
            for name, t_in, t_out, nframes in list(self._spans):
                events.append({
                    "name": name, "ph": "X", "pid": 0,
                    "tid": lanes.get(name, 0),
                    "ts": (t_in - t0) * 1e6,
                    "dur": max(0.1, (t_out - t_in) * 1e6),
                    "args": {"frames": nframes},
                })
        for name, r in self.report().items():
            if not (self._detail and self._spans):
                events.append({
                    "name": name, "ph": "X", "pid": 0,
                    "tid": lanes.get(name, 0), "ts": 0,
                    "dur": max(1, int(r["proctime_us_avg"] * r["calls"])),
                    "args": {k: v for k, v in r.items() if v is not None},
                })
            events.append({
                "name": f"{name}/fps", "ph": "C", "pid": 0,
                "ts": 0, "args": {"fps": round(r["framerate_fps"], 1)},
            })
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)


def frame_nbytes(item) -> int:
    """Payload size of a frame (host or device tensors)."""
    try:
        return sum(int(getattr(t, "nbytes", 0)) for t in item.tensors)
    except Exception:
        return 0
