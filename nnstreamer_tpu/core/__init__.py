"""Core runtime (L1): tensor type system, schemas, buffers, events, sync,
subplugin registry, config, logging."""

from .types import (  # noqa: F401
    ANY,
    FORMAT_FLEXIBLE,
    FORMAT_SPARSE,
    FORMAT_STATIC,
    FORMATS,
    RANK_LIMIT,
    TENSOR_COUNT_LIMIT,
    StreamSpec,
    TensorSpec,
    all_type_names,
    dims_to_string,
    dtype_from_name,
    dtype_to_name,
    pack_flex_header,
    parse_dims_string,
    sparse_decode,
    sparse_encode,
    unpack_flex_header,
)
from .buffer import (  # noqa: F401
    EOS,
    CapsEvent,
    CustomEvent,
    Event,
    Flush,
    SegmentEvent,
    TensorFrame,
)
from .sync import Collator, SyncPolicy  # noqa: F401
from . import config, registry  # noqa: F401
from .log import get_logger  # noqa: F401
