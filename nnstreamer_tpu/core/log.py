"""Logging shims.

Reference: ``gst/nnstreamer/nnstreamer_log.{h,c}`` — ``ml_logi/w/e/d/f``
macros routed to the platform logger, with backtraces attached on fatal
paths.  Here: one stdlib logger per element/category, fatal helper raising
with traceback, env-tunable level (NNS_TPU_LOG=debug).
"""

from __future__ import annotations

import logging
import os
import traceback

_root = logging.getLogger("nnstreamer_tpu")
if not _root.handlers:
    _h = logging.StreamHandler()
    _h.setFormatter(
        logging.Formatter("%(asctime)s %(levelname).1s %(name)s: %(message)s", "%H:%M:%S")
    )
    _root.addHandler(_h)
    _root.setLevel(
        getattr(logging, os.environ.get("NNS_TPU_LOG", "INFO").upper(), logging.INFO)
    )


def get_logger(category: str) -> logging.Logger:
    return _root.getChild(category)


def fatal(logger: logging.Logger, msg: str, *args) -> "NoReturn":  # noqa: F821
    """Log with backtrace and raise (reference: ml_logf + _backtrace_to_string)."""
    text = msg % args if args else msg
    logger.error("%s\n%s", text, "".join(traceback.format_stack(limit=12)))
    raise RuntimeError(text)
