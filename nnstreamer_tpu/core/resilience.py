"""Resilience primitives: retry policies, circuit breakers, error
classification, and deterministic fault injection.

Reference analog: NNStreamer's always-on deployments survive flaky
cameras, dropped offload links, and bad frames (the query elements'
timeout/retry knobs, nnstreamer-edge reconnect logic).  The reproduction
was strictly fail-stop before this module; these primitives are shared
by the pipeline supervisor (``pipeline/pipeline.py``), the query client
(``elements/query.py``), and the raw-TCP transports
(``distributed/tcp_query.py``).

Design rules:

* **Injectable time.** Every time-dependent class takes ``clock`` (and
  ``sleep`` where it blocks) so tests run on a fake clock — tier-1 must
  never wait out a real backoff.
* **Deterministic jitter.** Jitter comes from a seedable
  ``random.Random``, never the process-global RNG.
* **Zero hot-path cost when idle.** ``FaultInjector.check`` is a plain
  dict lookup guarded by one bool; un-armed sites cost ~nothing.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple, Type

from .log import get_logger

log = get_logger("resilience")


# ---------------------------------------------------------------------------
# Transient-vs-fatal error classification
# ---------------------------------------------------------------------------
# Transient: the operation may succeed if simply re-tried (network blips,
# timeouts, resource exhaustion).  Fatal: retrying cannot help (bad
# arguments, schema mismatches, programming errors) — retry loops must
# fail fast instead of burning their deadline budget on them.
_TRANSIENT_TYPES: Tuple[Type[BaseException], ...] = (
    ConnectionError,
    TimeoutError,
    InterruptedError,
    BrokenPipeError,
    OSError,  # includes socket.timeout/socket.error
)
_FATAL_TYPES: Tuple[Type[BaseException], ...] = (
    TypeError,
    ValueError,
    KeyError,
    IndexError,
    AttributeError,
    NotImplementedError,
)


class TransientError(RuntimeError):
    """Raise (or wrap with) this to force transient classification."""


class FatalError(RuntimeError):
    """Raise (or wrap with) this to force fatal classification."""


class DeviceOomError(TransientError):
    """The accelerator ran out of memory (XLA ``RESOURCE_EXHAUSTED``).

    Transient BY DESIGN: the op may succeed on a smaller batch bucket or
    after cache/pool trimming — the filter's shrink-retry and the slot
    engine's slot-shed ladder both cure it without a restart.  Carries
    no device identity: the chip is still there, just full."""


class DeviceLostError(TransientError):
    """A device vanished from under the program (chip reset, mesh member
    death, runtime lost its connection to the accelerator).

    Transient at the SERVING level — a re-mesh onto the surviving
    devices (or an element restart that re-picks devices) cures it —
    but never curable by a plain same-device retry, so recovery paths
    must re-place, not just re-call.  ``device_ids`` names the lost
    device ordinals when the runtime (or an injected fault) knows them;
    empty means "one unidentified member"."""

    def __init__(self, msg: str = "device lost", device_ids=()):
        super().__init__(msg)
        self.device_ids = tuple(device_ids)


#: message fragments that mark an XLA runtime error as OOM vs device
#: loss (the jax runtime has no stable typed taxonomy; string-matching
#: its status text is the supported art, and the fragments below cover
#: PJRT/XLA across the generations this repo runs on)
_OOM_MARKERS = (
    "RESOURCE_EXHAUSTED", "Out of memory", "out of memory", "OOM",
    "Resource exhausted", "Failed to allocate",
)
_LOST_MARKERS = (
    "device is lost", "Device lost", "DEVICE_LOST",
    "device not found", "No such device", "device unavailable",
    "failed to connect to device", "chip reset", "halted",
    "INTERNAL: Mesh", "missing device",
)


def classify_device_error(err: BaseException):
    """Map a raw backend/runtime exception to the typed device-error
    taxonomy: returns a :class:`DeviceOomError` / :class:`DeviceLostError`
    (the original as ``__cause__``) or ``None`` when the error is not a
    device-resource failure.  Already-typed errors pass through.  The
    single classification point for every invoke path (jax-xla backend,
    slot-engine pump), so the OOM/lost vocabulary cannot drift."""
    if isinstance(err, (DeviceOomError, DeviceLostError)):
        return err
    mod = type(err).__module__ or ""
    name = type(err).__name__
    if not (name == "XlaRuntimeError" or mod.startswith("jaxlib")
            or mod.startswith("jax.")):
        return None
    msg = str(err)
    if any(m in msg for m in _OOM_MARKERS):
        out = DeviceOomError(f"device OOM: {msg[:400]}")
        out.__cause__ = err
        return out
    if any(m in msg for m in _LOST_MARKERS):
        out = DeviceLostError(f"device lost: {msg[:400]}")
        out.__cause__ = err
        return out
    return None


def device_call(fn, *args, inject=True):
    """THE classification boundary around a raw device call (shared by
    the jax-xla backend and the slot-engine pump so the two wrappers
    cannot drift): fires the deterministic ``device.oom`` /
    ``device.lost`` fault sites where the real chip would fail, maps
    raw runtime errors through :func:`classify_device_error`, and
    re-raises everything else untouched.  ``inject=False`` keeps the
    typed classification but skips the fault sites — transfer/staging
    paths use it so an armed ``device.oom``/``device.lost`` counter
    keeps firing at compiled-call boundaries only (deterministic
    injection placement), while a REAL transfer-time
    ``RESOURCE_EXHAUSTED`` still surfaces typed."""
    try:
        if inject and FAULTS.is_armed():
            FAULTS.check("device.oom")
            FAULTS.check("device.lost")
        return fn(*args)
    except (KeyboardInterrupt, SystemExit):
        raise
    except BaseException as e:  # noqa: BLE001 — classification boundary
        typed = classify_device_error(e)
        if typed is None or typed is e:
            raise
        raise typed from e


class RemoteApplicationError(RuntimeError):
    """The remote ANSWERED — with an application-level error reply.

    The round trip itself succeeded, so this must never count against
    the remote's health (circuit breakers, down-cooldowns): a stream of
    poison frames must not trip a breaker open against a healthy
    server."""


def is_remote_application_error(err: BaseException) -> bool:
    """True when the failure is an application-level reply from a live
    server (transport worked), as opposed to a connectivity/timeout
    fault.  Health machinery (breakers, cooldowns) must ignore these."""
    if isinstance(err, RemoteApplicationError):
        return True
    try:
        import grpc

        if isinstance(err, grpc.RpcError):
            code = getattr(err, "code", lambda: None)()
            # a status the server DECIDED to send ≠ a dead server.
            # DATA_LOSS is the exception among decided statuses: a
            # corrupt exchange IS ill-health of the link/remote —
            # sustained corruption must be able to trip breakers.
            return code not in (
                None,
                grpc.StatusCode.UNAVAILABLE,
                grpc.StatusCode.DEADLINE_EXCEEDED,
                grpc.StatusCode.CANCELLED,
                grpc.StatusCode.DATA_LOSS,
            )
    except ImportError:  # pragma: no cover — grpc is a baked-in dep
        pass
    return False


def register_transient(*types: Type[BaseException]) -> None:
    """Extend the transient set (e.g. a transport's own error type)."""
    global _TRANSIENT_TYPES
    _TRANSIENT_TYPES = _TRANSIENT_TYPES + tuple(types)


def register_fatal(*types: Type[BaseException]) -> None:
    global _FATAL_TYPES
    _FATAL_TYPES = _FATAL_TYPES + tuple(types)


def is_transient(err: BaseException) -> bool:
    """Best-effort classification; unknown exception types default to
    transient (an always-on pipeline prefers one wasted retry over a
    dropped stream), except the known-fatal program-error set."""
    if not isinstance(err, Exception):
        return False  # KeyboardInterrupt/SystemExit/GeneratorExit: never retry
    if isinstance(err, FatalError):
        return False
    if isinstance(err, TransientError):
        return True
    # explicit marker wins over the type tables (a transport can stamp
    # an exception it re-raises without subclassing)
    marked = getattr(err, "nns_transient", None)
    if marked is not None:
        return bool(marked)
    # gRPC: UNAVAILABLE / DEADLINE_EXCEEDED / RESOURCE_EXHAUSTED retry;
    # INVALID_ARGUMENT / UNIMPLEMENTED etc. do not
    try:
        import grpc

        if isinstance(err, grpc.RpcError):
            code = getattr(err, "code", lambda: None)()
            return code in (
                grpc.StatusCode.UNAVAILABLE,
                grpc.StatusCode.DEADLINE_EXCEEDED,
                grpc.StatusCode.RESOURCE_EXHAUSTED,
                grpc.StatusCode.ABORTED,
            )
    except ImportError:  # pragma: no cover — grpc is a baked-in dep
        pass
    if isinstance(err, _FATAL_TYPES):
        return False
    if isinstance(err, _TRANSIENT_TYPES):
        return True
    return True


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------
@dataclass
class RetryPolicy:
    """Exponential backoff with jitter under a total deadline budget.

    ``max_attempts`` bounds tries (first call included); ``deadline_s``
    bounds the *total* wall time spent inside :meth:`call` — a retry
    whose backoff would overrun the budget is not taken.  ``jitter`` is
    the +/- fraction applied to each delay (0.25 = 25%), drawn from a
    seedable RNG for reproducible tests.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.25
    deadline_s: Optional[float] = None
    classify: Callable[[BaseException], bool] = field(default=is_transient)
    seed: Optional[int] = None

    def __post_init__(self):
        self._rng = random.Random(self.seed)

    def delay_for(self, attempt: int) -> float:
        """Backoff before retry #`attempt` (1-based: after the first
        failure attempt=1)."""
        raw = min(
            self.max_delay_s,
            self.base_delay_s * (self.multiplier ** max(0, attempt - 1)),
        )
        if self.jitter:
            raw *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(0.0, raw)

    def call(
        self,
        fn: Callable[..., Any],
        *args: Any,
        on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
        **kwargs: Any,
    ) -> Any:
        """Run ``fn`` under this policy.  ``on_retry(attempt, err,
        delay)`` fires before each backoff; fatal errors and budget
        exhaustion re-raise the last error immediately."""
        start = clock()
        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except (KeyboardInterrupt, SystemExit, GeneratorExit):
                raise  # interrupts must never be absorbed into a retry
            except BaseException as e:  # noqa: BLE001 — policy boundary
                attempt += 1
                if not self.classify(e):
                    raise
                if attempt >= self.max_attempts:
                    raise
                delay = self.delay_for(attempt)
                if self.deadline_s is not None:
                    elapsed = clock() - start
                    if elapsed + delay >= self.deadline_s:
                        raise
                if on_retry is not None:
                    on_retry(attempt, e, delay)
                if delay > 0:
                    sleep(delay)


# ---------------------------------------------------------------------------
# CircuitBreaker
# ---------------------------------------------------------------------------
class CircuitOpenError(ConnectionError):
    """Raised by :meth:`CircuitBreaker.call` while the circuit is open.

    Subclasses ConnectionError so existing transport-boundary handlers
    (and :func:`is_transient`) treat a tripped breaker as a transient,
    fail-fast condition."""


class CircuitBreaker:
    """Classic closed / open / half-open breaker on a rolling window.

    * **closed**: calls flow; failures are timestamped into a rolling
      ``window_s`` deque — reaching ``failure_threshold`` failures
      inside the window trips the breaker open.
    * **open**: calls are refused (``allow()`` False /
      :class:`CircuitOpenError`) until ``reset_timeout_s`` passes.
    * **half-open**: up to ``half_open_max`` probe calls are let
      through; one success closes the breaker (and clears the window),
      one failure re-opens it for another ``reset_timeout_s``.

    Thread-safe; ``clock`` is injectable for fake-clock tests.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"

    def __init__(
        self,
        failure_threshold: int = 5,
        window_s: float = 30.0,
        reset_timeout_s: float = 5.0,
        half_open_max: int = 1,
        clock: Callable[[], float] = time.monotonic,
        name: str = "",
        on_trip: Optional[Callable[["CircuitBreaker"], None]] = None,
    ):
        self.failure_threshold = max(1, int(failure_threshold))
        self.window_s = float(window_s)
        self.reset_timeout_s = float(reset_timeout_s)
        self.half_open_max = max(1, int(half_open_max))
        self.name = name
        # observability hook: fired (outside the lock) each time the
        # breaker transitions to OPEN — the query client routes it into
        # the pipeline's flight recorder (Documentation/observability.md)
        self._on_trip = on_trip
        self._clock = clock
        self._lock = threading.Lock()
        self._failures: List[float] = []
        self._state = self.CLOSED
        self._opened_at = 0.0
        self._probes = 0
        self._last_probe_at = 0.0
        self._trips = 0  # lifetime count of closed/half-open -> open

    # -- state ---------------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._peek_state()

    @property
    def trip_count(self) -> int:
        with self._lock:
            return self._trips

    def _peek_state(self) -> str:
        # lock held: open lazily decays into half-open on inspection
        now = self._clock()
        if self._state == self.OPEN:
            if now - self._opened_at >= self.reset_timeout_s:
                self._state = self.HALF_OPEN
                self._probes = 0
        elif (
            self._state == self.HALF_OPEN
            and self._probes >= self.half_open_max
            and now - self._last_probe_at >= self.reset_timeout_s
        ):
            # a probe slot was reserved but its outcome never recorded
            # (caller abandoned mid-call — e.g. a generator torn down by
            # pipeline stop): self-heal by opening a new probe window
            # instead of staying wedged half-open forever
            self._probes = 0
        return self._state

    def allow(self) -> bool:
        """True if a call may proceed now (reserves a probe slot while
        half-open)."""
        with self._lock:
            st = self._peek_state()
            if st == self.CLOSED:
                return True
            if st == self.HALF_OPEN and self._probes < self.half_open_max:
                self._probes += 1
                self._last_probe_at = self._clock()
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            if self._peek_state() == self.OPEN:
                # STALE in-flight success (request predates the trip,
                # e.g. a slow response from before the failure burst):
                # closing here would bypass reset_timeout and half-open
                # probing entirely — under partial loss the breaker
                # would flap closed on every stray success.  Symmetric
                # to the stale-failure case in record_failure().
                return
            self._failures.clear()
            if self._state != self.CLOSED:
                log.info("breaker %s: closed (probe succeeded)", self.name)
            self._state = self.CLOSED
            self._probes = 0

    def record_failure(self) -> None:
        tripped = False
        with self._lock:
            now = self._clock()
            st = self._peek_state()
            if st == self.HALF_OPEN and self._probes > 0:
                # a granted probe failed: straight back to open.  With no
                # probe outstanding this is a STALE in-flight failure
                # (request older than the open window, e.g. a timeout
                # longer than reset_timeout) — it falls through to plain
                # window accounting instead of re-opening and bumping
                # trip_count for a probe that never ran.
                self._state = self.OPEN
                self._opened_at = now
                self._probes = 0
                self._trips += 1
                tripped = True
                log.warning("breaker %s: re-opened (probe failed)", self.name)
            else:
                self._failures.append(now)
                cutoff = now - self.window_s
                self._failures = [t for t in self._failures if t >= cutoff]
                if (
                    st == self.CLOSED
                    and len(self._failures) >= self.failure_threshold
                ):
                    self._state = self.OPEN
                    self._opened_at = now
                    self._trips += 1
                    tripped = True
                    log.warning(
                        "breaker %s: OPEN (%d failures in %.1fs)",
                        self.name, len(self._failures), self.window_s,
                    )
        if tripped and self._on_trip is not None:
            try:
                self._on_trip(self)
            except Exception:  # observer bugs must never break the breaker
                log.exception("breaker %s: on_trip hook failed", self.name)

    def call(self, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> Any:
        if not self.allow():
            raise CircuitOpenError(
                f"circuit {self.name or 'breaker'} is {self.state}"
            )
        try:
            result = fn(*args, **kwargs)
        except BaseException:
            self.record_failure()
            raise
        self.record_success()
        return result

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self._peek_state(),
                "recent_failures": len(self._failures),
                "trips": self._trips,
            }


# ---------------------------------------------------------------------------
# FaultInjector
# ---------------------------------------------------------------------------
class _FaultPlan:
    """One armed site: decides per-invocation whether to raise."""

    def __init__(
        self,
        exc: Any = None,
        rate: float = 0.0,
        times: Optional[int] = None,
        after: int = 0,
        every: Optional[int] = None,
        seed: int = 0,
        callback: Optional[Callable[[int], Optional[BaseException]]] = None,
        delay: float = 0.0,
        hang: bool = False,
        corrupt: Optional[str] = None,
    ):
        self.exc = exc if exc is not None else TransientError("injected fault")
        self.rate = float(rate)
        self.times = times  # max number of faults to fire (None = forever)
        self.after = int(after)  # skip the first N invocations
        self.every = every  # fire on every Nth invocation (deterministic)
        self.callback = callback
        # latency faults: delay=S sleeps the caller S seconds at the site
        # (then proceeds normally); hang=True blocks until cooperatively
        # interrupted, then raises StallError (core/liveness.py) — the
        # deterministic stand-in for an element that silently wedges
        self.delay = float(delay)
        self.hang = bool(hang)
        # corruption faults: 'bitflip' | 'truncate' — consumed by
        # FaultInjector.mangle() at wire sites (check() ignores these
        # plans; the fault is a data mutation, not an exception)
        if corrupt not in (None, "bitflip", "truncate"):
            raise ValueError(f"corrupt={corrupt!r} (want bitflip|truncate)")
        self.corrupt = corrupt
        self._rng = random.Random(seed)
        self.calls = 0
        self.fired = 0

    def decide(self) -> Optional[Tuple[str, Any]]:
        """None (no fault) or an action: ``("raise", exc)``,
        ``("delay", seconds)``, or ``("hang", None)``."""
        i = self.calls
        self.calls += 1
        if self.callback is not None:
            err = self.callback(i)
            if err is not None:
                self.fired += 1
                return ("raise", err)
            return None
        if i < self.after:
            return None
        if self.times is not None and self.fired >= self.times:
            return None
        hit = (
            ((i - self.after) % self.every == 0) if self.every
            else (self._rng.random() < self.rate)
        )
        if not hit:
            return None
        self.fired += 1
        if self.corrupt:
            return ("corrupt", self.corrupt)
        if self.hang:
            return ("hang", None)
        if self.delay > 0:
            return ("delay", self.delay)
        exc = self.exc
        if isinstance(exc, type):
            return ("raise", exc("injected fault"))
        try:
            # fresh instance per fire: concurrent raisers of ONE shared
            # instance would cross-contaminate __traceback__/__context__
            return ("raise", type(exc)(*exc.args))
        except Exception:  # exotic ctor signature: fall back to sharing
            return ("raise", exc)


class FaultInjector:
    """Process-wide registry of named fault sites.

    Production code sprinkles ``FAULTS.check("tcp_query.send")`` at
    interesting boundaries; the check is a no-op until a test *arms*
    the site::

        FAULTS.arm("tcp_query.send", rate=0.3, seed=7,
                   exc=ConnectionResetError)
        ...
        FAULTS.reset()   # in teardown, always

    Determinism: rate-based plans draw from their own seeded RNG, and
    ``every=N`` fires on exactly every Nth invocation — two runs with
    the same seed inject the same fault sequence.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._plans: Dict[str, _FaultPlan] = {}
        self._armed = False  # one-bool fast path for un-instrumented runs
        # release valve for in-progress delay/hang faults: reset()/disarm()
        # set it so no teardown ever waits on an injected wedge
        self._release = threading.Event()

    def arm(
        self,
        site: str,
        exc: Any = None,
        rate: float = 1.0,
        times: Optional[int] = None,
        after: int = 0,
        every: Optional[int] = None,
        seed: int = 0,
        callback: Optional[Callable[[int], Optional[BaseException]]] = None,
        delay: float = 0.0,
        hang: bool = False,
        corrupt: Optional[str] = None,
    ) -> None:
        """Arm `site`.  ``exc`` may be an exception instance or class;
        ``rate`` is the per-invocation fault probability (1.0 = always),
        ``every=N`` switches to strictly periodic injection, ``after``
        skips the first invocations, ``times`` caps total faults, and
        ``callback(i)`` takes full control (return an exception or
        None).  ``delay=S`` injects S seconds of latency instead of an
        error (the call then proceeds); ``hang=True`` blocks the caller
        until cooperatively interrupted — the site's ``interrupt``
        callable, the element's interrupt flag, or ``reset()`` — then
        raises :class:`~..core.liveness.StallError`.

        ``corrupt="bitflip"|"truncate"`` injects deterministic seeded
        WIRE CORRUPTION instead of an exception: instrumented transports
        route their encoded bytes through :meth:`mangle`, which flips
        one seeded bit / truncates at a seeded offset whenever the plan
        fires (``check()`` ignores corrupt plans — the fault is a data
        mutation, not a raise)."""
        with self._lock:
            self._plans[site] = _FaultPlan(
                exc=exc, rate=rate, times=times, after=after,
                every=every, seed=seed, callback=callback,
                delay=delay, hang=hang, corrupt=corrupt,
            )
            self._armed = True
            self._release.clear()

    def disarm(self, site: str) -> None:
        with self._lock:
            self._plans.pop(site, None)
            self._armed = bool(self._plans)
            if not self._armed:
                self._release.set()

    def reset(self) -> None:
        with self._lock:
            self._plans.clear()
            self._armed = False
            self._release.set()

    def is_armed(self) -> bool:
        """Fast gate for call sites whose site NAME is costly to build
        (f-strings on per-frame paths): skip check() entirely when no
        plan is armed."""
        return self._armed

    def check(self, site: str,
              interrupt: Optional[Callable[[], bool]] = None) -> None:
        """Raise/delay/hang per the planned fault for `site`, if armed
        (hot-path no-op otherwise).  ``interrupt`` is the cooperative
        escape hatch for latency faults: sites on supervised paths pass
        the element's interrupt/stop predicate so a watchdog escalation
        (or pipeline stop) can break an injected hang."""
        if not self._armed:
            return
        with self._lock:
            plan = self._plans.get(site)
            if plan is None or plan.corrupt is not None:
                return  # corrupt plans fire via mangle(), not check()
            action = plan.decide()
        if action is None:
            return
        kind, arg = action
        if kind == "raise":
            log.debug("fault injected at %s: %r", site, arg)
            raise arg
        if kind == "delay":
            log.debug("latency fault at %s: %.3fs", site, arg)
            deadline = time.monotonic() + arg
            while time.monotonic() < deadline:
                if (interrupt is not None and interrupt()) or \
                        self._release.wait(
                            min(0.005, max(0.0, deadline - time.monotonic()))):
                    break
            return
        # hang: block until someone pulls the plug, then surface as a
        # stall so restart machinery can treat it like any transient
        log.debug("hang fault at %s (waiting for interrupt)", site)
        while not (interrupt is not None and interrupt()):
            if self._release.wait(0.005):
                break
        from .liveness import StallError

        raise StallError(f"injected hang at {site} interrupted")

    def mangle(self, site: str, data):
        """Deterministic wire corruption: when `site` is armed with a
        ``corrupt=`` plan and the plan fires, return a mutated COPY of
        ``data`` (one seeded bit flipped, or the buffer truncated at a
        seeded offset); otherwise return ``data`` unchanged.

        Instrumented transports call this on their ENCODED bytes, after
        checksums are computed — simulating corruption on the wire, so
        the receiver's integrity verification is what must catch it.
        Sites guard the call with :meth:`is_armed` to keep the un-armed
        hot path free."""
        if not self._armed:
            return data
        with self._lock:
            plan = self._plans.get(site)
            if plan is None or plan.corrupt is None:
                return data
            action = plan.decide()
            if action is None:
                return data
            kind = action[1]
            buf = bytearray(bytes(data))
            if not buf:
                return data
            if kind == "truncate":
                cut = plan._rng.randrange(len(buf))
                log.debug("corruption fault at %s: truncated %d -> %d bytes",
                          site, len(buf), cut)
                return bytes(buf[:cut])
            pos = plan._rng.randrange(len(buf) * 8)
            buf[pos // 8] ^= 1 << (pos % 8)
            log.debug("corruption fault at %s: bit %d flipped", site, pos)
            return bytes(buf)

    def mangle_parts(self, site: str, parts: List) -> List:
        """:meth:`mangle` over a vectored parts list: the join (a copy)
        happens only when `site` actually holds a corrupt plan, so
        gather-send hot paths never pay it un-armed."""
        if not self._armed:
            return parts
        with self._lock:
            plan = self._plans.get(site)
            armed = plan is not None and plan.corrupt is not None
        if not armed:
            return parts
        return [self.mangle(site, b"".join(bytes(p) for p in parts))]

    def stats(self, site: str) -> Dict[str, int]:
        """{calls, fired} counters for an armed (or just-disarmed) site;
        zeros if never armed."""
        with self._lock:
            plan = self._plans.get(site)
            if plan is None:
                return {"calls": 0, "fired": 0}
            return {"calls": plan.calls, "fired": plan.fired}

    def armed_sites(self) -> List[str]:
        with self._lock:
            return sorted(self._plans)


#: the process-wide injector every instrumented site consults
FAULTS = FaultInjector()
