"""Fleet observatory: discovery-plane telemetry digests + fleet rollups.

The serving fleet already publishes rich per-server signals — admission
ledgers, slot-engine counters, memory watermarks, draining/degraded
state — but until this module they were trapped behind each server's
``health()``; no component could see the fleet.  This module closes the
sensing half of the autoscaling loop (ROADMAP item 4) in three pieces:

* :class:`DigestPublisher` — a fake-clock-testable periodic builder of a
  compact, versioned, BOUNDED JSON digest of one server's live state
  (seq + monotonic age, tokens/s EWMA, slot occupancy, memory headroom
  bytes, per-tenant admitted/shed, inflight, draining/degraded/swap
  state).  The serversrc drives it on the watchdog-sweeper cadence and
  publishes each digest via the retained-announce ``update()`` path
  (``distributed/hybrid.py``), so the discovery plane carries telemetry
  with zero new connections and zero per-frame cost.
* :class:`FleetObservatory` — subscribes to the announce topics, keeps a
  bounded per-server table with TTL eviction (each digest carries its
  own ``ttl_s``; a crashed server that never tombstones its announce is
  retired here), and computes fleet rollups: aggregate tokens/s,
  weighted slot occupancy, admittable-slot headroom, per-tenant fleet
  admitted/shed, draining/degraded census, worst per-tenant SLO burn.
  Counter rollups include RETIRED servers (tombstoned or TTL-evicted),
  so fleet totals stay exactly equal to the sum of every per-server
  ledger that ever served — the chaos harness pins this.
* :func:`hint_from_announce` — the ONE capture path for per-endpoint
  routing hints: the digest carries ``draining``/``degraded``, and the
  legacy top-level announce keys (pre-digest fleets) stay accepted.

Staleness is explicit by design: a digest names its ``seq``, its
publisher's monotonic ``age_s``, and its ``ttl_s`` — a consumer can
always tell a live number from a stale one (the PR-8 lesson: never
export a point-in-time number as if live).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .log import get_logger
from .telemetry import METRICS, REGISTRY, Sample, metric_kind

log = get_logger("fleet")

#: digest schema version (consumers skip digests they don't speak)
DIGEST_VERSION = 1
#: announce key the digest rides under (``info["digest"]``)
DIGEST_KEY = "digest"
#: serialized-size bound on one digest (the announce must stay a small
#: control-plane message; over-budget digests drop their per-tenant maps
#: loudly via ``truncated`` instead of growing without bound)
DIGEST_MAX_BYTES = 4096
#: per-tenant rows kept in one digest (busiest tenants win; the drop is
#: visible via ``tenants_dropped`` so truncation is never silent)
DIGEST_MAX_TENANTS = 16
#: default digest TTL = this many publish intervals without a fresh
#: digest before the observatory retires the row
DIGEST_TTL_INTERVALS = 3.0
#: fraction of a row's TTL after which it is STALE: still listed (the
#: server may be merely slow), but a wedged-but-announcing server must
#: never count as capacity, so stale rows are excluded from the rollup's
#: headroom/throughput gauges and from controller math
DIGEST_STALE_FRACTION = 0.5
#: retired-contribution snapshots kept for possible resurrection
#: (a topic is one process instance — pid+uuid — so a very old
#: snapshot can never match a new server; bound the ledger)
RETIRED_ROWS_MAX = 1024
#: smoothing for the tokens/s EWMA carried in the digest
_RATE_EWMA = 0.3

#: bound on live per-server rows in one observatory (beyond it the
#: oldest row is retired — table growth is an operator error, not OOM)
OBSERVATORY_MAX_SERVERS = 512


def hint_from_announce(info: dict) -> Dict[str, bool]:
    """The ONE capture path for per-endpoint routing hints from a
    retained announce: prefer the digest's ``draining``/``degraded``
    fields (they are refreshed on the digest cadence, not only at state
    changes), fall back to the legacy top-level announce keys so mixed
    fleets (pre-digest servers) keep propagating health."""
    d = info.get(DIGEST_KEY)
    if isinstance(d, dict) and "draining" in d:
        return {
            "draining": bool(d.get("draining", False)),
            "degraded": bool(d.get("degraded", False)),
        }
    return {
        "draining": bool(info.get("draining", False)),
        "degraded": bool(info.get("degraded", False)),
    }


def pipeline_digest_stats(pipe) -> Dict[str, Any]:
    """Scan one pipeline's ``health()`` rows for the digest's
    cross-element signals: slot-engine counters (summed over
    generators), the most interesting hot-swap state, per-tenant SLO
    burn (worst per tenant across elements), and the memory-watermark
    headroom.  Shared by the serversrc's digest source and the bench
    evidence attach, so the two cannot capture different facts."""
    stats: Dict[str, Any] = {}
    gen_keys = ("gen_tokens", "gen_slots", "gen_occupied", "gen_waiting")
    sums = dict.fromkeys(gen_keys, 0)
    have_gen = False
    swap = "idle"
    slo_burn: Dict[str, float] = {}
    ttft_p95 = 0.0
    try:
        health = pipe.health()
    except Exception:  # a digest must never die on a health bug
        log.exception("digest health scan failed")
        return stats
    for row in health.values():
        if "gen_slots" in row:
            have_gen = True
            for k in gen_keys:
                sums[k] += int(row.get(k, 0) or 0)
        s = row.get("swap_state")
        if s and s != "idle":
            swap = s
        slo = row.get("slo")
        if isinstance(slo, dict):
            for tenant, srow in slo.items():
                burns = [
                    v for k, v in srow.items()
                    if k.endswith("_burn") and isinstance(v, (int, float))
                ]
                if burns:
                    slo_burn[tenant] = max(
                        slo_burn.get(tenant, 0.0), max(burns))
                t95 = srow.get("ttft_p95_ms")
                if isinstance(t95, (int, float)):
                    ttft_p95 = max(ttft_p95, float(t95))
    if have_gen:
        stats["tokens"] = sums["gen_tokens"]
        stats["slots"] = sums["gen_slots"]
        stats["occupied"] = sums["gen_occupied"]
        stats["waiting"] = sums["gen_waiting"]
    stats["swap"] = swap
    if ttft_p95 > 0:
        # worst observed p95 TTFT across tenants — the predictive
        # autoscaler's latency observable (core/autoscale.py PerfModel)
        stats["ttft_p95_ms"] = round(ttft_p95, 3)
    if slo_burn:
        stats["slo_burn"] = {
            t: round(float(b), 3) for t, b in slo_burn.items()}
    # shared-prefix cache advert: per-server hit/miss counters plus a
    # bounded MRU list of hot prefix digests, so peers (and the
    # observatory rollup) can see WHERE a shared prefix is already warm.
    # Duck-typed off the elements — only armed slotted generators grow a
    # prefix_digest_info(); everything else is silently skipped.
    pfx = {"hits": 0, "misses": 0, "entries": 0}
    pfx_hot: List[str] = []
    have_pfx = False
    for el in getattr(pipe, "elements", {}).values():
        info_fn = getattr(el, "prefix_digest_info", None)
        if info_fn is None:
            continue
        try:
            info = info_fn()
        except Exception:
            log.exception("prefix digest scan failed for %s",
                          getattr(el, "name", el))
            continue
        if not isinstance(info, dict):
            continue
        have_pfx = True
        for k in ("hits", "misses", "entries"):
            pfx[k] += int(info.get(k, 0) or 0)
        for d in info.get("hot", ()):
            if d not in pfx_hot:
                pfx_hot.append(d)
    if have_pfx:
        pfx["hot"] = pfx_hot[:8]
        stats["prefix"] = pfx
    mon = getattr(pipe, "memory_monitor", None)
    if mon is not None:
        snap = mon.snapshot()
        limit = int(snap.get("mem_bytes_limit", 0) or 0)
        in_use = int(snap.get("mem_bytes_in_use", 0) or 0)
        if limit > 0:
            headroom = max(0, int(limit * mon.high) - in_use)
        else:
            headroom = 0
        stats["mem_headroom_bytes"] = headroom
        stats["mem_pressure"] = int(snap.get("mem_pressure", 0) or 0)
    return stats


class DigestPublisher:
    """Periodic builder/publisher of one server's telemetry digest.

    ``source()`` returns the raw stats dict (the serversrc merges its
    admission ledger with :func:`pipeline_digest_stats`); ``publish(d)``
    ships the built digest (the serversrc routes it through the retained
    announce's ``update()``).  :meth:`poll` is rate-limited by
    ``interval_s`` on the injected ``clock`` — drive it from any slow
    cadence (the watchdog sweeper) or directly in tests with a fake
    clock; ``poll(force=True)`` publishes NOW (drain entry, final
    pre-stop flush) so state changes never wait out the interval.

    Every digest carries its own staleness contract: a monotonically
    increasing ``seq``, the publisher's monotonic ``age_s`` (resets on
    restart — a consumer can tell a reborn server from a stale row), and
    ``ttl_s`` after which consumers must treat the row as dead."""

    def __init__(self, source: Callable[[], Dict[str, Any]],
                 publish: Callable[[Dict[str, Any]], None],
                 interval_s: float = 2.0,
                 clock: Callable[[], float] = time.monotonic,
                 name: str = "digest"):
        self.source = source
        self.publish = publish
        self.interval_s = max(0.05, float(interval_s))
        self.ttl_s = self.interval_s * DIGEST_TTL_INTERVALS
        self.clock = clock
        self.name = name
        self.seq = 0
        self.published = 0
        self.publish_failures = 0
        self.last_digest: Optional[Dict[str, Any]] = None
        self._t0 = clock()
        self._last_pub = float("-inf")
        # tokens/s EWMA state (successive gen_tokens deltas)
        self._last_tokens: Optional[int] = None
        self._last_tokens_ts: Optional[float] = None
        self._rate: Optional[float] = None
        self._lock = threading.Lock()

    def _tokens_rate(self, tokens: Optional[int], now: float) -> float:
        """Fold the cumulative token counter into a tokens/s EWMA —
        cheap, and unlike a raw counter it reads as LIVE throughput."""
        if tokens is None:
            return 0.0
        if self._last_tokens is not None and self._last_tokens_ts is not None:
            dt = now - self._last_tokens_ts
            if dt > 0:
                rate = max(0.0, tokens - self._last_tokens) / dt
                self._rate = (rate if self._rate is None
                              else self._rate + _RATE_EWMA
                              * (rate - self._rate))
        self._last_tokens = tokens
        self._last_tokens_ts = now
        return round(self._rate or 0.0, 3)

    def _bounded_tenants(self, tenants: Dict[str, Dict[str, Any]]
                         ) -> Tuple[Dict[str, Dict[str, int]], int]:
        rows = {
            str(t): {"admitted": int(r.get("admitted", 0)),
                     "shed": int(r.get("shed", 0))}
            for t, r in tenants.items()
        }
        if len(rows) <= DIGEST_MAX_TENANTS:
            return rows, 0
        busiest = sorted(
            rows, key=lambda t: (rows[t]["admitted"] + rows[t]["shed"]),
            reverse=True)[:DIGEST_MAX_TENANTS]
        return {t: rows[t] for t in busiest}, len(rows) - DIGEST_MAX_TENANTS

    def build(self) -> Dict[str, Any]:
        """One digest from the current ``source()`` stats (no publish,
        no rate limit — :meth:`poll` wraps this)."""
        now = self.clock()
        stats = dict(self.source() or {})
        self.seq += 1
        digest: Dict[str, Any] = {
            "v": DIGEST_VERSION,
            "seq": self.seq,
            "age_s": round(now - self._t0, 3),
            "interval_s": self.interval_s,
            "ttl_s": round(self.ttl_s, 3),
            "draining": bool(stats.get("draining", False)),
            "degraded": bool(stats.get("degraded", False)),
            "swap": str(stats.get("swap", "idle")),
            "inflight": int(stats.get("inflight", 0)),
            "admitted": int(stats.get("admitted", 0)),
            "shed": int(stats.get("shed", 0)),
            "tokens_per_s": self._tokens_rate(stats.get("tokens"), now),
        }
        for k in ("tokens", "slots", "occupied", "waiting",
                  "mem_headroom_bytes", "mem_pressure"):
            if k in stats:
                digest[k] = int(stats[k])
        if "ttft_p95_ms" in stats:
            digest["ttft_p95_ms"] = round(float(stats["ttft_p95_ms"]), 3)
        tenants, dropped = self._bounded_tenants(stats.get("tenants") or {})
        if tenants:
            digest["tenants"] = tenants
        if dropped:
            digest["tenants_dropped"] = dropped
        slo_burn = stats.get("slo_burn")
        if slo_burn:
            digest["slo_burn"] = dict(slo_burn)
        # shared-prefix cache advert (armed slotted generators only):
        # exact hit/miss counters for the fleet rollup plus the bounded
        # hot-digest list peers use to find warm prefixes
        pfx = stats.get("prefix")
        if isinstance(pfx, dict):
            digest["prefix"] = {
                "hits": int(pfx.get("hits", 0) or 0),
                "misses": int(pfx.get("misses", 0) or 0),
                "entries": int(pfx.get("entries", 0) or 0),
                "hot": [str(d) for d in pfx.get("hot", ())][:8],
            }
        # size bound: the announce is a control-plane message — an
        # oversized digest drops its per-tenant maps LOUDLY rather than
        # growing without bound (rollups then under-report those maps,
        # which `truncated` makes visible fleet-wide)
        if len(json.dumps(digest)) > DIGEST_MAX_BYTES:
            digest.pop("tenants", None)
            digest.pop("slo_burn", None)
            digest.pop("prefix", None)
            digest["truncated"] = True
        return digest

    def poll(self, force: bool = False) -> Optional[Dict[str, Any]]:
        """Publish a fresh digest when the interval elapsed (or
        ``force``).  Returns the digest published, None when skipped.
        The WHOLE build+publish runs under one lock: the sweeper thread
        and a force-publish (drain entry) may race, and the retained
        announce must end up holding the HIGHEST seq — an unlocked
        publish could let an older digest land last and sit retained
        until the next interval (publish itself is a non-blocking
        enqueue, so holding the lock across it is cheap)."""
        with self._lock:
            now = self.clock()
            if not force and now - self._last_pub < self.interval_s:
                return None
            digest = self.build()
            self._last_pub = now
            try:
                self.publish(digest)
            except Exception as e:  # noqa: BLE001 — broker I/O best-effort
                self.publish_failures += 1
                log.warning("%s: digest publish failed: %s", self.name, e)
                return None
            self.last_digest = digest
            self.published += 1
            return digest


# ---------------------------------------------------------------------------
# Observatory
# ---------------------------------------------------------------------------
class _ServerRow:
    """One live server's latest digest + receipt bookkeeping."""

    __slots__ = ("topic", "host", "port", "digest", "received_ts", "digests")

    def __init__(self, topic: str, host: str, port: int):
        self.topic = topic
        self.host = host
        self.port = port
        self.digest: Dict[str, Any] = {}
        self.received_ts = 0.0
        self.digests = 0  # digests ingested for this row

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"


class FleetObservatory:
    """Fleet-wide view over the discovery plane's telemetry digests.

    Subscribe with :meth:`start` (an MQTT wildcard subscription over the
    announce topics) or feed announces directly through :meth:`ingest`
    (tests, bench).  Rows age out on each digest's own ``ttl_s``
    (checked lazily at read time — the observatory needs no thread of
    its own); tombstoned or TTL-evicted rows move their counters into a
    retired accumulator so :meth:`rollup` totals remain exactly the sum
    of every per-server ledger that ever served.

    Export rides the one registry path: :meth:`start` registers a single
    scrape-time collector emitting ``nns.fleet.*`` samples (labels
    ``fleet=<topic>``), :meth:`serve_metrics` opens the same Prometheus
    endpoint pipelines use, and :meth:`snapshot` is the pollable view
    the ``tools/fleet_top.py`` dashboard and the autoscaling controller
    (ROADMAP item 4) consume."""

    def __init__(self, topic: str = "", default_ttl_s: float = 10.0,
                 max_servers: int = OBSERVATORY_MAX_SERVERS,
                 clock: Callable[[], float] = time.monotonic,
                 stale_fraction: float = DIGEST_STALE_FRACTION,
                 retired_cap: int = RETIRED_ROWS_MAX):
        self.topic = topic
        self.default_ttl_s = float(default_ttl_s)
        self.max_servers = int(max_servers)
        self.stale_fraction = float(stale_fraction)
        self.retired_cap = max(1, int(retired_cap))
        self.clock = clock
        self._lock = threading.Lock()
        self._rows: Dict[str, _ServerRow] = {}   # topic -> row
        self._client = None
        self._server = None  # MetricsServer (serve_metrics)
        self._collector_registered = False
        # exactness across churn: retired counters accumulate at
        # tombstone/TTL-eviction time.  Per-topic contribution snapshots
        # (bounded LRU) let a RESURRECTED instance — a row TTL-evicted
        # while its server was merely slow/partitioned, then re-ingested
        # from the SAME instance topic — reverse its retired
        # contribution, or its cumulative counters would double-count
        # in the rollup forever
        self._retired_tokens = 0
        self._retired_admitted = 0
        self._retired_shed = 0
        self._retired_prefix_hits = 0
        self._retired_prefix_misses = 0
        self._retired_tenants: Dict[str, Dict[str, int]] = {}
        from collections import OrderedDict

        self._retired_rows: "OrderedDict[str, Dict[str, Any]]" = (
            OrderedDict())
        self.retired = 0         # rows retired (tombstone)
        self.stale_evicted = 0   # rows retired (TTL / table bound)
        self.retired_evicted = 0  # retired snapshots dropped by the cap
        self.resurrected = 0     # retired rows that came back alive
        self.digests = 0         # digests ingested, lifetime
        self.servers_seen = 0    # distinct announce instances ever seen
        # control-plane health: WHEN telemetry last arrived and WHETHER
        # the broker link is up — rows aging into the stale tier is a
        # symptom; this is the cause, surfaced explicitly
        self._plane_born_ts = self.clock()
        self._last_ingest_ts: Optional[float] = None

    # -- control-plane health ------------------------------------------------
    @property
    def plane_connected(self) -> bool:
        """True while the broker connection is up.  Direct-feed mode
        (tests/bench calling :meth:`ingest` with no broker) reads
        connected: there is no link to lose."""
        client = self._client
        return client is None or client.connected.is_set()

    @property
    def plane_reconnects(self) -> int:
        client = self._client
        return getattr(client, "reconnects", 0) if client is not None else 0

    def plane_ingest_age_s(self, now: Optional[float] = None) -> float:
        """Seconds since ANY discovery-plane traffic was ingested
        (dup-seq redeliveries and tombstones count — they prove the
        plane is moving); age since construction when nothing arrived
        yet."""
        now = self.clock() if now is None else now
        last = self._last_ingest_ts
        return max(0.0, now - (self._plane_born_ts if last is None
                               else last))

    # -- wiring -------------------------------------------------------------
    def start(self, broker_host: str, broker_port: int,
              brokers: Optional[List[Tuple[str, int]]] = None,
              ) -> "FleetObservatory":
        """Subscribe to ``nns/query/<topic>/#`` on the broker and
        register the ``nns.fleet.*`` registry collector.  ``brokers``
        is the ordered failover list handed to the MQTT client."""
        from ..distributed.mqtt import MqttClient

        self._client = MqttClient(broker_host, broker_port,
                                  brokers=brokers)
        # empty topic = EVERY announce topic: MQTT matches level by
        # level, so the pattern must be nns/query/# (nns/query//# would
        # only match servers whose topic= is literally empty)
        pattern = (f"nns/query/{self.topic}/#" if self.topic
                   else "nns/query/#")
        self._client.subscribe(pattern, self._on_msg, qos=0)
        if not self._collector_registered:
            REGISTRY.register_collector(self._collect)
            self._collector_registered = True
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            self._server = None
        if self._collector_registered:
            REGISTRY.unregister_collector(self._collect)
            self._collector_registered = False
        client, self._client = self._client, None
        if client is not None:
            client.close()

    def serve_metrics(self, port: int = 0, host: str = "127.0.0.1") -> int:
        """Prometheus exposition over the shared registry (the fleet
        collector registered by :meth:`start` rides it).  Returns the
        bound port."""
        from .telemetry import MetricsServer

        if not self._collector_registered:
            REGISTRY.register_collector(self._collect)
            self._collector_registered = True
        if self._server is None:
            self._server = MetricsServer(
                port=port, host=host, name=f"fleet-{self.topic or 'all'}")
        return self._server.port

    def _on_msg(self, topic: str, payload: bytes) -> None:
        if not payload:
            self.note_tombstone(topic)
            return
        try:
            info = json.loads(payload.decode())
        except ValueError:
            log.warning("undecodable announce on %s", topic)
            return
        self.ingest(topic, info)

    # -- ingest -------------------------------------------------------------
    def ingest(self, topic: str, info: dict) -> bool:
        """One retained announce (or announce update): upsert the
        server's row when it carries a digest this observatory speaks.
        Returns True when the row advanced (new instance or newer
        seq)."""
        digest = info.get(DIGEST_KEY)
        if not isinstance(digest, dict):
            return False
        if int(digest.get("v", 0)) != DIGEST_VERSION:
            return False
        try:
            host = str(info["host"])
            port = int(info["port"])
            seq = int(digest["seq"])
        except (KeyError, TypeError, ValueError):
            return False
        now = self.clock()
        with self._lock:
            # any decodable digest proves the plane is moving — set
            # BEFORE the dup-seq dedupe (a re-announced broker redelivers
            # retained state with an already-seen seq)
            self._last_ingest_ts = now
            self._evict_stale_locked(now)
            row = self._rows.get(topic)
            if row is None:
                row = _ServerRow(topic, host, port)
                self._rows[topic] = row
                if topic in self._retired_rows:
                    # resurrection: the instance was retired (transient
                    # staleness) but is alive — reverse its retired
                    # contribution, or its cumulative counters would
                    # be summed twice in every rollup from here on
                    self._unretire_locked(topic)
                else:
                    self.servers_seen += 1
            elif seq <= int(row.digest.get("seq", 0)):
                # retained redelivery / out-of-order duplicate: the row
                # already holds this digest or a newer one
                return False
            row.host, row.port = host, port
            row.digest = digest
            row.received_ts = now
            row.digests += 1
            self.digests += 1
            # table bound AFTER the upsert: the evicted row must be the
            # one with the oldest digest, never the half-initialized
            # newcomer (its counters retire exactly like a stale row's)
            while len(self._rows) > self.max_servers:
                oldest = min(
                    self._rows.values(), key=lambda r: r.received_ts)
                self._retire_locked(oldest, stale=True)
            return True

    def note_tombstone(self, topic: str) -> None:
        """The server deleted its retained announce (clean stop): retire
        its row — counters survive in the retired accumulator."""
        with self._lock:
            self._last_ingest_ts = self.clock()
            row = self._rows.pop(topic, None)
            if row is not None:
                self._retire_locked(row, stale=False, pop=False)

    #: back-compat alias for the module-level default cap
    _RETIRED_ROWS_MAX = RETIRED_ROWS_MAX

    def _retire_locked(self, row: _ServerRow, stale: bool,
                       pop: bool = True) -> None:
        d = row.digest
        pfx = d.get("prefix") or {}
        contrib = {
            "tokens": int(d.get("tokens", 0) or 0),
            "admitted": int(d.get("admitted", 0) or 0),
            "shed": int(d.get("shed", 0) or 0),
            "prefix_hits": int(pfx.get("hits", 0) or 0),
            "prefix_misses": int(pfx.get("misses", 0) or 0),
            "tenants": {
                t: {"admitted": int(r.get("admitted", 0)),
                    "shed": int(r.get("shed", 0))}
                for t, r in (d.get("tenants") or {}).items()
            },
        }
        self._retired_tokens += contrib["tokens"]
        self._retired_admitted += contrib["admitted"]
        self._retired_shed += contrib["shed"]
        self._retired_prefix_hits += contrib["prefix_hits"]
        self._retired_prefix_misses += contrib["prefix_misses"]
        for t, r in contrib["tenants"].items():
            agg = self._retired_tenants.setdefault(
                t, {"admitted": 0, "shed": 0})
            agg["admitted"] += r["admitted"]
            agg["shed"] += r["shed"]
        self._retired_rows[row.topic] = contrib
        self._retired_rows.move_to_end(row.topic)
        while len(self._retired_rows) > self.retired_cap:
            # aggregates already hold the evicted row's counters exactly
            # (the accumulators above are separate from these
            # snapshots); what is lost is only the ability to reverse a
            # resurrection for that topic — count it LOUDLY
            evicted_topic, _ = self._retired_rows.popitem(last=False)
            self.retired_evicted += 1
            log.warning(
                "retired-server ledger over cap (%d): dropping "
                "resurrection snapshot for %s (aggregates preserved)",
                self.retired_cap, evicted_topic)
        if stale:
            self.stale_evicted += 1
        else:
            self.retired += 1
        if pop:
            self._rows.pop(row.topic, None)

    def _unretire_locked(self, topic: str) -> None:
        contrib = self._retired_rows.pop(topic)
        self._retired_tokens -= contrib["tokens"]
        self._retired_admitted -= contrib["admitted"]
        self._retired_shed -= contrib["shed"]
        self._retired_prefix_hits -= int(contrib.get("prefix_hits", 0))
        self._retired_prefix_misses -= int(contrib.get("prefix_misses", 0))
        for t, r in contrib["tenants"].items():
            agg = self._retired_tenants.get(t)
            if agg is None:
                continue
            agg["admitted"] -= r["admitted"]
            agg["shed"] -= r["shed"]
            if agg["admitted"] == 0 and agg["shed"] == 0:
                self._retired_tenants.pop(t, None)
        self.resurrected += 1
        log.info(
            "digest row %s resurrected: its retired contribution "
            "(%d tokens) reversed", topic, contrib["tokens"])

    def _row_ttl(self, row: _ServerRow) -> float:
        return float(row.digest.get("ttl_s", self.default_ttl_s)
                     or self.default_ttl_s)

    def _stale_locked(self, row: _ServerRow, now: float) -> bool:
        """Stale tier below eviction: the digest outlived
        ``stale_fraction`` of its TTL.  The row stays listed (the server
        may be merely slow), but it is flagged in :meth:`servers`,
        counted in ``rollup()["stale"]``, and EXCLUDED from the
        headroom/throughput gauges — a wedged-but-announcing server must
        never count as capacity."""
        return now - row.received_ts > self.stale_fraction * self._row_ttl(row)

    def _evict_stale_locked(self, now: float) -> None:
        for row in list(self._rows.values()):
            ttl = self._row_ttl(row)
            if now - row.received_ts > ttl:
                log.warning(
                    "digest from %s (%s) stale for %.1fs > ttl %.1fs; "
                    "retiring the row", row.addr, row.topic,
                    now - row.received_ts, ttl)
                self._retire_locked(row, stale=True)

    # -- views --------------------------------------------------------------
    def servers(self) -> List[Dict[str, Any]]:
        """Live per-server table (stale rows evicted first): one dict
        per server with addr, digest fields, and the observed age."""
        now = self.clock()
        with self._lock:
            self._evict_stale_locked(now)
            # the digest's own age_s is the PUBLISHER's uptime; seen_s
            # is how long ago THIS observatory received it (staleness)
            return [
                {
                    **r.digest,
                    "topic": r.topic,
                    "addr": r.addr,
                    "seen_s": round(now - r.received_ts, 3),
                    "digests": r.digests,
                    "stale": self._stale_locked(r, now),
                }
                for r in sorted(self._rows.values(), key=lambda r: r.addr)
            ]

    def rollup(self) -> Dict[str, Any]:
        """Fleet aggregates.  Counters (``tokens``, ``admitted``,
        ``shed``, per-tenant rows) sum over live AND retired servers —
        exactly the sum of every per-server ledger that ever served;
        gauges (occupancy, headroom, tokens/s) cover live servers
        only."""
        now = self.clock()
        with self._lock:
            self._evict_stale_locked(now)
            rows = list(self._rows.values())
            roll: Dict[str, Any] = {
                "servers": len(rows),
                "stale": 0,
                "draining": 0,
                "degraded": 0,
                "swapping": 0,
                "mem_pressured": 0,
                "inflight": 0,
                "slots": 0,
                "occupied": 0,
                "waiting": 0,
                "tokens_per_s": 0.0,
                "slot_headroom": 0,
                "mem_headroom_bytes": 0,
                "ttft_p95_ms": 0.0,
                "tokens": self._retired_tokens,
                "admitted": self._retired_admitted,
                "shed": self._retired_shed,
                "prefix_hits": self._retired_prefix_hits,
                "prefix_misses": self._retired_prefix_misses,
                "prefix_entries": 0,
                "digests": self.digests,
                "retired": self.retired,
                "stale_evicted": self.stale_evicted,
                "retired_evicted": self.retired_evicted,
                "servers_seen": self.servers_seen,
                # control-plane health (explicit broker-loss signal)
                "plane_connected": 1 if self.plane_connected else 0,
                "plane_ingest_age_s": round(
                    self.plane_ingest_age_s(now), 3),
                "plane_reconnects": self.plane_reconnects,
            }
            tenants: Dict[str, Dict[str, int]] = {
                t: dict(r) for t, r in self._retired_tenants.items()
            }
            slo_burn: Dict[str, float] = {}
            for r in rows:
                d = r.digest
                stale = self._stale_locked(r, now)
                roll["stale"] += 1 if stale else 0
                roll["draining"] += 1 if d.get("draining") else 0
                roll["degraded"] += 1 if d.get("degraded") else 0
                roll["swapping"] += (
                    1 if d.get("swap", "idle") != "idle" else 0)
                pressured = bool(d.get("mem_pressure", 0))
                roll["mem_pressured"] += 1 if pressured else 0
                roll["inflight"] += int(d.get("inflight", 0) or 0)
                slots = int(d.get("slots", 0) or 0)
                occupied = int(d.get("occupied", 0) or 0)
                roll["slots"] += slots
                roll["occupied"] += occupied
                roll["waiting"] += int(d.get("waiting", 0) or 0)
                if not stale:
                    # capacity/throughput gauges come from FRESH rows
                    # only: a wedged-but-announcing server's numbers are
                    # fiction, and counting its free slots as headroom
                    # would talk the controller out of a needed scale-up
                    roll["tokens_per_s"] += float(d.get("tokens_per_s", 0.0)
                                                  or 0.0)
                    # admittable headroom: free slots on servers NOT
                    # under memory pressure (a pressured server sheds
                    # BUSY at the door, so its free slots are not
                    # admittable)
                    if not pressured:
                        roll["slot_headroom"] += max(0, slots - occupied)
                    roll["mem_headroom_bytes"] += int(
                        d.get("mem_headroom_bytes", 0) or 0)
                    roll["ttft_p95_ms"] = max(
                        roll["ttft_p95_ms"],
                        float(d.get("ttft_p95_ms", 0.0) or 0.0))
                roll["tokens"] += int(d.get("tokens", 0) or 0)
                roll["admitted"] += int(d.get("admitted", 0) or 0)
                roll["shed"] += int(d.get("shed", 0) or 0)
                pfx = d.get("prefix") or {}
                roll["prefix_hits"] += int(pfx.get("hits", 0) or 0)
                roll["prefix_misses"] += int(pfx.get("misses", 0) or 0)
                roll["prefix_entries"] += int(pfx.get("entries", 0) or 0)
                for t, trow in (d.get("tenants") or {}).items():
                    agg = tenants.setdefault(t, {"admitted": 0, "shed": 0})
                    agg["admitted"] += int(trow.get("admitted", 0))
                    agg["shed"] += int(trow.get("shed", 0))
                for t, b in (d.get("slo_burn") or {}).items():
                    slo_burn[t] = max(slo_burn.get(t, 0.0), float(b))
            roll["occupancy"] = round(
                roll["occupied"] / roll["slots"], 4) if roll["slots"] else 0.0
            lookups = roll["prefix_hits"] + roll["prefix_misses"]
            roll["prefix_hit_ratio"] = round(
                roll["prefix_hits"] / lookups, 4) if lookups else 0.0
            roll["tokens_per_s"] = round(roll["tokens_per_s"], 3)
            roll["tenants"] = tenants
            roll["slo_burn"] = {
                t: round(b, 3) for t, b in slo_burn.items()}
            return roll

    def snapshot(self) -> Dict[str, Any]:
        """Pollable fleet view: the rollup plus the live server table —
        what ``tools/fleet_top.py`` renders and scripts consume."""
        return {"rollup": self.rollup(), "servers": self.servers()}

    # -- registry export (ONE collector; scrape-time only) ------------------
    _ROLLUP_METRICS: Tuple[Tuple[str, str], ...] = (
        ("servers", "nns.fleet.servers"),
        ("stale", "nns.fleet.stale"),
        ("draining", "nns.fleet.draining"),
        ("degraded", "nns.fleet.degraded"),
        ("swapping", "nns.fleet.swapping"),
        ("mem_pressured", "nns.fleet.mem_pressured"),
        ("inflight", "nns.fleet.inflight"),
        ("slots", "nns.fleet.slots"),
        ("occupied", "nns.fleet.occupied"),
        ("waiting", "nns.fleet.waiting"),
        ("occupancy", "nns.fleet.occupancy"),
        ("tokens_per_s", "nns.fleet.tokens_per_s"),
        ("slot_headroom", "nns.fleet.slot_headroom"),
        ("mem_headroom_bytes", "nns.fleet.mem_headroom_bytes"),
        ("tokens", "nns.fleet.tokens"),
        ("admitted", "nns.fleet.admitted"),
        ("shed", "nns.fleet.shed"),
        ("prefix_hits", "nns.fleet.prefix_hits"),
        ("prefix_misses", "nns.fleet.prefix_misses"),
        ("prefix_hit_ratio", "nns.fleet.prefix_hit_ratio"),
        ("prefix_entries", "nns.fleet.prefix_entries"),
        ("digests", "nns.fleet.digests"),
        ("retired", "nns.fleet.retired"),
        ("stale_evicted", "nns.fleet.stale_evicted"),
        ("retired_evicted", "nns.fleet.retired_evicted"),
        ("ttft_p95_ms", "nns.fleet.ttft_p95_ms"),
        ("plane_connected", "nns.fleet.plane_connected"),
        ("plane_ingest_age_s", "nns.fleet.plane_ingest_age_s"),
        ("plane_reconnects", "nns.fleet.plane_reconnects"),
    )

    def _collect(self) -> List[Sample]:
        roll = self.rollup()
        base = {"fleet": self.topic or "all"}
        out: List[Sample] = []
        for key, mname in self._ROLLUP_METRICS:
            assert mname in METRICS, mname  # catalogued (schema lint)
            out.append(Sample(
                mname, dict(base), float(roll.get(key, 0) or 0),
                metric_kind(mname)))
        for t, trow in roll["tenants"].items():
            tl = {**base, "tenant": t or "_"}
            out.append(Sample("nns.fleet.tenant_admitted", dict(tl),
                              trow["admitted"], "counter"))
            out.append(Sample("nns.fleet.tenant_shed", dict(tl),
                              trow["shed"], "counter"))
        for t, b in roll["slo_burn"].items():
            out.append(Sample(
                "nns.fleet.slo_burn", {**base, "tenant": t or "_"},
                b, "gauge"))
        return out
