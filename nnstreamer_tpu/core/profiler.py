"""jax.profiler integration (SURVEY §5.1: the reference delegates tracing
to GstShark/gst-instruments; the TPU-native equivalent is XLA's own
profiler, surfaced through the same kind of element properties).

One process-global trace session (the jax profiler is a singleton):
elements call :func:`trace_start`/:func:`trace_stop` and refcounting keeps
the session alive while any element wants it.  View traces with
TensorBoard or xprof (``trace-dir`` holds the .xplane.pb files).
"""

from __future__ import annotations

import threading
from typing import Optional

from .log import get_logger

log = get_logger("profiler")

_lock = threading.Lock()
_refs = 0
_dir: Optional[str] = None


def trace_start(trace_dir: str) -> bool:
    """Begin (or join) the global profiler trace; returns True if tracing."""
    global _refs, _dir
    with _lock:
        if _refs == 0:
            import jax

            try:
                jax.profiler.start_trace(trace_dir)
            except Exception as e:  # pragma: no cover — profiler unavailable
                log.warning("profiler trace unavailable: %s", e)
                return False
            _dir = trace_dir
        elif trace_dir != _dir:
            log.warning(
                "profiler already tracing to %s; ignoring %s", _dir, trace_dir
            )
        _refs += 1
        return True


def trace_stop() -> None:
    """Drop one trace reference; the session ends at zero."""
    global _refs, _dir
    with _lock:
        if _refs == 0:
            return
        _refs -= 1
        if _refs == 0:
            import jax

            try:
                jax.profiler.stop_trace()
            except Exception as e:  # pragma: no cover
                log.warning("profiler stop failed: %s", e)
            log.info("profiler trace written to %s", _dir)
            _dir = None


def annotate(name: str):
    """Context manager labeling a region in the trace (TraceAnnotation)."""
    import jax

    return jax.profiler.TraceAnnotation(name)
