"""Profilers: the jax/XLA trace session and the incident-time thread
sampler.

**jax.profiler integration** (SURVEY §5.1: the reference delegates
tracing to GstShark/gst-instruments; the TPU-native equivalent is XLA's
own profiler, surfaced through the same kind of element properties).
One process-global trace session (the jax profiler is a singleton):
elements call :func:`trace_start`/:func:`trace_stop` and refcounting
keeps the session alive while any element wants it.  View traces with
TensorBoard or xprof (``trace-dir`` holds the .xplane.pb files).

**Incident-time thread profiler** (`Documentation/observability.md`
"Thread profiler"): a sampling wall-clock profiler over the NAMED
framework threads — segment dispatch workers (named after their head
element), the completion-window ``-reaper``, the ingest-lane ``-stage``
worker, slot-engine pumps, watchdogs.  :func:`profile_threads` samples
``sys._current_frames()`` at ~50 Hz for a bounded window and returns
collapsed top-stacks per thread, so "where did the 86% dispatch tax go"
is answerable from a flight-recorder dump without a chip or
TensorBoard.  The flight recorder (:mod:`~.telemetry`) attaches a
capture to every incident dump; call it directly for on-demand looks at
a live pipeline.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import Counter
from typing import Dict, Optional, Tuple

from .log import get_logger

log = get_logger("profiler")

_lock = threading.Lock()
_refs = 0
_dir: Optional[str] = None


def trace_start(trace_dir: str) -> bool:
    """Begin (or join) the global profiler trace; returns True if tracing."""
    global _refs, _dir
    with _lock:
        if _refs == 0:
            import jax

            try:
                jax.profiler.start_trace(trace_dir)
            except Exception as e:
                log.warning("profiler trace unavailable: %s", e)
                # a failed start can leave the jax singleton half-armed
                # (start_trace raised after claiming the session); reset
                # it so the next trace_start — possibly from a different
                # element with a different dir — enters the refs==0 path
                # against a clean singleton instead of refcounting on
                # top of stale state.  EXCEPT when the failure says the
                # session is already active: that one belongs to someone
                # ELSE (an operator's own TensorBoard capture) — a reset
                # would kill their in-progress trace mid-run.
                if "already" not in str(e).lower():
                    try:
                        jax.profiler.stop_trace()
                    except Exception:  # allow-silent: best-effort reset
                        pass           # of a never-started session
                _dir = None
                return False
            _dir = trace_dir
        elif trace_dir != _dir:
            log.warning(
                "profiler already tracing to %s; ignoring %s", _dir, trace_dir
            )
        _refs += 1
        return True


def trace_stop() -> None:
    """Drop one trace reference; the session ends at zero."""
    global _refs, _dir
    with _lock:
        if _refs == 0:
            return
        _refs -= 1
        if _refs == 0:
            import jax

            try:
                jax.profiler.stop_trace()
            except Exception as e:
                log.warning("profiler stop failed: %s", e)
            log.info("profiler trace written to %s", _dir)
            _dir = None


def trace_active() -> bool:
    """True while any element holds the global trace session open (the
    ``nns.profiler.active`` gauge reads the per-element view via
    ``health_info``; this is the process-wide one)."""
    return _refs > 0


def annotate(name: str):
    """Context manager labeling a region in the trace (TraceAnnotation)."""
    import jax

    return jax.profiler.TraceAnnotation(name)


# ---------------------------------------------------------------------------
# Incident-time thread profiler (sampling, wall-clock, host-side)
# ---------------------------------------------------------------------------
#: thread-name prefixes that are NOT framework threads (library pools,
#: pytest/debugger internals) — the same census rule the test-suite leak
#: check uses: every framework thread is explicitly named
THREAD_IGNORE: Tuple[str, ...] = (
    "MainThread", "Thread-", "ThreadPool", "Dummy", "asyncio", "pydevd",
    "raylet",
)


def framework_thread_names() -> Dict[int, str]:
    """{ident: name} for live framework threads (named, not ignored)."""
    return {
        t.ident: t.name
        for t in threading.enumerate()
        if t.ident is not None and t.is_alive()
        and not t.name.startswith(THREAD_IGNORE)
    }


def _collapse(frame, max_depth: int) -> str:
    """One thread's current stack as a collapsed ``a;b;c`` string,
    outermost first (flamegraph convention), frames as file:func."""
    parts = []
    f = frame
    while f is not None and len(parts) < max_depth:
        code = f.f_code
        parts.append(f"{os.path.basename(code.co_filename)}:{code.co_name}")
        f = f.f_back
    parts.reverse()
    return ";".join(parts)


def profile_threads(duration_s: float = 0.25, hz: float = 50.0,
                    top: int = 5, max_depth: int = 48,
                    include=None) -> Dict:
    """Sample the named framework threads for ``duration_s`` at ``hz``.

    Pure-Python wall-clock sampling via ``sys._current_frames()``: no
    tracing hooks are installed, the profiled threads pay nothing, and a
    thread BLOCKED in a C call (a wedged device sync, a socket read) is
    still visible — its Python stack is parked on the blocking call,
    which is exactly the answer an incident needs.  The CALLING thread
    blocks for the window; keep it off latency-critical paths (the
    flight recorder's rate limit bounds it there).

    Returns ``{duration_s, hz, samples, threads: {name: {samples,
    top_stacks: [{stack, count}, ...]}}}`` — ``stack`` is the collapsed
    ``file:func;file:func;...`` form, outermost first.  ``include``
    restricts to thread names containing any of the given substrings.
    """
    hz = max(1.0, float(hz))
    n = max(1, int(float(duration_s) * hz))
    period = 1.0 / hz
    me = threading.get_ident()
    agg: Dict[str, Counter] = {}
    taken = 0
    t0 = time.perf_counter()
    for i in range(n):
        names = framework_thread_names()
        # two pipelines in one process can both own an element (and
        # thus a streaming thread) named e.g. "f": disambiguate
        # duplicates as "name#<ident>" so a stalled thread's stacks are
        # never blended with a healthy namesake's
        seen: Counter = Counter(names.values())
        frames = sys._current_frames()
        try:
            for ident, name in names.items():
                if ident == me:
                    continue
                if include is not None and not any(
                        s in name for s in include):
                    continue
                frame = frames.get(ident)
                if frame is None:
                    continue
                key = name if seen[name] == 1 else f"{name}#{ident}"
                agg.setdefault(key, Counter())[
                    _collapse(frame, max_depth)] += 1
        finally:
            del frames  # frame objects pin their locals; release now
        taken += 1
        if i + 1 < n:
            time.sleep(period)
    return {
        "duration_s": round(time.perf_counter() - t0, 4),
        "hz": hz,
        "samples": taken,
        "threads": {
            name: {
                "samples": sum(ctr.values()),
                "top_stacks": [
                    {"stack": s, "count": c}
                    for s, c in ctr.most_common(top)
                ],
            }
            for name, ctr in sorted(agg.items())
        },
    }
