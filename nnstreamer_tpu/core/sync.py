"""Multi-stream time-synchronization policies.

Reference: ``gst/nnstreamer/tensor_common.h:62-182`` (enum
``tensor_time_sync_mode``: NOSYNC / SLOWEST / BASEPAD / REFRESH) and the
collect-pads engine ``gst_tensor_time_sync_buffer_from_collectpad``
(``nnstreamer_plugin_api_impl.c:101-533``); behavior documented in
``Documentation/synchronization-policies-at-mux-merge.md``.

Used by the N:1 elements (mux / merge).  The reference implements this over
GstCollectPads; here it is a small pure-Python collator that the threaded
pipeline runtime drives — deterministic and unit-testable without a pipeline.

Policies:

* ``nosync``  — combine one frame per pad in arrival order.
* ``slowest`` — output timestamps follow the slowest pad: a set is emitted at
  the max of the head timestamps; faster pads drop frames older than the base.
* ``basepad`` — option ``"<pad>:<duration>"``: the designated pad drives
  output; other pads contribute their newest frame within ``duration`` seconds
  of the base timestamp (reference option is in nanoseconds; here seconds).
* ``refresh`` — any new frame on any pad triggers output; other pads re-use
  their most recent frame.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Deque, List, Optional, Sequence

from .buffer import TensorFrame

NOSYNC = "nosync"
SLOWEST = "slowest"
BASEPAD = "basepad"
REFRESH = "refresh"
MODES = (NOSYNC, SLOWEST, BASEPAD, REFRESH)


@dataclass
class SyncPolicy:
    mode: str = NOSYNC
    base_pad: int = 0  # basepad only
    window: Optional[float] = None  # basepad tolerance, seconds; None = unlimited

    @classmethod
    def from_string(cls, mode: str, option: str = "") -> "SyncPolicy":
        mode = (mode or NOSYNC).strip().lower()
        if mode not in MODES:
            raise ValueError(f"unknown sync mode {mode!r}")
        if mode == BASEPAD and option:
            pad_s, _, dur_s = option.partition(":")
            return cls(mode, int(pad_s), float(dur_s) if dur_s else None)
        return cls(mode)


def _pts(f: TensorFrame) -> float:
    return f.pts if f.pts is not None else 0.0


class Collator:
    """Collects frames from N pads and emits synchronized frame-sets."""

    def __init__(self, num_pads: int, policy: SyncPolicy):
        if num_pads < 1:
            raise ValueError("need at least one pad")
        self.num_pads = num_pads
        self.policy = policy
        self.queues: List[Deque[TensorFrame]] = [deque() for _ in range(num_pads)]
        self.last: List[Optional[TensorFrame]] = [None] * num_pads
        self.eos = [False] * num_pads
        self._refresh_dirty = [False] * num_pads

    # -- input --------------------------------------------------------------
    def push(self, pad: int, frame: TensorFrame) -> None:
        self.queues[pad].append(frame)
        self._refresh_dirty[pad] = True

    def mark_eos(self, pad: int) -> None:
        self.eos[pad] = True

    @property
    def all_eos(self) -> bool:
        """Whether the combined stream is finished, per policy:

        * SLOWEST — ends when the slowest pad ends (reference semantics:
          stream is over once any pad is EOS with nothing queued).
        * BASEPAD — ends when the base pad is drained.
        * NOSYNC / REFRESH — ends only when every pad is drained (EOS pads
          repeat their last frame while others still flow).
        """
        drained = [e and not q for e, q in zip(self.eos, self.queues)]
        if self.policy.mode == SLOWEST:
            return any(drained)
        if self.policy.mode == BASEPAD:
            return drained[self.policy.base_pad]
        return all(drained)

    # -- output -------------------------------------------------------------
    def collect(self) -> Optional[List[TensorFrame]]:
        """Return one synchronized set of frames (index = pad), or None if
        not ready yet.  Call repeatedly until None to drain."""
        mode = self.policy.mode
        if mode == NOSYNC:
            return self._collect_nosync()
        if mode == SLOWEST:
            return self._collect_slowest()
        if mode == BASEPAD:
            return self._collect_basepad()
        if mode == REFRESH:
            return self._collect_refresh()
        raise AssertionError(mode)

    def _collect_nosync(self) -> Optional[List[TensorFrame]]:
        if not all(self.queues[i] for i in range(self.num_pads) if not self.eos[i]):
            return None
        if not any(self.queues):
            return None
        out = []
        for i, q in enumerate(self.queues):
            if q:
                f = q.popleft()
                self.last[i] = f
            elif self.last[i] is not None:  # EOS pad: repeat last
                f = self.last[i]
            else:
                return None
            out.append(f)
        return out

    def _collect_slowest(self) -> Optional[List[TensorFrame]]:
        active = [i for i in range(self.num_pads) if not (self.eos[i] and not self.queues[i])]
        if not active or not all(self.queues[i] for i in active):
            return None
        base = max(_pts(self.queues[i][0]) for i in active)
        # a frame <= base is superseded once a NEWER frame <= base is queued
        # behind it — keep only the newest candidate per pad (safe eager
        # drop: the outcome can never change)
        for i in active:
            q = self.queues[i]
            while len(q) > 1 and _pts(q[1]) <= base:
                q.popleft()
        # plan the full set before popping anything (no partial consumption).
        # A pad whose head is STALE (< base) with no queued successor and no
        # EOS must wait — a better frame may still arrive (the reference pops
        # the stale head to pad->buffer and returns "need more data",
        # nnstreamer_plugin_api_impl.c:289-327; once a newer head exists the
        # remembered frame is the pad's contribution).  Phase-offset streams
        # therefore emit continuously one set per slowest-pad frame.
        pops = []
        for i in range(self.num_pads):
            q = self.queues[i]
            if i in active and q and _pts(q[0]) <= base:
                if _pts(q[0]) < base and len(q) == 1 and not self.eos[i]:
                    return None
                pops.append(i)
            elif self.last[i] is None:
                return None
        out: List[Optional[TensorFrame]] = [None] * self.num_pads
        for i in range(self.num_pads):
            if i in pops:
                self.last[i] = self.queues[i].popleft()
            out[i] = self.last[i]
        return [f for f in out if f is not None]

    def _collect_basepad(self) -> Optional[List[TensorFrame]]:
        b = self.policy.base_pad
        if not self.queues[b]:
            return None
        base_frame = self.queues[b].popleft()
        self.last[b] = base_frame
        base = _pts(base_frame)
        out: List[Optional[TensorFrame]] = [None] * self.num_pads
        out[b] = base_frame
        for i in range(self.num_pads):
            if i == b:
                continue
            q = self.queues[i]
            # take the newest frame not newer than base+window
            window = self.policy.window if self.policy.window is not None else float("inf")
            picked = None
            while q and _pts(q[0]) <= base + window:
                picked = q.popleft()
                if q and _pts(q[0]) > base:
                    break
            if picked is not None:
                self.last[i] = picked
            if self.last[i] is None:
                # need at least one frame ever seen on every pad
                self.queues[b].appendleft(base_frame)
                return None
            out[i] = self.last[i]
        return [f for f in out if f is not None]

    def _collect_refresh(self) -> Optional[List[TensorFrame]]:
        if not any(self._refresh_dirty):
            return None
        for i, q in enumerate(self.queues):
            while q:
                self.last[i] = q.popleft()
        if any(f is None for f in self.last):
            return None
        self._refresh_dirty = [False] * self.num_pads
        return list(self.last)  # type: ignore[arg-type]
