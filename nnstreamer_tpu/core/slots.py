"""Continuous batching for the generation path: the slot scheduler.

The serving shape of "millions of users" LLM inference: MANY concurrent
autoregressive streams share ONE fixed-width decode batch.  Each live
request occupies a *slot*; the jitted transformer decode scan runs over
the whole slot batch every iteration (``k = min(chunk, min remaining)``
tokens per active slot — per-token dispatch amortized exactly like the
unslotted path), so aggregate token throughput is bound by the token
batch, not by the request count — the roofline view the perf evidence
reports (Documentation/performance.md "Continuous batching").

Mechanics (model halves: ``models/transformer.SlotModel``):

* **join at token boundaries** — a new prompt claims a free slot, its
  pages are reset (only ITS slot is touched), then its prompt is
  prefilled in ``prefill_chunk``-sized pieces INTERLEAVED with the decode
  loop (``prefill_priority`` chunks per decode step), so one long prompt
  never stalls the tokens other streams are owed;
* **leave immediately** — finished, cancelled and deadline-evicted
  streams free their slot at the next token boundary; the idle-slot
  mask keeps the decode step shape-stable, so churn causes ZERO
  retracing (``SlotModel.decode_compiles`` stays at the fixed bucket
  count);
* **per-token deadline QoS** — a stream whose request deadline
  (PR-2 ``DEADLINE_META`` budget, crossed the wire) or per-token pace
  budget (``token_budget_s``) is blown is EVICTED from its slot and
  answered with a typed-expiry final chunk (partial tokens preserved,
  ``evicted="deadline"`` meta) instead of rotting in the batch;
* **priority joins** — free slots go to the highest PR-8 priority class
  first (FIFO within a class), so tenant QoS extends to slot admission.

Threading: the engine runs its own decode pump thread (the PR-6
CompletionWindow reaper discipline) so decode never waits on the
element's mailbox poll; the ELEMENT drains ready chunks on its dispatch
thread via :meth:`pop_ready` (emission and supervision attribution stay
on the pipeline thread), and engine errors re-raise there too.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from .continuity import (
    GOAWAY_META, PREFIX_GRAIN, RESUME_META, prefix_digests, prompt_digest,
)
from .liveness import ThreadBeat
from .log import get_logger
from .resilience import DeviceLostError, DeviceOomError, device_call

log = get_logger("slots")

#: terminal stream states
DONE_STATES = ("done", "evicted", "cancelled", "failed")


def lru_bucket(lru: "OrderedDict", key, build, cap: int):
    """THE bounded compile-bucket discipline (filter _stack_jit_cache,
    PR-3), shared by every chunk-length jit cache — the slot engine's
    prefill/decode buckets AND the unslotted generator's decode chunks
    — so the eviction rule cannot drift between paths.  Returns the
    cached (or freshly built) entry; evicts least-recently-used past
    ``cap`` (evicted lengths simply retrace on next use)."""
    fn = lru.get(key)
    if fn is not None:
        lru.move_to_end(key)
        return fn
    fn = build(key)
    lru[key] = fn
    while len(lru) > cap:
        lru.popitem(last=False)
    return fn


class PrefixEntry:
    """One published grain chunk of a shared prefix: the immutable page
    blob (a COPY — never a view into a live slot) for prompt positions
    ``[index*grain, (index+1)*grain)``, keyed by its chain digest, plus
    the refcount that fences reclamation."""

    __slots__ = (
        "digest", "index", "pages", "tokens", "nbytes", "refs",
        "last_used",
    )

    def __init__(self, digest: str, index: int, pages, tokens: int,
                 nbytes: int, now: float):
        self.digest = digest
        self.index = int(index)
        self.pages = pages          # model-opaque blob (attach interprets)
        self.tokens = int(tokens)
        self.nbytes = int(nbytes)
        self.refs = 0
        self.last_used = now


class PrefixCache:
    """Refcounted shared-prefix page pool (ROADMAP item 4): the KV bytes
    the dominant traffic shape (long shared system prompt + short user
    suffix) keeps recomputing, published ONCE and attached by every
    later stream.

    * keyed by chunk-grain CHAIN digests
      (:func:`~.continuity.prefix_digests`): entry *i* is valid only
      under the exact prefix that produced chunks ``0..i-1``, so pages
      from different prefixes can never alias;
    * **publish** stores copies exported at the grain boundary by the
      prefilling stream (the slot keeps its private pages — eviction of
      a published entry never touches a live slot);
    * **acquire** pins (``refs += 1``) the longest run of consecutive
      cached chunks from index 0; the engine holds the pins for the
      stream's whole slot occupancy and releases them with the slot, so
      *a cached page is never reclaimed under a live reader* — eviction
      (LRU past ``cap_entries``/``cap_bytes``) and :meth:`trim` only
      ever take ``refs == 0`` entries;
    * :meth:`trim` is the FIRST rung of the PR-14 ``nns.mem.*``
      pressure ladder (``Pipeline.enable_memory_monitor``): cached
      prefixes are pure recomputable capacity — the most reclaimable
      bytes on the chip.

    Accounting is exact (the fleet observatory cross-checks integer
    totals): one hit or one miss per ELIGIBLE lookup (a prompt with at
    least one full grain chunk), one publish per entry stored, one
    eviction per entry reclaimed, however it left."""

    def __init__(self, grain: int = PREFIX_GRAIN, cap_entries: int = 256,
                 cap_bytes: int = 0,
                 clock: Callable[[], float] = time.monotonic):
        self.grain = max(1, int(grain))
        self.cap_entries = max(1, int(cap_entries))
        self.cap_bytes = max(0, int(cap_bytes))
        self.clock = clock
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, PrefixEntry]" = OrderedDict()
        self.bytes = 0
        # exact counters (lock-held writes, GIL-atomic reads)
        self.hits = 0
        self.misses = 0
        self.publishes = 0
        self.evictions = 0
        self.hit_tokens = 0   # prefill tokens skipped via attach

    @staticmethod
    def _nbytes(pages) -> int:
        """Byte accounting over a model-opaque page blob (dict/list
        nesting of array-likes; non-arrays count a nominal 8)."""
        n = 0
        stack = [pages]
        while stack:
            x = stack.pop()
            if isinstance(x, dict):
                stack.extend(x.values())
            elif isinstance(x, (list, tuple)):
                stack.extend(x)
            else:
                n += int(getattr(x, "nbytes", 8))
        return n

    def acquire(self, digests: List[str]) -> List[PrefixEntry]:
        """Pin the longest run of consecutive cached chunks from index
        0 for the given chain digests.  Counts ONE hit (+`hit_tokens`)
        when the run is non-empty, else ONE miss.  Callers MUST balance
        with :meth:`release` exactly once."""
        with self._lock:
            run: List[PrefixEntry] = []
            for i, d in enumerate(digests):
                e = self._entries.get(d)
                if e is None or e.index != i:
                    break
                run.append(e)
            if run:
                now = self.clock()
                for e in run:
                    e.refs += 1
                    e.last_used = now
                    self._entries.move_to_end(e.digest)
                self.hits += 1
                self.hit_tokens += sum(e.tokens for e in run)
            else:
                self.misses += 1
            return run

    def release(self, entries: List[PrefixEntry]) -> None:
        with self._lock:
            for e in entries:
                e.refs = max(0, e.refs - 1)

    def contains(self, digest: str) -> bool:
        with self._lock:
            return digest in self._entries

    def publish(self, digest: str, index: int, pages,
                tokens: int) -> bool:
        """Store one exported grain chunk.  False (not stored) when the
        digest is already present or every evictable entry is pinned and
        the caps leave no room — the publisher loses nothing either way
        (its slot keeps its private pages)."""
        nbytes = self._nbytes(pages)
        with self._lock:
            if digest in self._entries:
                return False
            if not self._make_room_locked(nbytes):
                return False
            e = PrefixEntry(
                digest, index, pages, tokens, nbytes, self.clock())
            self._entries[digest] = e
            self.bytes += nbytes
            self.publishes += 1
            return True

    def _make_room_locked(self, incoming: int) -> bool:
        def over() -> bool:
            return (len(self._entries) + 1 > self.cap_entries
                    or (self.cap_bytes > 0
                        and self.bytes + incoming > self.cap_bytes))

        while over():
            victim = next(
                (e for e in self._entries.values() if e.refs == 0), None)
            if victim is None:
                return False  # everything pinned: refuse, never reclaim
            self._evict_locked(victim)
        return True

    def _evict_locked(self, e: PrefixEntry) -> None:
        del self._entries[e.digest]
        self.bytes -= e.nbytes
        self.evictions += 1

    def trim(self) -> int:
        """Reclaim every COLD (``refs == 0``) entry — the memory
        pressure ladder's first rung.  Pinned entries survive by
        construction.  Returns entries freed (the monitor's unit)."""
        with self._lock:
            cold = [e for e in self._entries.values() if e.refs == 0]
            for e in cold:
                self._evict_locked(e)
            return len(cold)

    def clear(self) -> int:
        """Drop EVERYTHING (device-loss remesh: the pages' placements
        died with the mesh).  Only called after every reader was handed
        off — any stale pin is force-released with its entry."""
        with self._lock:
            n = len(self._entries)
            self.evictions += n
            self._entries.clear()
            self.bytes = 0
            return n

    def hot_digests(self, k: int = 8) -> List[str]:
        """Most-recently-used entry digests, truncated for the bounded
        discovery digest (core/fleet.py advertises them so operators
        can see WHICH prefixes a server holds)."""
        with self._lock:
            es = sorted(
                self._entries.values(), key=lambda e: -e.last_used)[:k]
            return [e.digest[:12] for e in es]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "prefix_hits": self.hits,
                "prefix_misses": self.misses,
                "prefix_publishes": self.publishes,
                "prefix_evictions": self.evictions,
                "prefix_entries": len(self._entries),
                "prefix_refs": sum(
                    e.refs for e in self._entries.values()),
                "prefix_bytes": self.bytes,
                "prefix_hit_tokens": self.hit_tokens,
            }


class GenStream:
    """One generation stream: a prompt waiting for / occupying a slot.

    ``frame`` is the source TensorFrame (kept alive so emitted chunks
    inherit its meta — client_id, trace id, tenant — via
    ``with_tensors``); tokens accumulate in ``pending`` until a chunk
    boundary or a terminal event flushes them.
    """

    __slots__ = (
        "sid", "frame", "prompt", "max_new", "chunk", "tenant", "priority",
        "deadline_ts", "token_budget_s", "state", "slot", "prefill_pos",
        "gen", "tok", "pending", "pending_n", "chunk_index", "tokens_out",
        "evict_reason", "submitted_ts", "last_token_ts", "joined_ts",
        # stream continuity (core/continuity.py): what the chunked
        # prefill actually runs over (prompt, or prompt + generated
        # prefix on a RESUME), the checkpoint to restart decode from,
        # and the per-chunk resume state stamped into emitted meta
        "prefill_src", "resume_tok", "resume_gen", "resume_info",
        # shared-prefix cache (PrefixCache): the chain digests of this
        # stream's eligible prefix chunks, the pinned entries it
        # attached (released with the slot), and the next chunk index
        # to consider publishing as prefill crosses grain boundaries
        "prefix_digests", "prefix_entries", "prefix_pub_i",
    )

    def __init__(self, sid: int, frame, prompt, max_new: int, chunk: int,
                 tenant: str = "", priority: int = 3,
                 deadline_ts: Optional[float] = None,
                 token_budget_s: float = 0.0, now: float = 0.0):
        self.sid = sid
        self.frame = frame
        self.prompt = prompt              # np.int32 (1, Tp)
        self.max_new = int(max_new)
        self.chunk = max(1, int(chunk))
        self.tenant = tenant
        self.priority = int(priority)
        self.deadline_ts = deadline_ts    # absolute monotonic or None
        self.token_budget_s = float(token_budget_s)
        self.state = "waiting"            # waiting|prefill|decoding|<DONE>
        self.slot: Optional[int] = None
        self.prefill_pos = 0
        self.gen = 0                      # tokens generated so far
        self.tok = 0                      # last token (host int)
        self.pending: List[Any] = []      # np arrays (1, k) awaiting a chunk
        self.pending_n = 0
        self.chunk_index = 0
        self.tokens_out = 0               # tokens actually emitted
        self.evict_reason: Optional[str] = None
        self.submitted_ts = now
        self.last_token_ts = now
        self.joined_ts: Optional[float] = None
        self.prefill_src = prompt         # prompt (+ prefix[:-1] on resume)
        self.resume_tok = 0               # last prefix token (resume only)
        self.resume_gen = 0               # tokens already delivered (resume)
        self.resume_info: Optional[Dict[str, Any]] = None
        self.prefix_digests: List[str] = []
        self.prefix_entries: List[Any] = []
        self.prefix_pub_i = 0

    @property
    def finished(self) -> bool:
        return self.state in DONE_STATES


class SimSlotModel:
    """Deterministic SIMULATED slot model (the async-sim discipline,
    PR-6): duck-types ``models.transformer.SlotModel`` but replaces the
    transformer with a token recurrence plus TPU-SHAPED step costs —
    every decode step pays a B-INDEPENDENT base (weight streaming +
    dispatch, the memory-bound LLM-decode regime batching amortizes)
    plus a small per-active-slot increment.

    This is what the ``pytest -m perf`` continuous-batching floor and
    the chaos harness drive: the object under test is the SLOT
    SCHEDULER (join/evict correctness, multiplexing win, emission-path
    overhead), not XLA-CPU GEMM scaling, which inverts the real
    accelerator's batch economics at zoo-model sizes.

    Token oracle: token 1 = ``sum(prompt) % vocab``; token j+1 =
    ``(31 * t_j + 17) % vocab`` — exact per-stream accounting is
    checkable without running a model.  The per-slot "pages" are a
    position counter that asserts slot isolation (a write to slot i can
    never touch slot j by construction, and tests pin the counters).
    """

    def __init__(self, slots: int, vocab: int = 997,
                 step_base_ms: float = 1.0, step_per_slot_ms: float = 0.05,
                 prefill_ms_per_token: float = 0.02,
                 sleep=time.sleep,
                 oom_at_step: Optional[int] = None,
                 lost_at_step: Optional[int] = None):
        import numpy as np

        self._np = np
        self.slots = int(slots)
        self.vocab = int(vocab)
        self.step_base_s = step_base_ms * 1e-3
        self.step_per_slot_s = step_per_slot_ms * 1e-3
        self.prefill_s_per_token = prefill_ms_per_token * 1e-3
        self._sleep = sleep
        self.decode_compiles = 0
        self.prefill_compiles = 0
        # deterministic device-resource chaos (the AsyncSim twin knobs):
        # decode ATTEMPT index N raises the typed error exactly once —
        # the attempt counter advances on faulted attempts, so the
        # engine's retry (a fresh attempt) proceeds.  Token sequences
        # are unaffected: the fault fires before any state mutation.
        self.oom_at_step = oom_at_step
        self.lost_at_step = lost_at_step
        self._attempts = 0
        self._pending_fault: Optional[str] = None
        #: simulated device-busy seconds (occupancy evidence)
        self.busy_s = 0.0
        # running prompt-sum per slot: chunked prefill accumulates into
        # it so token 1 covers the WHOLE prompt across chunk boundaries
        self._prefill_carry: Dict[int, int] = {}

    def fail_next(self, kind: str) -> None:
        """Arm the NEXT decode attempt to raise the typed device error
        (``"oom"`` | ``"lost"``), race-free against a running pump —
        the chaos harness's scripted injection point."""
        if kind not in ("oom", "lost"):
            raise ValueError(f"fail_next({kind!r}): want oom|lost")
        self._pending_fault = kind

    def init_cache(self):
        np = self._np
        return {"pos": np.zeros((self.slots,), np.int64)}

    def reset_slot(self, cache, slot):
        cache = {"pos": cache["pos"].copy()}
        cache["pos"][int(slot)] = 0
        self._prefill_carry[int(slot)] = 0
        return cache

    def export_prefix(self, cache, slot, start: int, stop: int):
        """Sim twin of ``SlotModel.export_prefix``: the oracle's only
        per-prefix state is the running prompt sum, so a chunk's "pages"
        are the CUMULATIVE carry at ``stop`` (the engine exports exactly
        at the grain-boundary moment ``prefill_pos == stop``, where the
        live carry covers precisely positions ``[0, stop)``)."""
        del cache, start
        return {"carry": int(self._prefill_carry.get(int(slot), 0)),
                "n": int(stop)}

    def attach_prefix(self, cache, slot, pages_list, n: int):
        """Sim twin of ``SlotModel.attach_prefix``: restore the carry
        from the LAST chunk (cumulative encoding) and set the slot's
        position to ``n`` — indistinguishable from a cold prefill paused
        at ``prefill_pos == n``, so token 1 still covers the whole
        prompt."""
        np = self._np
        cache = {"pos": cache["pos"].copy()}
        cache["pos"][int(slot)] = np.int64(n)
        self._prefill_carry[int(slot)] = int(pages_list[-1]["carry"])
        return cache

    def prefill_fn(self, n: int):
        np = self._np
        self.prefill_compiles += 1

        def fn(params, cache, toks, slot):
            dt = self.prefill_s_per_token * toks.shape[1]
            self._sleep(dt)
            self.busy_s += dt
            cache = {"pos": cache["pos"].copy()}
            cache["pos"][int(slot)] += toks.shape[1]
            tot = (self._prefill_carry.get(int(slot), 0)
                   + int(toks.sum())) % self.vocab
            self._prefill_carry[int(slot)] = tot
            # "logits": one-hot at the oracle's token 1 so pick_first
            # recovers it
            logits = np.zeros((1, self.vocab), np.float32)
            logits[0, tot] = 1.0
            return cache, logits

        return fn

    def pick_first(self, logits):
        np = self._np
        return np.argmax(np.asarray(logits), axis=-1).astype(np.int32)

    def step_token(self, t: int) -> int:
        return (31 * int(t) + 17) % self.vocab

    def decode_fn(self, k: int):
        np = self._np
        self.decode_compiles += 1

        def fn(params, cache, tok, gen, active):
            idx = self._attempts
            self._attempts += 1
            pending, self._pending_fault = self._pending_fault, None
            if pending == "lost" or (
                    self.lost_at_step is not None
                    and idx == self.lost_at_step):
                raise DeviceLostError(
                    "sim: simulated mesh-member death", device_ids=(0,))
            if pending == "oom" or (
                    self.oom_at_step is not None
                    and idx == self.oom_at_step):
                raise DeviceOomError("sim: simulated HBM exhaustion")
            n_active = int(active.sum())
            dt = k * (self.step_base_s
                      + self.step_per_slot_s * n_active)
            self._sleep(dt)
            self.busy_s += dt
            tok = np.asarray(tok).copy()
            gen = np.asarray(gen).copy()
            cache = {"pos": cache["pos"].copy()}
            toks = np.zeros((self.slots, k), np.int32)
            for step in range(k):
                for slot in range(self.slots):
                    if active[slot]:
                        tok[slot] = self.step_token(tok[slot])
                        toks[slot, step] = tok[slot]
                gen = gen + active
            cache["pos"] = cache["pos"] + k * active.astype(np.int64)
            return cache, tok, gen, toks

        return fn


class SlotEngine:
    """Fixed-width continuous-batching scheduler over a
    :class:`~nnstreamer_tpu.models.transformer.SlotModel`.

    Public API (thread-safe): :meth:`submit`, :meth:`cancel`,
    :meth:`pop_ready`, :meth:`pending`, :meth:`wait_progress`,
    :meth:`snapshot`.  ``start``/``stop`` bound the pump thread's life
    to the owning element's.
    """

    #: bound on live prefill jit buckets (chunk-length LRU — same
    #: discipline as the filter's _stack_jit_cache, PR-3)
    JIT_BUCKET_MAX = 16
    #: deadline evictions fire this far BEFORE the request deadline: the
    #: typed-expiry answer must still reach a client whose own timeout
    #: fires exactly AT the deadline (one reply's worth of headroom)
    EVICT_MARGIN_S = 0.05

    def __init__(self, model, params, *, max_seq: int, chunk: int = 8,
                 prefill_chunk: int = 32, prefill_priority: int = 1,
                 token_budget_s: float = 0.0,
                 jit_bucket_max: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic,
                 name: str = "slots",
                 resume_sig: Optional[str] = None,
                 on_device_lost: Optional[Callable[..., Any]] = None,
                 slo=None,
                 prefix_cache: Optional[PrefixCache] = None):
        import numpy as np

        self._np = np
        self.model = model
        self.params = params
        self.slots = int(model.slots)
        self.max_seq = int(max_seq)
        self.chunk = max(1, int(chunk))
        self.prefill_chunk = max(1, int(prefill_chunk))
        self.prefill_priority = max(0, int(prefill_priority))
        self.token_budget_s = float(token_budget_s)
        self.jit_bucket_max = int(jit_bucket_max or self.JIT_BUCKET_MAX)
        self.clock = clock
        self.name = name
        # shared-prefix page pool (None = off: ZERO behavior change —
        # no digesting, no attach, no publish, no snapshot keys).  The
        # grain must land on the chunked-prefill grid, or warm and cold
        # runs would see different chunk boundaries (different XLA
        # programs / float reduction orders) and bit-exactness breaks.
        self.prefix = prefix_cache
        if prefix_cache is not None and (
                prefix_cache.grain % self.prefill_chunk != 0):
            raise ValueError(
                f"prefix grain {prefix_cache.grain} must be a multiple "
                f"of prefill_chunk {self.prefill_chunk} (bit-exactness "
                "requires identical prefill chunk boundaries)")
        # stream continuity (core/continuity.py): with a signature armed,
        # every chunk carries resume state in meta, and a drain hands
        # live streams off as resumable GOAWAY final chunks instead of
        # waiting them out; None = legacy engine (no stamping, drains
        # let streams finish)
        self.resume_sig = resume_sig
        self._goaway = False
        # degrade-don't-die (core/resilience.py device taxonomy): the
        # element-supplied recovery hook for a lost mesh member —
        # ``on_device_lost(err) -> (model, params) | None`` rebuilds the
        # model on the surviving devices (None = the model recovered in
        # place, e.g. the sim twin).  Without a hook a lost device is a
        # sticky engine error (supervision restart rebuilds the element).
        self.on_device_lost = on_device_lost
        # per-stream SLO accounting (telemetry.SloTracker, engine side):
        # one TTFT stamp at the first-token pick, one record_n per
        # decode scan, one counter per terminal outcome — all on the
        # pump thread (the tracker's single-writer contract); None =
        # zero cost everywhere
        self.slo = slo
        # background-thread liveness: the pump beats once per loop —
        # a pump with pending work and a stale beat is WEDGED (stuck in
        # a device call), which the sticky pop_ready error can never
        # surface because the thread never returns
        self.heartbeat = ThreadBeat(f"{name}-slots", clock=clock)

        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)       # pump wakeups
        self._progress = threading.Condition(self._lock)   # consumer waits
        self._waiting: List[GenStream] = []
        self._occupants: List[Optional[GenStream]] = [None] * self.slots
        self._ready: List[Tuple[int, Any]] = []  # (pad, TensorFrame) outs
        self._streams: Dict[int, GenStream] = {}  # live (non-terminal)
        self._sid = 0
        self._error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

        # device state (pump-thread-private after start)
        self._cache = None
        self._tok_vec = None
        self._gen_vec = None
        # chunk-length jit buckets, LRU-bounded (filter _stack_jit_cache
        # discipline): one per distinct prefill piece / decode scan length
        self._prefill_lru: "OrderedDict[int, Any]" = OrderedDict()
        self._decode_lru: "OrderedDict[int, Any]" = OrderedDict()

        # exact accounting (lock-held writes, GIL-atomic reads)
        self.joins = 0
        self.completions = 0
        self.evictions = 0
        self.cancellations = 0
        self.decode_steps = 0
        self.prefill_chunks = 0
        self.tokens_total = 0
        self.tokens_per_step = 0.0  # EWMA of active slots per decode step
        self.resumes = 0            # streams joined via a RESUME request
        self.goaway_evicted = 0     # live streams handed off on drain
        # device-resource resilience accounting (exact; the chaos e2e
        # and the registry read these)
        self.oom_retries = 0        # device steps retried after an OOM
        self.oom_sheds = 0          # slots shed (resumably) to relieve HBM
        self.device_lost = 0        # lost-device events survived
        self.device_lost_evicted = 0  # live streams handed off on loss
        self.remeshes = 0           # models rebuilt on surviving devices

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        np = self._np
        self._stop.clear()
        self._error = None
        self._goaway = False
        self._cache = self.model.init_cache()
        # engine-owned state vectors are HOST numpy (model-agnostic: the
        # jax halves convert at the jit boundary — (S,) ints, negligible
        # — and sim models consume them directly)
        self._tok_vec = np.zeros((self.slots,), np.int32)
        self._gen_vec = np.zeros((self.slots,), np.int32)
        self._thread = threading.Thread(
            target=self._pump, name=f"{self.name}-slots", daemon=True)
        self.heartbeat.bind(self._thread)
        self.heartbeat.beat()
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._work:
            self._work.notify_all()
            self._progress.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=30.0)
            self._thread = None
        with self._lock:
            abandoned = len(self._streams)  # waiting ones are members too
            if abandoned:
                log.warning(
                    "%s: engine stopped with %d stream(s) abandoned",
                    self.name, abandoned)
            if self.prefix is not None:
                for s in self._streams.values():
                    self._release_prefix(s)
            self._waiting.clear()
            self._streams.clear()
            self._occupants = [None] * self.slots
            self._ready.clear()
        self._cache = None
        self._prefill_lru.clear()
        self._decode_lru.clear()

    # -- submission / cancellation -----------------------------------------
    def submit(self, frame, prompt, max_new: int, chunk: int,
               tenant: str = "", priority: int = 3,
               deadline_ts: Optional[float] = None,
               resume: Optional[Dict[str, Any]] = None) -> GenStream:
        """Queue one prompt for a slot.  ``prompt`` is host int32
        (1, Tp), already validated against ``max_seq`` by the caller.

        ``resume`` = ``{"prefix": (1, R) int32, "tokens_done": R}``
        joins a CHECKPOINTED stream instead of a fresh one: the chunked
        prefill runs over prompt + prefix[:-1], decode restarts from the
        prefix's last token at absolute step R (the per-step sampling
        key folds at the absolute index, so the remaining tokens are
        bit-identical to an uninterrupted run), and emitted
        ``tokens_done`` / ``chunk_index`` continue from R.  The caller
        validated signature/digest/shape; R == 0 degrades to a fresh
        join (full replay, client-side dedupe owns the overlap)."""
        np = self._np
        with self._lock:
            if self._error is not None:
                raise self._error
            self._sid += 1
            s = GenStream(
                self._sid, frame, prompt, max_new, chunk,
                tenant=tenant, priority=priority, deadline_ts=deadline_ts,
                token_budget_s=self.token_budget_s, now=self.clock(),
            )
            if self.resume_sig is not None:
                s.resume_info = {
                    "v": 1, "sig": self.resume_sig,
                    "digest": prompt_digest(prompt), "chunk": int(s.chunk),
                }
            if resume is not None:
                self.resumes += 1
                r = int(resume.get("tokens_done", 0))
                if r > 0:
                    prefix = np.asarray(resume["prefix"], dtype=np.int32)
                    s.prefill_src = (
                        np.concatenate([prompt, prefix[:, :r - 1]], axis=1)
                        .astype(np.int32) if r > 1 else prompt)
                    s.resume_tok = int(prefix[0, r - 1])
                    s.resume_gen = r
                    s.tokens_out = r
                    s.chunk_index = r // s.chunk
            self._streams[s.sid] = s
            self._waiting.append(s)
            self._work.notify_all()
            return s

    def begin_goaway(self) -> None:
        """Drain handoff (rolling restart): from the next token boundary
        on, every live stream — decoding, prefilling, or still waiting —
        is flushed with a RESUMABLE final chunk (partial tokens +
        resume state + the ``goaway`` marker) and its slot freed, so the
        client migrates it to a healthy server and the serversrc's
        drain completes as soon as the handoffs are delivered.  No-op on
        a legacy engine without a resume signature: a handoff chunk the
        client cannot resume would silently truncate the stream."""
        if self.resume_sig is None:
            log.warning(
                "%s: drain without resume state armed — live streams "
                "will finish in place instead of migrating", self.name)
            return
        with self._work:
            self._goaway = True
            self._work.notify_all()

    def end_goaway(self) -> None:
        """Rescind a drain handoff (the resize rollback path: the
        replacement model failed to build, so this engine keeps
        serving).  Streams already flushed stay handed off — their
        clients resume them here or elsewhere; new joins stop being
        swept from the next boundary on."""
        with self._work:
            self._goaway = False

    #: cumulative ledger counters that survive an in-place engine
    #: rebuild (autoscale resize): the server's lifetime accounting —
    #: digests and the fleet observatory's exactness ride on these
    #: never moving backwards
    _LEDGER_ATTRS = (
        "joins", "completions", "evictions", "cancellations",
        "decode_steps", "prefill_chunks", "tokens_total", "resumes",
        "goaway_evicted", "oom_retries", "oom_sheds", "device_lost",
        "device_lost_evicted", "remeshes",
    )

    def adopt_ledger(self, other: "SlotEngine") -> None:
        """Carry ``other``'s cumulative counters into this engine (call
        before :meth:`start`).  A slot-width resize replaces the engine
        but not the SERVER — its digest counters must stay monotonic or
        the observatory's exact fleet totals would lose the pre-resize
        history."""
        for attr in self._LEDGER_ATTRS:
            setattr(self, attr, getattr(other, attr))
        self.tokens_per_step = other.tokens_per_step

    def cancel(self, sid: Optional[int] = None,
               client_id: Optional[int] = None) -> bool:
        """Cancel by stream id or by the source frame's client_id meta
        (the serversink's client-gone feedback).  The slot frees at the
        next token boundary; no further chunks are emitted."""
        with self._lock:
            for s in list(self._streams.values()):
                if s.finished:
                    continue  # reaped at the next boundary; never recount
                if (sid is not None and s.sid == sid) or (
                        client_id is not None
                        and s.frame.meta.get("client_id") == client_id):
                    s.state = "cancelled"
                    self.cancellations += 1
                    self._work.notify_all()
                    return True
        return False

    # -- consumer side (element dispatch thread) ----------------------------
    def pop_ready(self) -> List[Tuple[int, Any]]:
        """Drain ready chunk frames (FIFO).  Re-raises any pump-thread
        error HERE, so supervision attributes it to the element call.
        The error is STICKY: a dead pump must keep failing loudly (and
        keep refusing submits) — a restart re-opens the element and
        builds a fresh engine."""
        with self._lock:
            if self._error is not None and not self._ready:
                raise self._error
            out, self._ready = self._ready, []
            return out

    def pending(self) -> int:
        """Logical frames parked in the engine (``pending_frames`` hook:
        scheduler fast-poll + drain/stop accounting): live streams
        (``_streams`` already includes the waiting ones) plus
        undelivered ready chunks."""
        with self._lock:
            return len(self._streams) + len(self._ready)

    def idle(self) -> bool:
        with self._lock:
            return not self._streams and not self._ready

    def wait_progress(self, timeout: float = 0.1) -> None:
        """Block the caller until the pump makes progress (EOS flush)."""
        with self._progress:
            if self._ready or self._error is not None:
                return
            self._progress.wait(timeout)

    # -- accounting ---------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            occupied = sum(1 for s in self._occupants if s is not None)
            snap = {
                "gen_slots": self.slots,
                "gen_occupied": occupied,
                "gen_waiting": len(self._waiting),
                "gen_joins": self.joins,
                "gen_completed": self.completions,
                "gen_evicted": self.evictions,
                "gen_cancelled": self.cancellations,
                "gen_tokens": self.tokens_total,
                "gen_decode_steps": self.decode_steps,
                "gen_prefill_chunks": self.prefill_chunks,
                "gen_tokens_per_step": round(self.tokens_per_step, 3),
                "gen_jit_buckets": (
                    len(self._prefill_lru) + len(self._decode_lru)),
                "gen_decode_compiles": self.model.decode_compiles,
                "gen_resumes": self.resumes,
                "gen_goaway_evicted": self.goaway_evicted,
                "gen_oom_retries": self.oom_retries,
                "gen_oom_sheds": self.oom_sheds,
                "gen_device_lost": self.device_lost,
                "gen_device_lost_evicted": self.device_lost_evicted,
                "gen_remeshes": self.remeshes,
            }
        # armed cache only: with the cache off the snapshot is
        # byte-identical to the pre-prefix engine (zero behavior change)
        if self.prefix is not None:
            snap.update(self.prefix.snapshot())
        return snap

    # -- pump internals -----------------------------------------------------
    def _prefill_fn(self, n: int):
        return lru_bucket(
            self._prefill_lru, n, self.model.prefill_fn,
            self.jit_bucket_max)

    def _decode_fn(self, k: int):
        return lru_bucket(
            self._decode_lru, k, self.model.decode_fn,
            self.jit_bucket_max)

    def _take(self, s: GenStream, n: int):
        """Slice the first ``n`` pending tokens off the stream's buffer
        (lock held)."""
        np = self._np
        buf = (s.pending[0] if len(s.pending) == 1
               else np.concatenate(s.pending, axis=1))
        piece = buf[:, :n]
        rest = buf[:, n:]
        s.pending = [rest] if rest.shape[1] else []
        s.pending_n = buf.shape[1] - n
        return piece

    def _emit_frame(self, s: GenStream, toks, final: bool,
                    extra_meta: Optional[Dict[str, Any]] = None) -> None:
        """Emit one chunk frame (lock held).  ``toks`` may be None for
        a terminal answer with nothing pending (eviction at a chunk
        boundary / never-joined stream): the stream still gets its
        FINAL answer as a tensor-LESS frame — the wire carries
        zero-tensor frames, while a (1, 0) tensor it would refuse."""
        np = self._np
        if toks is not None:
            s.tokens_out += toks.shape[1]
            tensors = [toks.astype(np.int32)]
        else:
            tensors = []
        out = s.frame.with_tensors(tensors)
        out.meta.update(
            stream_seq=s.frame.seq, chunk_index=s.chunk_index,
            tokens_done=s.tokens_out, final=bool(final),
        )
        if s.resume_info is not None:
            # stream continuity: every chunk is a checkpoint — the
            # client can rebuild the stream from its accumulated tokens
            # plus this state on ANY server with a matching signature
            out.meta[RESUME_META] = s.resume_info
        if extra_meta:
            out.meta.update(extra_meta)
        s.chunk_index += 1
        self._ready.append((0, out))
        self._progress.notify_all()

    def _emit_boundary(self, s: GenStream) -> None:
        """Emit EXACTLY chunk-sized pieces (lock held) — identical
        chunking to the unslotted path, whatever the scan length was."""
        while s.pending_n >= s.chunk:
            self._emit_frame(s, self._take(s, s.chunk), final=False)

    def _emit_terminal(self, s: GenStream,
                       extra_meta: Optional[Dict[str, Any]] = None
                       ) -> None:
        """Terminal flush (lock held): full chunks first, then the tail
        as the FINAL frame (exactly the unslotted tail semantics)."""
        while s.pending_n > s.chunk:
            self._emit_frame(s, self._take(s, s.chunk), final=False)
        self._emit_frame(
            s, self._take(s, s.pending_n) if s.pending_n else None,
            final=True, extra_meta=extra_meta)

    def _release_prefix(self, s: GenStream) -> None:
        """Unpin the stream's attached prefix entries (exactly once:
        the list empties).  The pin spans the WHOLE slot occupancy —
        that is the refcount contract ("never reclaimed under a live
        reader"), not merely the attach moment."""
        if self.prefix is not None and s.prefix_entries:
            self.prefix.release(s.prefix_entries)
            s.prefix_entries = []

    def _free_slot(self, s: GenStream) -> None:
        """Release the stream's slot (lock held): pages become reusable
        without touching neighbors; the idle mask clears outside."""
        self._release_prefix(s)
        if s.slot is not None:
            self._occupants[s.slot] = None
        self._streams.pop(s.sid, None)

    def _finish(self, s: GenStream, state: str,
                extra_meta: Optional[Dict[str, Any]] = None) -> None:
        s.state = state
        if state == "done":
            self.completions += 1
            self._slo_stream(s, "good")
            self._emit_terminal(s)
        elif state == "evicted":
            self.evictions += 1
            # typed expiry (deadline/pace): the SLO ledger classifies
            # it as expired, never goodput
            self._slo_stream(s, "expired")
            self._emit_terminal(s, extra_meta=extra_meta or {})
        # cancelled: the consumer is gone — nothing to emit
        self._free_slot(s)

    def _slo_stream(self, s: GenStream, outcome: str) -> None:
        if self.slo is not None:
            self.slo.note_stream(s.tenant, outcome)

    def _sweep_deadlines(self, now: float) -> None:
        """Evict streams whose request deadline or per-token budget is
        blown; expire waiting streams that died in the queue (lock
        held).  The typed-expiry chunk preserves partial tokens."""
        for s in list(self._streams.values()):
            if s.finished:
                continue
            if not (s.deadline_ts is not None
                    and now >= s.deadline_ts - self.EVICT_MARGIN_S):
                continue
            if s.state == "waiting":
                try:
                    self._waiting.remove(s)
                except ValueError:
                    pass
            self._evict(s, "deadline")

    def _evict(self, s: GenStream, reason: str) -> None:
        """Typed-expiry eviction (lock held): partial tokens flush with
        the eviction meta, the slot frees at this boundary."""
        s.evict_reason = reason
        self._finish(s, "evicted", extra_meta={
            "evicted": reason, "deadline_expired": True,
        })
        log.warning(
            "%s: stream %d evicted (%s) after %d token(s)",
            self.name, s.sid, reason, s.tokens_out)

    def _handoff_one(self, s: GenStream, reason: str) -> None:
        """Flush ONE live stream as a resumable handoff final chunk and
        free its slot (lock held).  A MIGRATION, not a failure: no
        ``deadline_expired`` marker (the client must not count a blown
        budget), partial tokens ride the final chunk, and the resume
        state on it lets the client continue bit-identically elsewhere.
        On a legacy engine (no resume signature) the chunk still closes
        the stream typed — truncation is loud, never a poisoned frame."""
        if s.state == "waiting":
            try:
                self._waiting.remove(s)
            except ValueError:
                pass
        s.state = "evicted"
        s.evict_reason = reason
        extra = {"evicted": reason}
        if self.resume_sig is not None:
            extra[GOAWAY_META] = True  # client migrates; tokens survive
        self._emit_terminal(s, extra_meta=extra)
        self._free_slot(s)

    def _sweep_goaway(self) -> None:
        """Drain handoff (lock held): flush EVERY live stream with a
        resumable GOAWAY final chunk and free its slot.  Runs every
        boundary while draining, so streams admitted just before the
        drain hand off too."""
        for s in list(self._streams.values()):
            if s.finished:
                continue
            self._handoff_one(s, "goaway")
            self.goaway_evicted += 1
            log.info(
                "%s: stream %d handed off on drain after %d token(s)",
                self.name, s.sid, s.tokens_out)

    # -- device-resource resilience (degrade, don't die) ---------------------
    def _device_step(self, fn, *args):
        """Every model call of the pump funnels through the shared
        classification boundary (``resilience.device_call``: the
        deterministic ``device.oom`` / ``device.lost`` sites plus
        raw-runtime-error typing) — the pump's recovery ladder keys on
        types, never on XLA status strings."""
        return device_call(fn, *args)

    def _handle_oom(self) -> None:
        """HBM exhaustion mid-step: shed the LOWEST-priority occupant as
        a resumable continuity chunk (its tokens survive — the client
        migrates the stream), freeing its slot's KV pages, then let the
        failed step retry on the smaller active set.  Never a
        restart-budget burn, never a poisoned frame."""
        with self._lock:
            self.oom_retries += 1
            live = [
                s for s in self._occupants
                if s is not None and not s.finished
            ]
            if not live:
                return  # nothing held; the bare retry is the relief
            victim = min(
                live,
                key=lambda s: (s.priority, -(s.joined_ts or 0.0)),
            )
            self.oom_sheds += 1
            self._handoff_one(victim, "oom")
            log.warning(
                "%s: device OOM — shed stream %d (priority %d, %d "
                "token(s) safe) and retrying the step",
                self.name, victim.sid, victim.priority, victim.tokens_out)

    def _recover_donated_cache(self) -> None:
        """Donation invalidates at DISPATCH, not at success: on a real
        (non-CPU) backend the decode/prefill jits donate the KV cache,
        so the step that just OOMed may have consumed it — retrying
        with deleted buffers would raise an UNTYPED "Array has been
        deleted" and kill the pump with every remaining stream.  When
        the cache died with the step, every occupant's device context
        is gone: hand ALL live streams off as resumable continuity
        chunks (resume re-prefills from prompt+tokens — bit-exact) and
        re-init device state clean.  No-op on the sim twin and CPU,
        where nothing donates."""
        try:
            import jax

            leaves = jax.tree_util.tree_leaves(self._cache)
        except Exception:  # noqa: BLE001 — sim twin / no jax
            return
        if not any(
                getattr(leaf, "is_deleted", lambda: False)()
                for leaf in leaves):
            return
        shed = 0
        with self._lock:
            for s in list(self._streams.values()):
                if s.finished:
                    continue
                self._handoff_one(s, "oom")
                self.oom_sheds += 1
                shed += 1
        self._reset_device_state()
        log.warning(
            "%s: donated KV cache died with the OOMed step — %d "
            "stream(s) handed off resumable, cache re-initialized",
            self.name, shed)

    def _reset_device_state(self, clear_jit_lrus: bool = False) -> None:
        """Re-init the engine's per-device decode state clean (fresh KV
        cache, zeroed token/progress vectors) after every occupant was
        handed off — shared by the donated-cache OOM recovery and the
        device-loss rebuild so the two paths cannot drift.
        ``clear_jit_lrus`` additionally drops the compiled prefill/
        decode programs (a REPLACEMENT model invalidates them; a cache
        re-init on the same model does not)."""
        np = self._np
        self._cache = self.model.init_cache()
        self._tok_vec = np.zeros((self.slots,), np.int32)
        self._gen_vec = np.zeros((self.slots,), np.int32)
        if clear_jit_lrus:
            self._prefill_lru.clear()
            self._decode_lru.clear()
            # a REPLACEMENT model invalidates published pages too (their
            # device placements died with the mesh); every reader was
            # handed off above, so nothing is pinned
            if self.prefix is not None:
                dropped = self.prefix.clear()
                if dropped:
                    log.warning(
                        "%s: dropped %d cached prefix entr(ies) with "
                        "the replaced model", self.name, dropped)

    def _handle_device_lost(self, err: DeviceLostError) -> None:
        """A mesh member died under the batch: hand EVERY live stream
        off with resume state (exactly the drain contract — clients
        migrate them), then rebuild the model on the surviving devices
        via the element's ``on_device_lost`` hook and keep serving
        degraded.  Without a hook the loss is sticky (supervision
        restart rebuilds the element)."""
        handed = 0
        with self._lock:
            self.device_lost += 1
            for s in list(self._streams.values()):
                if s.finished:
                    continue
                self._handoff_one(s, "device_lost")
                self.device_lost_evicted += 1
                handed += 1
        hook = self.on_device_lost
        if hook is None:
            raise err
        replacement = hook(err)  # raises = unrecoverable -> sticky error
        with self._lock:
            if replacement is not None:
                self.model, self.params = replacement
            self.remeshes += 1
        # every slot was freed above: device state re-inits clean on
        # the replacement model (compile buckets retrace on demand)
        self._reset_device_state(clear_jit_lrus=True)
        log.warning(
            "%s: device lost (%s) — %d stream(s) handed off, model "
            "rebuilt on survivors (remesh #%d)",
            self.name, err, handed, self.remeshes)

    def _reap_cancelled(self) -> None:
        """Free slots of streams cancelled since the last boundary and
        drop cancelled entries still waiting (lock held).  SLO
        classification happens HERE (pump thread — the tracker's
        single-writer contract), exactly once per cancelled stream
        (``_free_slot`` removes it from ``_streams``)."""
        self._waiting = [w for w in self._waiting if w.state != "cancelled"]
        for s in list(self._streams.values()):
            if s.state == "cancelled":
                self._slo_stream(s, "evicted")
                self._free_slot(s)

    def _join_waiting(self, now: float) -> List[GenStream]:
        """Assign free slots to waiting streams — highest PR-8 priority
        class first, FIFO within a class (lock held).  Returns the
        joined streams (their pages reset OUTSIDE the lock)."""
        joined = []
        free = [i for i, oc in enumerate(self._occupants) if oc is None]
        if not free or not self._waiting:
            return joined
        order = sorted(
            range(len(self._waiting)),
            key=lambda i: (-self._waiting[i].priority, i),
        )
        winners = sorted(order[: len(free)])  # FIFO among the admitted
        for slot, wi in zip(free, winners):
            s = self._waiting[wi]
            s.slot = slot
            s.state = "prefill"
            s.joined_ts = now
            s.last_token_ts = now
            self._occupants[slot] = s
            self.joins += 1
            joined.append(s)
        taken = set(winners)
        self._waiting = [
            w for i, w in enumerate(self._waiting) if i not in taken
        ]
        return joined

    def _pump(self) -> None:
        try:
            self._pump_loop()
        except BaseException as e:  # noqa: BLE001 — thread boundary
            with self._lock:
                self._error = e
                self._progress.notify_all()
            if not self._stop.is_set():
                log.exception("%s: slot pump failed", self.name)

    def _pump_loop(self) -> None:
        np = self._np

        while not self._stop.is_set():
            self.heartbeat.beat()
            with self._work:
                self._reap_cancelled()
                if self._goaway:
                    self._sweep_goaway()
                self._sweep_deadlines(self.clock())
                joined = self._join_waiting(self.clock())
                have_prefill = any(
                    s is not None and s.state == "prefill"
                    for s in self._occupants)
                have_decode = any(
                    s is not None and s.state == "decoding"
                    for s in self._occupants)
                if not (joined or have_prefill or have_decode):
                    self._work.wait(0.05)
                    continue

            # ---- prefill phase: while decoding, up to prefill_priority
            # chunks interleave per scan (a long prompt never stalls
            # live streams for more than that); with the decode batch
            # EMPTY there is nothing to protect — run every pending
            # joiner's next chunk so the batch fills immediately
            prefilling = [
                s for s in self._occupants
                if s is not None and s.state == "prefill"
                and not s.finished
            ]
            budget = (self.prefill_priority if have_decode
                      else max(1, len(prefilling)))
            try:
                for s in prefilling:
                    if budget <= 0:
                        break
                    budget -= 1
                    self._prefill_one(s)
            except DeviceOomError:
                # prefill state is re-entrant (prefill_pos advanced only
                # on success): shed a slot and re-run next iteration
                self._handle_oom()
                self._recover_donated_cache()
                continue
            except DeviceLostError as e:
                self._handle_device_lost(e)
                continue

            # ---- decode phase: k tokens for every active slot in ONE
            # lax.scan dispatch (k = min(chunk, min remaining), so every
            # stream completes exactly at a scan boundary and joins/
            # leaves happen at token boundaries)
            with self._lock:
                decoding = [
                    s for s in self._occupants
                    if s is not None and s.state == "decoding"
                    and not s.finished
                ]
            if not decoding:
                continue
            k = min(
                self.chunk,
                min(s.max_new - s.gen for s in decoding),
            )
            k = max(1, k)
            active = np.zeros((self.slots,), np.int32)
            for s in decoding:
                active[s.slot] = 1
            try:
                self._cache, tok, gen, toks = self._device_step(
                    self._decode_fn(k),
                    self.params, self._cache, self._tok_vec,
                    self._gen_vec, active,
                )
            except DeviceOomError:
                # the step raised before any state assignment: shed the
                # lowest-priority slot (its tokens survive as a
                # resumable chunk) and retry on the smaller batch
                self._handle_oom()
                self._recover_donated_cache()
                continue
            except DeviceLostError as e:
                self._handle_device_lost(e)
                continue
            # materialize BEFORE emission: a yielded token must EXIST,
            # not merely be dispatched (generator element contract)
            toks_host = np.asarray(toks)  # (slots, k)
            # np.array (not asarray): a jax result view is read-only and
            # prefill writes per-slot entries in place
            self._tok_vec = np.array(tok, dtype=np.int32)
            self._gen_vec = np.array(gen, dtype=np.int32)
            now = self.clock()
            with self._lock:
                self.decode_steps += 1
                self.tokens_total += k * len(decoding)
                a = 0.2  # EWMA horizon ~ last 5 scans
                self.tokens_per_step = (
                    len(decoding) if self.decode_steps == 1
                    else (1 - a) * self.tokens_per_step + a * len(decoding)
                )
                for s in decoding:
                    if s.finished:  # cancelled mid-scan: tokens discarded
                        continue
                    row = toks_host[s.slot:s.slot + 1, :]  # (1, k)
                    s.tok = int(row[0, -1])
                    s.gen += k
                    # per-token pace QoS: the scan's OWN per-token rate
                    # against the stream's budget — a stream decoding
                    # slower than its pace is evicted (tokens from this
                    # scan are preserved in the typed-expiry flush)
                    pace_blown = (
                        s.token_budget_s > 0.0
                        and (now - s.last_token_ts) / k > s.token_budget_s
                    )
                    # SLO per-token inter-arrival: the scan's k tokens
                    # as k observations of the same pace — one bucket
                    # increment, reusing the pace sweep's clock reads
                    if self.slo is not None:
                        self.slo.note_tokens(
                            s.tenant, max(0.0, now - s.last_token_ts), k)
                    s.last_token_ts = now
                    s.pending.append(row.astype(np.int32))
                    s.pending_n += k
                    if s.gen >= s.max_new:
                        self._finish(s, "done")
                    elif pace_blown:
                        self._evict(s, "token_budget")
                    else:
                        self._emit_boundary(s)

    # -- shared-prefix cache (attach on join, publish at boundaries) --------
    def _attach_prefix(self, s: GenStream, slot: int) -> None:
        """First-touch lookup (pump thread, right after the slot reset):
        digest the prefill source at grain boundaries, pin the longest
        cached run, and write its pages into the slot — prefill then
        starts at the first uncached token instead of token 0.

        The attach is capped at ``tp - 1`` chunks' worth so at least the
        final prompt token always prefills (its logits feed the
        unchanged token-1 pick).  RESUME joins share the path: their
        ``prefill_src`` starts with the same prompt bytes, so a resumed
        stream landing on a warm server skips the prefix too — and on a
        cache-COLD server simply prefills everything, bit-identically
        (the cache changes WHERE prefill starts, never what any chunk
        computes)."""
        pc = self.prefix
        tp = int(s.prefill_src.shape[1])
        max_chunks = (tp - 1) // pc.grain
        if max_chunks <= 0:
            return  # too short to share: neither a hit nor a miss
        s.prefix_digests = prefix_digests(
            s.prefill_src, pc.grain)[:max_chunks]
        entries = pc.acquire(s.prefix_digests)
        s.prefix_pub_i = len(entries)
        if not entries:
            return
        n = sum(e.tokens for e in entries)
        self._cache = self.model.attach_prefix(
            self._cache, slot, [e.pages for e in entries], n)
        s.prefix_entries = entries
        s.prefill_pos = n

    def _publish_prefix(self, s: GenStream, slot: int) -> None:
        """After each prefill chunk: when ``prefill_pos`` lands exactly
        on the next unpublished grain boundary, export that chunk's
        pages (a copy — donation-safe) and publish them under its chain
        digest.  The boundary moment is guaranteed to occur for every
        eligible chunk because the grain is a prefill_chunk multiple
        (and the sim twin's cumulative carry is only correct AT the
        boundary)."""
        pc = self.prefix
        g = pc.grain
        while s.prefix_pub_i < len(s.prefix_digests):
            i = s.prefix_pub_i
            if (i + 1) * g != s.prefill_pos:
                return  # boundary not (yet) reached this chunk
            d = s.prefix_digests[i]
            if not pc.contains(d):
                pages = self.model.export_prefix(
                    self._cache, slot, i * g, (i + 1) * g)
                pc.publish(d, i, pages, g)
            s.prefix_pub_i += 1

    def _prefill_one(self, s: GenStream) -> None:
        """One chunked-prefill step for a joining stream: reset pages on
        first touch, run one chunk, pick token 1 when the prompt is
        done.  Device work runs OUTSIDE the lock.

        RESUME joins prefill ``prefill_src`` = prompt + generated
        prefix[:-1] through the SAME buckets — the cache after the
        prefill is bit-identical to the incremental decode that built
        it on the dead server — then skip the pick entirely: the next
        decode input is the prefix's LAST token at absolute step
        ``resume_gen``, both known from the checkpoint."""
        np = self._np

        slot = np.int32(s.slot)
        if s.prefill_pos == 0:
            self._cache = self.model.reset_slot(self._cache, slot)
            if self.prefix is not None:
                self._attach_prefix(s, int(s.slot))
                if s.prefill_pos >= s.prefill_src.shape[1]:
                    # defensive: attach is capped at tp-1, so the final
                    # prompt token (whose logits pick token 1) always
                    # prefills — this branch is unreachable by design
                    raise AssertionError(
                        "prefix attach covered the whole prompt")
        tp = s.prefill_src.shape[1]
        n = min(self.prefill_chunk, tp - s.prefill_pos)
        toks = s.prefill_src[:, s.prefill_pos:s.prefill_pos + n].astype(
            np.int32)
        self._cache, logits = self._device_step(
            self._prefill_fn(n), self.params, self._cache, toks, slot)
        s.prefill_pos += n
        if self.prefix is not None:
            self._publish_prefix(s, int(s.slot))
        with self._lock:
            self.prefill_chunks += 1
        if s.prefill_pos < tp:
            return
        if s.resume_gen:
            # checkpointed restart: no pick, no token-1 emission — the
            # client already holds tokens 1..resume_gen
            self._tok_vec[s.slot] = s.resume_tok
            self._gen_vec[s.slot] = s.resume_gen
            now = self.clock()
            with self._lock:
                if s.finished:  # cancelled/handed off during prefill
                    return
                s.tok = s.resume_tok
                s.gen = s.resume_gen
                s.last_token_ts = now
                if s.resume_gen >= s.max_new:
                    self._finish(s, "done")  # defensive: nothing left
                else:
                    s.state = "decoding"
            return
        # prompt fully prefilled: pick token 1 (raw gen_seed key — the
        # exact pick the unslotted prefill applies)
        t1 = self.model.pick_first(logits)
        t1_host = int(np.asarray(t1)[0])
        self._tok_vec[s.slot] = t1_host
        self._gen_vec[s.slot] = 1
        now = self.clock()
        # SLO TTFT: the promised one-stamp-per-first-token — resumed
        # streams skip it above (their first token predates this server)
        if self.slo is not None:
            self.slo.note_ttft(s.tenant, max(0.0, now - s.submitted_ts))
        with self._lock:
            if s.finished:  # cancelled during prefill
                return
            s.tok = t1_host
            s.gen = 1
            self.tokens_total += 1  # token 1 comes from the prefill pick
            s.last_token_ts = now
            s.pending.append(np.array([[t1_host]], np.int32))
            s.pending_n = 1
            if s.max_new <= 1:
                self._finish(s, "done")
            else:
                s.state = "decoding"
                self._emit_boundary(s)
