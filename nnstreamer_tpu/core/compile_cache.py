"""Persistent XLA compilation cache: fast pipeline startup.

The reference's backends amortize startup by caching *engines* on disk
(e.g. TensorRT builds then caches serialized engines,
``ext/nnstreamer/tensor_filter/tensor_filter_tensorrt.cc``).  The XLA
analog is jax's persistent compilation cache: compiled executables keyed
by (HLO, flags, platform) survive process restarts, so a production
pipeline's first frame costs milliseconds instead of the 20-40 s TPU
compile.

Config (``core/config.py`` ini + env overrides):

    [xla]
    cache_dir = ~/.cache/nnstreamer_tpu/xla   ; "" disables
    cache_min_compile_secs = 0.0

Env: ``NNS_TPU_XLA_CACHE_DIR`` / ``NNS_TPU_XLA_CACHE_MIN_COMPILE_SECS``.
Enabled automatically by the jax-xla backend on open(); idempotent.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from . import config as nns_config
from .log import get_logger

log = get_logger("compile_cache")

_DEFAULT_DIR = "~/.cache/nnstreamer_tpu/xla"
_lock = threading.Lock()
_enabled: Optional[str] = None


def host_fingerprint() -> str:
    """Short tag identifying this host's compilation compatibility class.

    XLA's CPU backend AOT-compiles for the host's exact CPU features; an
    entry produced on another machine can load but SIGILL at run time
    (cpu_aot_loader machine-feature-mismatch warnings).  Keying the cache
    directory by platform + CPU-feature hash keeps each compatibility
    class in its own subtree, so cross-host cache reuse can't happen.
    """
    import hashlib
    import platform

    feats = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    feats = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        feats = platform.processor()
    tag = hashlib.sha1(
        f"{platform.system()}-{platform.machine()}-{feats}".encode()
    ).hexdigest()[:12]
    return f"{platform.machine()}-{tag}"


def enable(cache_dir: Optional[str] = None,
           platform: Optional[str] = None) -> Optional[str]:
    """Turn on the persistent cache (idempotent); returns the directory
    in use, or None when disabled by config/error.

    ``platform`` is the caller's actual device platform when known.  With
    no explicit directory (arg/env/ini), the cache auto-enables only for
    accelerator platforms: TPU compiles are the 20-40 s ones worth
    persisting, while XLA:CPU persists AOT machine code whose embedded
    compile "features" include tuning prefs (+prefer-no-gather, ...) the
    host feature probe never reports — so every warm-start load logs a
    spurious cpu_aot_loader feature-mismatch error.  An explicit
    directory overrides (tests, CPU farms that accept the noise).
    """
    global _enabled
    with _lock:
        if _enabled is not None and not (cache_dir and not _enabled):
            # sticky result — except that an explicit cache_dir may retry
            # after an earlier failure/disable
            if cache_dir and _enabled:
                want = os.path.expanduser(cache_dir)
                # _enabled is <dir>/<host-fingerprint>; same request iff
                # want is that dir (or the full fingerprinted path)
                if want not in (_enabled, os.path.dirname(_enabled)):
                    log.warning(
                        "compile cache already enabled at %s; ignoring "
                        "request for %s (call reset_for_tests() first to "
                        "re-point)", _enabled, want,
                    )
            return _enabled or None
        explicit = (
            cache_dir
            if cache_dir is not None
            else nns_config.get_value("xla", "cache_dir", None)
        )
        if explicit is None and platform == "cpu":
            # auto mode on CPU: skip (see docstring); stays retryable so a
            # later accelerator-backend open() can still enable it
            log.debug("persistent cache auto-disabled on cpu platform")
            return None
        raw = _DEFAULT_DIR if explicit is None else explicit
        if not raw:
            _enabled = ""
            return None
        # per-host subtree: AOT entries are only valid on hosts with the
        # same CPU feature set (see host_fingerprint)
        path = os.path.join(os.path.expanduser(raw), host_fingerprint())
        try:
            # parse every knob BEFORE mutating jax.config so a bad ini
            # value cannot leave the cache half-enabled.  min 0: streaming
            # pipelines recompile per shape bucket, and those sub-second
            # compiles are exactly the ones worth persisting.
            min_secs = float(
                nns_config.get_value(
                    "xla", "cache_min_compile_secs", "0.0"
                )
            )
            os.makedirs(path, exist_ok=True)
            import jax

            jax.config.update("jax_compilation_cache_dir", path)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", min_secs
            )
        except Exception as e:  # config knob drift must never kill serving
            log.warning("persistent compilation cache unavailable: %s", e)
            _enabled = ""
            return None
        _enabled = path
        log.info("XLA persistent compilation cache at %s", path)
        return path


def reset_for_tests() -> None:
    global _enabled
    with _lock:
        _enabled = None
