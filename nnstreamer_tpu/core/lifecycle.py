"""Lifecycle primitives: validated hot model swap, graceful drain, and
rolling-restart support — the zero-downtime operations layer.

PR-1/2 made the pipeline survive *unplanned* failures (crashes, hangs,
overload); this module covers the two most common *planned* disruptions
of a production serving fleet — model updates and server restarts — so
neither drops a frame:

* **Validated hot model swap** (:class:`HotSwapCoordinator`): the
  reference's ``is-updatable``/RELOAD_MODEL contract
  (``tensor_filter_tensorflow_lite.cc:274`` double-buffered interpreter
  reload) done the TPU-native way.  The new model is staged on a
  *second* backend instance in a background thread — open, schema
  compatibility check against the pipeline's negotiated specs, JIT
  warmup on a zero probe frame — so the XLA trace (multi-second on TPU)
  never lands on the hot path; then the serving pointer swaps at a
  frame boundary.  Any staging failure keeps the old model serving
  (``swap_failures``), and an error burst inside the post-swap
  observation window rolls back to the retained old backend
  (``rollbacks``).  The retiring backend closes only after the
  element's last in-flight frame has been emitted (the graveyard is
  reaped at drained frame boundaries).

* **Graceful drain** (``Pipeline.drain`` — see pipeline/pipeline.py):
  quiesce sources, flush in-flight frames to the sinks through the
  existing EOS machinery under a bounded deadline, report exact
  ``{drained, dropped, elapsed}``.

* **Rolling query-server restart**: a draining query server refuses
  *new* requests with a GOAWAY reply (:class:`ServerGoawayError` — 'G'
  on raw TCP, UNAVAILABLE+goaway detail on gRPC) that clients treat as
  an immediate, resend-safe failover signal: the refused request
  provably never executed, the reply is health (never a breaker trip),
  and no busy-pacing wait is owed to a host that asked us to leave.

Design rules follow core/resilience.py: injectable clocks, zero hot-path
cost when idle (the coordinator's pending checks are plain attribute
reads), and every counter exact.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from .log import get_logger
from .resilience import FAULTS, RemoteApplicationError

log = get_logger("lifecycle")


class ServerGoawayError(RemoteApplicationError):
    """The server refused the request because it is DRAINING (GOAWAY).

    Subclasses :class:`RemoteApplicationError`: the server answered, so
    breakers/cooldowns must not count it against the remote's health —
    a planned restart is not an outage.  A GOAWAY-refused request
    provably never executed, which makes an immediate resend on another
    host safe even under at-most-once delivery; unlike BUSY there is no
    pacing to honor (the host is leaving, not overloaded), so clients
    fail over with zero added latency."""

    def __init__(self, msg: str = "server draining (goaway)"):
        super().__init__(msg)


def pipeline_quiescing(element: Any, drain: bool = True) -> bool:
    """True when the element's owning pipeline wants its sources to stop
    producing: hard stop always; graceful drain when ``drain``.  Shared
    by every source whose ``frames()`` generator waits in an internal
    poll loop (appsrc, repo, edge/grpc/mqtt subscribers) — the
    scheduler-level drain check only runs between yields, so sources
    that block *inside* ``frames()`` must poll this themselves."""
    p = getattr(element, "_pipeline", None)
    if p is None:
        return False
    if p._stop_flag.is_set():
        return True
    return bool(drain and p.draining)


class SwapTicket:
    """Handle for one hot-swap request.

    States: ``staging`` → ``failed`` | ``staged`` → ``applied`` →
    ``committed`` | ``rolled-back`` (plus ``refused`` when a request is
    rejected up front, e.g. another swap is already in flight).
    ``wait_staged`` unblocks when the background validation finished
    either way; ``wait_applied`` when the new model actually started
    serving (the swap lands at the element's next frame boundary)."""

    def __init__(self, model: str):
        self.model = model
        self.state = "staging"
        self.error: Optional[BaseException] = None
        self._staged_done = threading.Event()
        self._applied = threading.Event()

    # -- transitions (coordinator-internal) ---------------------------------
    def _fail(self, err: BaseException, state: str = "failed") -> None:
        self.error = err
        self.state = state
        self._staged_done.set()
        self._applied.set()  # never will be: unblock waiters

    def _staged(self) -> None:
        self.state = "staged"
        self._staged_done.set()

    def _apply(self) -> None:
        self.state = "applied"
        self._applied.set()

    # -- API ----------------------------------------------------------------
    @property
    def ok(self) -> bool:
        """Staging succeeded (the swap may still be pending/observing)."""
        return self.error is None

    def wait_staged(self, timeout: Optional[float] = None) -> bool:
        return self._staged_done.wait(timeout)

    def wait_applied(self, timeout: Optional[float] = None) -> bool:
        """True once the new model is serving (False on timeout or when
        staging failed — check ``ok``)."""
        if not self._applied.wait(timeout):
            return False
        return self.state in ("applied", "committed", "rolled-back")


class HotSwapCoordinator:
    """Stage → validate → warm → swap → observe → commit/rollback state
    machine for one serving element (composed by ``tensor_filter``).

    The element supplies three callables:

    * ``build(model) -> backend`` — open a SECOND backend instance for
      the new model (must not touch the serving one).
    * ``validate(backend) -> (in_spec, out_spec)`` — raise unless the
      new model is schema-compatible with the pipeline's negotiated
      specs; returns the model info the element adopts at swap time.
    * ``warmup(backend) -> None`` — run the JIT/probe invoke(s) so the
      first real frame after the swap pays no compile.

    Threading contract: ``request``/staging run on a private daemon
    thread; ``take_staged``/``activated``/``note_ok``/``note_error``/
    ``discard``/``reap`` are called ONLY from the element's streaming
    thread (single consumer); counters and slots are lock-guarded so
    ``snapshot()`` may be read from anywhere.

    Fault sites (deterministic chaos, core/resilience.py FAULTS):
    ``filter.reload.load`` fires before the new backend opens,
    ``filter.reload.warmup`` before the probe invoke, and
    ``filter.reload.post`` inside the observation window's invoke path —
    the three planned-failure kinds of a model rollout."""

    def __init__(
        self,
        name: str,
        build: Callable[[str], Any],
        validate: Callable[[Any], Tuple[Any, Any]],
        warmup: Callable[[Any], None],
        clock: Callable[[], float] = time.monotonic,
    ):
        self.name = name
        self._build = build
        self._validate = validate
        self._warmup = warmup
        self._clock = clock
        self._lock = threading.Lock()
        # lifetime counters (survive element restarts — accounting is the
        # acceptance contract: a failed swap must show up HERE, never in
        # the supervisor's restart budget)
        self.swaps = 0
        self.swap_failures = 0
        self.rollbacks = 0
        self.model_version = 0
        self.last_error = ""
        # staged slot: (backend, model, in_spec, out_spec, ticket)
        self._staged: Optional[Tuple] = None
        self._staging = False
        # bumped by close(): a staging thread that completes after the
        # element stopped must discard its backend (never stage it —
        # that would leak a device-resident model, or silently apply a
        # stale pre-stop swap after a restart)
        self._close_epoch = 0
        # retired slot while observing: (old_blob, ticket); old_blob is
        # the element's opaque restore state (backend + model info)
        self._retired: Optional[Tuple] = None
        self.observing = False
        self._obs_deadline = 0.0
        self._obs_errors = 0
        self._obs_burst = 3
        # backends awaiting close — reaped only at a DRAINED frame
        # boundary, so a retiring backend can never be closed under its
        # last in-flight frames
        self._graveyard: list = []

    # -- hot-path pending checks (plain attribute reads) ---------------------
    @property
    def has_boundary_work(self) -> bool:
        """Anything to do at the next frame boundary?  Cheap enough for
        the per-call hot path."""
        return (
            self._staged is not None
            or bool(self._graveyard)
            or (self.observing and self._clock() >= self._obs_deadline)
        )

    # -- request / staging ----------------------------------------------------
    def request(self, model: str, observation_window: float = 5.0,
                error_burst: int = 3) -> SwapTicket:
        """Begin staging ``model`` on a background thread; returns the
        ticket immediately.  Refused (ticket state ``refused``) when a
        swap is already staging/staged *or still inside its observation
        window* (accepting then would overwrite the retained old backend
        before its commit/rollback verdict — leaking it and stranding
        its ticket) — the caller retries after it lands; refusals are
        not ``swap_failures`` (nothing was tried)."""
        ticket = SwapTicket(model)
        with self._lock:
            if (self._staging or self._staged is not None
                    or self._retired is not None):
                ticket._fail(
                    RuntimeError(f"{self.name}: a model swap is already "
                                 "in progress"),
                    state="refused",
                )
                return ticket
            self._staging = True
            self._pending_window = max(0.0, float(observation_window))
            self._pending_burst = max(1, int(error_burst))
            epoch = self._close_epoch
        t = threading.Thread(
            target=self._stage, args=(model, ticket, epoch),
            name=f"{self.name}-model-stage", daemon=True,
        )
        t.start()
        return ticket

    def stage_sync(self, model: str, observation_window: float = 5.0,
                   error_burst: int = 3) -> SwapTicket:
        """Synchronous staging (tests / call sites that want to block)."""
        ticket = self.request(model, observation_window, error_burst)
        if ticket.state != "refused":
            ticket.wait_staged()
        return ticket

    def _stage(self, model: str, ticket: SwapTicket, epoch: int) -> None:
        backend = None
        try:
            FAULTS.check("filter.reload.load")
            backend = self._build(model)
            in_spec, out_spec = self._validate(backend)
            FAULTS.check("filter.reload.warmup")
            self._warmup(backend)
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:  # noqa: BLE001 — staging boundary: ANY
            # failure here must leave the old model serving untouched
            if backend is not None:
                try:
                    backend.close()
                except Exception:  # allow-silent: teardown of a dead stage
                    pass
            with self._lock:
                self._staging = False
                stale = epoch != self._close_epoch
                if not stale:
                    self.swap_failures += 1
                    self.last_error = repr(e)
            if not stale:
                log.error(
                    "%s: hot swap to %r failed during staging "
                    "(old model keeps serving): %s", self.name, model, e,
                )
            ticket._fail(e)
            return
        with self._lock:
            self._staging = False
            stale = epoch != self._close_epoch
            if not stale:
                self._staged = (backend, model, in_spec, out_spec, ticket)
        if stale:
            # the element stopped while we were staging: the freshly
            # opened backend must be torn down, never staged (a restart
            # must not inherit a pre-stop swap)
            try:
                backend.close()
            except Exception:
                log.exception("%s: closing orphaned staged backend failed",
                              self.name)
            ticket._fail(RuntimeError("element stopped during staging"))
            return
        log.info(
            "%s: model %r staged and warmed; swapping at the next frame "
            "boundary", self.name, model,
        )
        ticket._staged()

    def note_inline_failure(self, err: BaseException) -> SwapTicket:
        """Account a failed LEGACY inline ``backend.reload()`` (staging
        bypassed): same counter, same keep-serving contract."""
        with self._lock:
            self.swap_failures += 1
            self.last_error = repr(err)
        t = SwapTicket("")
        t._fail(err)
        return t

    def note_inline_swap(self, model: str) -> SwapTicket:
        """Account a successful legacy inline reload (no observation
        window — the backend swapped internally)."""
        with self._lock:
            self.swaps += 1
            self.model_version += 1
        t = SwapTicket(model)
        t._staged()
        t._apply()
        t.state = "committed"
        return t

    # -- swap at the frame boundary (element streaming thread only) ----------
    def take_staged(self) -> Optional[Tuple]:
        """Claim the staged (backend, model, in_spec, out_spec, ticket)
        or None.  The caller MUST follow up with :meth:`activated`."""
        with self._lock:
            staged, self._staged = self._staged, None
            return staged

    def activated(self, old_blob: Tuple, ticket: SwapTicket) -> None:
        """The element swapped its serving pointer; retain the old
        backend for the observation window."""
        with self._lock:
            self._retired = (old_blob, ticket)
            self.observing = True
            self._obs_deadline = self._clock() + getattr(
                self, "_pending_window", 5.0)
            self._obs_errors = 0
            self._obs_burst = getattr(self, "_pending_burst", 3)
            self.swaps += 1
            self.model_version += 1
        ticket._apply()

    def note_ok(self) -> None:
        """A post-swap invoke succeeded: commit once the observation
        window has elapsed (the retired backend moves to the graveyard,
        closed at the next drained boundary)."""
        if not self.observing or self._clock() < self._obs_deadline:
            return
        self._commit()

    def _commit(self) -> None:
        with self._lock:
            if self._retired is None:
                self.observing = False
                return
            (old_blob, ticket), self._retired = self._retired, None
            self.observing = False
            self._graveyard.append(old_blob[0])
        ticket.state = "committed"
        log.info("%s: swap committed (model_version=%d)",
                 self.name, self.model_version)

    def note_error(self, err: BaseException) -> Optional[Tuple]:
        """A post-swap invoke failed.  Returns ``(old_blob,
        rolled_back)`` — the element retries the frame on the retained
        old backend either way (zero frame loss), and on ``rolled_back``
        it must restore its pointers from ``old_blob`` and hand the
        failed new backend to :meth:`discard`.  None when no observation
        window is active (normal supervision applies)."""
        if not self.observing or self._retired is None:
            return None
        with self._lock:
            if self._retired is None:
                return None
            self._obs_errors += 1
            self.last_error = repr(err)
            burst = self._obs_errors >= self._obs_burst
            old_blob, ticket = self._retired
            if burst:
                self._retired = None
                self.observing = False
                self.rollbacks += 1
                self.model_version -= 1
        if burst:
            ticket.state = "rolled-back"
            log.error(
                "%s: %d invoke error(s) inside the post-swap observation "
                "window — rolled back to the previous model: %s",
                self.name, self._obs_errors, err,
            )
        else:
            log.warning(
                "%s: post-swap invoke error %d/%d (frame served by the "
                "retained old model): %s",
                self.name, self._obs_errors, self._obs_burst, err,
            )
        return (old_blob, burst)

    def discard(self, backend: Any) -> None:
        """Queue a rolled-back (or otherwise dead) backend for closing
        at the next drained frame boundary."""
        with self._lock:
            self._graveyard.append(backend)

    def reap(self) -> None:
        """Close graveyard backends.  Call ONLY after the element's
        in-flight window is drained — this is what guarantees a retiring
        backend outlives its last in-flight frame."""
        with self._lock:
            dead, self._graveyard = self._graveyard, []
        for be in dead:
            try:
                be.close()
            except Exception:
                log.exception("%s: closing retired backend failed", self.name)

    def close(self) -> None:
        """Element stop: tear down every non-serving backend this
        coordinator still holds (staged, retired, graveyard).  Counters
        survive — they are lifetime accounting."""
        with self._lock:
            staged, self._staged = self._staged, None
            retired, self._retired = self._retired, None
            dead, self._graveyard = self._graveyard, []
            self.observing = False
            self._staging = False
            # an in-flight staging thread sees the epoch change and
            # discards its backend instead of staging it
            self._close_epoch += 1
        if staged is not None:
            dead.append(staged[0])
            staged[4]._fail(RuntimeError("element stopped before swap"))
        if retired is not None:
            dead.append(retired[0][0])
            retired[1].state = "committed"  # the new model served until stop
        for be in dead:
            try:
                be.close()
            except Exception:
                log.exception("%s: closing backend failed", self.name)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            state = (
                "staging" if self._staging
                else "staged" if self._staged is not None
                else "observing" if self.observing
                else "idle"
            )
            return {
                "swaps": self.swaps,
                "swap_failures": self.swap_failures,
                "rollbacks": self.rollbacks,
                "model_version": self.model_version,
                "swap_state": state,
                "swap_last_error": self.last_error,
            }
