"""Process-wide subplugin registry.

Reference: ``gst/nnstreamer/nnstreamer_subplugin.c`` — per-kind hash tables
with ``register_subplugin`` (:223), ``get_subplugin`` (:139, which dlopens on
miss), ``get_all_subplugins`` (:174), plus custom-property description lists.

The TPU-native registry keys on the same kinds (filter / decoder / converter /
trainer / custom) but loads Python entry points instead of dlopening shared
objects: a subplugin is any callable/class registered under a name, either
directly (in-process, ≙ custom-easy) or lazily via a module path from the
config search list (≙ the .so search path).
"""

from __future__ import annotations

import importlib
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

KIND_FILTER = "filter"
KIND_DECODER = "decoder"
KIND_CONVERTER = "converter"
KIND_TRAINER = "trainer"
KIND_CUSTOM = "custom"
KINDS = (KIND_FILTER, KIND_DECODER, KIND_CONVERTER, KIND_TRAINER, KIND_CUSTOM)

_lock = threading.RLock()
_tables: Dict[str, Dict[str, Any]] = {k: {} for k in KINDS}
# name -> "module[:attr]" resolved on first get (lazy, ≙ dlopen-on-demand)
_lazy: Dict[str, Dict[str, str]] = {k: {} for k in KINDS}
# per-subplugin custom property descriptions (reference :254)
_custom_props: Dict[Tuple[str, str], Dict[str, str]] = {}


class SubpluginNotFound(KeyError):
    pass


def register(kind: str, name: str, obj: Any, *, replace: bool = True) -> None:
    """Register a subplugin object under (kind, name).

    Reference: ``register_subplugin`` nnstreamer_subplugin.c:223.
    """
    if kind not in KINDS:
        raise ValueError(f"unknown subplugin kind {kind!r}")
    with _lock:
        if not replace and name in _tables[kind]:
            raise ValueError(f"{kind} subplugin {name!r} already registered")
        _tables[kind][name] = obj


def register_lazy(kind: str, name: str, target: str) -> None:
    """Register a lazily imported subplugin: target = "pkg.module[:attr]"."""
    if kind not in KINDS:
        raise ValueError(f"unknown subplugin kind {kind!r}")
    with _lock:
        _lazy[kind][name] = target


def unregister(kind: str, name: str) -> bool:
    with _lock:
        found = _tables[kind].pop(name, None) is not None
        found = (_lazy[kind].pop(name, None) is not None) or found
        return found


def get(kind: str, name: str) -> Any:
    """Look up a subplugin, importing a lazy target on first use.

    Reference: ``get_subplugin`` nnstreamer_subplugin.c:139 (dlopen on miss).
    """
    with _lock:
        if name in _tables[kind]:
            return _tables[kind][name]
        target = _lazy[kind].get(name)
    if target is None:
        raise SubpluginNotFound(f"no {kind} subplugin named {name!r}")
    mod_name, _, attr = target.partition(":")
    mod = importlib.import_module(mod_name)
    obj = getattr(mod, attr) if attr else mod
    register(kind, name, obj)
    return obj


def get_all(kind: str) -> List[str]:
    """Names of every known subplugin of a kind (registered + lazy).

    Reference: ``get_all_subplugins`` nnstreamer_subplugin.c:174.
    """
    with _lock:
        return sorted(set(_tables[kind]) | set(_lazy[kind]))


def exists(kind: str, name: str) -> bool:
    with _lock:
        return name in _tables[kind] or name in _lazy[kind]


def set_custom_property_desc(kind: str, name: str, desc: Dict[str, str]) -> None:
    """Attach human-readable descriptions of a subplugin's custom properties."""
    with _lock:
        _custom_props[(kind, name)] = dict(desc)


def get_custom_property_desc(kind: str, name: str) -> Optional[Dict[str, str]]:
    with _lock:
        d = _custom_props.get((kind, name))
        return dict(d) if d else None
