"""Orbax-backed checkpoint/resume for in-pipeline training.

The reference's checkpoint story is model-save/load-path on tensor_trainer
plus deterministic datarepo sample indices (SURVEY §5.4) — final-state only.
TPU fleets are preemptible, so the TPU build adds what §5.3 calls out as
missing: periodic full-state checkpoints (params + optimizer state + epoch)
that a restarted pipeline resumes from.

Layout: ``<dir>/step_<N>/`` per checkpoint (Orbax StandardCheckpointer),
newest-wins resume via :func:`latest_step`.
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional

_STEP_RE = re.compile(r"^step_(\d+)$")


def _step_dir(path: str, step: int) -> str:
    return os.path.join(os.path.abspath(path), f"step_{step}")


def save_state(path: str, step: int, state: Any) -> str:
    """Save a pytree as checkpoint `step` under `path`; returns the dir."""
    import orbax.checkpoint as ocp

    d = _step_dir(path, step)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(d, state, force=True)
    ckptr.wait_until_finished()
    return d


def latest_step(path: str) -> Optional[int]:
    """Newest complete checkpoint step under `path`, or None."""
    if not os.path.isdir(path):
        return None
    steps = []
    for name in os.listdir(path):
        m = _STEP_RE.match(name)
        if m and os.path.isdir(os.path.join(path, name)):
            steps.append(int(m.group(1)))
    return max(steps) if steps else None


def restore_state(path: str, step: int, template: Any) -> Any:
    """Restore checkpoint `step`; `template` supplies the pytree structure
    (shapes/dtypes must match what was saved)."""
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(_step_dir(path, step), template)


def prune(path: str, keep: int) -> None:
    """Delete all but the newest `keep` checkpoints."""
    import shutil

    if keep <= 0 or not os.path.isdir(path):
        return
    steps = sorted(
        int(m.group(1))
        for m in (_STEP_RE.match(n) for n in os.listdir(path))
        if m and os.path.isdir(os.path.join(path, m.group(0)))
    )
    for s in steps[:-keep]:
        shutil.rmtree(_step_dir(path, s), ignore_errors=True)
