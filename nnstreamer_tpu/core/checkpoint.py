"""Orbax-backed checkpoint/resume for in-pipeline training.

The reference's checkpoint story is model-save/load-path on tensor_trainer
plus deterministic datarepo sample indices (SURVEY §5.4) — final-state only.
TPU fleets are preemptible, so the TPU build adds what §5.3 calls out as
missing: periodic full-state checkpoints (params + optimizer state + step +
data cursor) that a restarted pipeline resumes from.

Layout: ``<dir>/step_<N>/`` per checkpoint (Orbax StandardCheckpointer)
plus a **completion marker** ``<dir>/step_<N>.ok`` written atomically
*after* the Orbax save finishes.  A crash mid-save leaves a step dir with
no marker; :func:`latest_step` only ever selects marked steps, so a torn
save can never be resumed (the write/commit split exists so the trainer
can fault-inject the gap between them).  The marker doubles as the
checkpoint's metadata record — a small JSON dict (the trainer stores its
data cursor there), read back via :func:`load_meta`.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Optional

_STEP_RE = re.compile(r"^step_(\d+)$")
_MARK_RE = re.compile(r"^step_(\d+)\.ok$")


def _step_dir(path: str, step: int) -> str:
    return os.path.join(os.path.abspath(path), f"step_{step}")


def _marker_path(path: str, step: int) -> str:
    return _step_dir(path, step) + ".ok"


def _fsync_path(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Crash-atomic file write: temp sibling in the same directory,
    fsync, then ``os.replace`` — a crash at any instant leaves either
    the old complete file or the new complete file, never a torn one
    (the datareposink pattern, shared here so the trainer's model saves
    and checkpoint markers use the one idiom)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    base = os.path.basename(path)
    tmp = os.path.join(d, f".{base}.tmp.{os.getpid()}")
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_path(d)


def write_state(path: str, step: int, state: Any) -> str:
    """Write checkpoint ``step`` under ``path`` WITHOUT committing it:
    the Orbax save runs to completion but no marker is written, so
    :func:`latest_step` will not select it until :func:`commit_state`
    runs.  Callers that don't need the split use :func:`save_state`."""
    import orbax.checkpoint as ocp

    d = _step_dir(path, step)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(d, state, force=True)
    ckptr.wait_until_finished()
    return d


def commit_state(path: str, step: int,
                 meta: Optional[Dict[str, Any]] = None) -> str:
    """Atomically publish checkpoint ``step`` by writing its completion
    marker (with optional JSON ``meta`` — the trainer's data cursor).
    Only after this returns can :func:`latest_step` select the step."""
    marker = _marker_path(path, step)
    payload = dict(meta or {})
    payload["step"] = int(step)
    atomic_write_bytes(marker, json.dumps(payload).encode())
    return marker


def save_state(path: str, step: int, state: Any,
               meta: Optional[Dict[str, Any]] = None) -> str:
    """Save + commit a pytree as checkpoint ``step``; returns the dir."""
    d = write_state(path, step, state)
    commit_state(path, step, meta)
    return d


def latest_step(path: str) -> Optional[int]:
    """Newest COMPLETE (marker-committed) checkpoint step under
    ``path``, or None.  Torn saves — a step dir without its ``.ok``
    marker — are never selected."""
    if not os.path.isdir(path):
        return None
    steps = []
    for name in os.listdir(path):
        m = _STEP_RE.match(name)
        if (m and os.path.isdir(os.path.join(path, name))
                and os.path.isfile(_marker_path(path, int(m.group(1))))):
            steps.append(int(m.group(1)))
    return max(steps) if steps else None


def load_meta(path: str, step: int) -> Dict[str, Any]:
    """The metadata dict committed with checkpoint ``step`` (empty for
    a missing/unreadable marker — pre-marker-era checkpoints restore
    with no cursor)."""
    try:
        with open(_marker_path(path, step)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def restore_state(path: str, step: int, template: Any) -> Any:
    """Restore checkpoint `step`; `template` supplies the pytree structure
    (shapes/dtypes must match what was saved)."""
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(_step_dir(path, step), template)


def prune(path: str, keep: int) -> None:
    """Delete all but the newest `keep` COMPLETE checkpoints.  Torn
    saves (unmarked dirs) and orphaned markers are always removed —
    they can never be resumed, so retaining them only wastes disk."""
    import shutil

    if keep <= 0 or not os.path.isdir(path):
        return
    complete, torn, orphans = [], [], []
    names = os.listdir(path)
    dirs = {int(m.group(1)) for m in map(_STEP_RE.match, names)
            if m and os.path.isdir(os.path.join(path, m.group(0)))}
    marks = {int(m.group(1)) for m in map(_MARK_RE.match, names) if m}
    for s in dirs:
        (complete if s in marks else torn).append(s)
    orphans = sorted(marks - dirs)
    for s in sorted(complete)[:-keep]:
        # marker FIRST: a crash between the two deletes must leave a
        # torn (never-resumed) dir, not a marked dir with no data
        try:
            os.remove(_marker_path(path, s))
        except OSError:
            pass
        shutil.rmtree(_step_dir(path, s), ignore_errors=True)
    for s in torn:
        shutil.rmtree(_step_dir(path, s), ignore_errors=True)
    for s in orphans:
        try:
            os.remove(_marker_path(path, s))
        except OSError:
            pass
