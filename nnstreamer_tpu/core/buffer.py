"""Stream payload and event objects.

Reference analogs:

- ``TensorFrame`` ≙ a GstBuffer holding up to 256 GstMemory tensor chunks plus
  pts/dts/duration timestamps (reference
  ``gst/nnstreamer/nnstreamer_plugin_api_impl.c:1541`` nth-memory access).
- ``meta`` dict ≙ GstMeta attachments; key ``"client_id"`` mirrors the query
  meta that routes answers back to the right client
  (reference ``gst/nnstreamer/tensor_meta.c``).
- Event classes ≙ GstEvent EOS / FLUSH / SEGMENT / CAPS.

TPU-first notes: tensor payloads may be numpy arrays *or* ``jax.Array``s —
elements that chain JAX computation keep data on device between elements
(the zero-copy analog of mapped GstMemory), and only sinks/serializers pull
to host.
"""

from __future__ import annotations

import itertools
import os
import sys
import time
from collections import deque as _deque
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .types import StreamSpec, TensorSpec, FORMAT_STATIC

# monotonic frame sequence for debugging/tracing
_seq = itertools.count()


@dataclass
class TensorFrame:
    """One frame of a tensor stream: N tensors + timestamps + metadata."""

    tensors: List[Any]  # np.ndarray | jax.Array, len <= TENSOR_COUNT_LIMIT
    pts: Optional[float] = None  # presentation timestamp, seconds
    duration: Optional[float] = None
    meta: Dict[str, Any] = field(default_factory=dict)
    seq: int = field(default_factory=lambda: next(_seq))

    def __len__(self) -> int:
        return len(self.tensors)

    def nth(self, i: int):
        """Reference: gst_tensor_buffer_get_nth_memory."""
        return self.tensors[i]

    def pick(self, indices: Sequence[int]) -> "TensorFrame":
        """input-combination / tensorpick subset-reorder."""
        return replace(
            self,
            tensors=[self.tensors[i] for i in indices],
            meta=dict(self.meta),
        )

    def with_tensors(self, tensors: Sequence[Any]) -> "TensorFrame":
        """New frame with same timestamps, COPIED meta, different payload.

        Meta is copied, not aliased: derived frames get stamped with new
        keys by decoders/elements, and a tee sibling sharing the source
        frame must never see those (the payload-sharing contract covers
        tensors only)."""
        return replace(self, tensors=list(tensors), meta=dict(self.meta))

    def spec(self) -> StreamSpec:
        """Derive the concrete schema of this frame."""
        return StreamSpec(
            tuple(TensorSpec(tuple(t.shape), np.dtype(t.dtype)) for t in self.tensors),
            FORMAT_STATIC,
        )

    def nbytes(self) -> int:
        return sum(int(np.prod(t.shape)) * np.dtype(t.dtype).itemsize for t in self.tensors)

    def to_host(self) -> "TensorFrame":
        """Materialize all payloads as numpy arrays (device -> host),
        overlapping the per-tensor transfers (see :func:`materialize`).
        Already-host frames return self — the common sink-side case must
        not pay a per-frame dataclass copy."""
        if all(type(t) is np.ndarray for t in self.tensors):
            return self
        return self.with_tensors(materialize(self.tensors))


@dataclass
class BatchFrame(TensorFrame):
    """A micro-batch travelling as ONE stream item: every tensor has a
    leading batch axis; ``frames_info`` keeps the per-logical-frame
    (pts, duration, meta) so the batch can be split back losslessly.

    TPU-first rationale (no reference analog): per-frame Python dispatch
    caps throughput long before the MXU does, so batch-capable element
    chains (filter -> fused decoder -> sink) move whole micro-batches —
    usually still device-resident — and split only at a host boundary.
    Produced by tensor_filter in batch-through mode and by block ingest
    (``AppSrc.push_block`` / converter ``emit-blocks``).  ``with_tensors``/
    ``pick`` preserve the subclass (dataclasses.replace), but delivery of
    a WHOLE block to an element additionally requires that element to set
    ``Element.BATCH_AWARE = True`` — the scheduler splits blocks into
    logical frames before anything else (per-frame semantics are the
    default; the batch fast path is an opt-in).  Sinks/decoders split via
    :meth:`split`.
    """

    frames_info: List[Tuple[Optional[float], Optional[float], Dict[str, Any]]] = field(
        default_factory=list
    )

    @property
    def batch_size(self) -> int:
        return len(self.frames_info)

    @classmethod
    def from_frames(
        cls, tensors: Sequence[Any], frames: Sequence[TensorFrame]
    ) -> "BatchFrame":
        first = frames[0]
        return cls(
            tensors=list(tensors),
            pts=first.pts,
            duration=first.duration,
            meta=dict(first.meta),
            frames_info=[(f.pts, f.duration, f.meta) for f in frames],
        )

    def split(self) -> List[TensorFrame]:
        """Materialize on host and fan back out into per-frame views.
        Per-frame wrappers come from the frame pool (the split fan-out is
        the hottest frame allocator at chip-rate streams)."""
        mats = materialize(self.tensors)
        acquire = FRAME_POOL.acquire
        return [
            acquire([m[b] for m in mats], pts=p, duration=d, meta=dict(fm))
            for b, (p, d, fm) in enumerate(self.frames_info)
        ]


def start_host_copies(tensors: Sequence[Any]) -> None:
    """Kick off async device->host copies for every device tensor (no-op
    for host arrays).  Callers that park outputs (the filter's dispatch
    window) call this at park time so the transfer overlaps later
    compute; :func:`materialize` calls it so N outputs cost ~one round
    trip instead of N serialized ones."""
    for t in tensors:
        start = getattr(t, "copy_to_host_async", None)
        if start is not None:
            try:
                start()
            except Exception:  # allow-silent: prefetch hint only
                pass  # stale/donated buffer: np.asarray later decides


def materialize(tensors: Sequence[Any]) -> List[np.ndarray]:
    """Bring a tensor list to host, overlapping the transfers.

    All device tensors start their device->host copies ASYNC before any
    is awaited: on a latency-bound link (PCIe queue, the dev tunnel) N
    outputs cost ~one round trip instead of N serialized ones — a hidden
    per-batch cost on every host boundary (BatchFrame.split, the unfused
    micro-batch path, sinks)."""
    start_host_copies(tensors)
    return [np.asarray(t) for t in tensors]


# ---------------------------------------------------------------------------
# Frame pool (hot-path allocation diet)
# ---------------------------------------------------------------------------
class FramePool:
    """Free-list of TensorFrame/BatchFrame carcasses.

    At chip-rate streams the per-frame wrapper objects (dataclass
    instance, meta dict, seq counter) are real scheduler overhead: every
    split/emit allocates one and every sink/drop frees one, thousands of
    times per second.  The pool recycles the *wrapper only* — payload
    tensors and meta dicts are dropped at recycle time so nothing large is
    ever pinned by the free list.

    Safety contract: :meth:`recycle` accepts a frame ONLY when the caller
    provably holds the last reference (``sys.getrefcount`` guard), so a
    frame retained by an element (``tensor_if`` previous-frame cache, a
    sink's stored frames, an application callback) can never be reused
    under its holder.  Call it with at most one local binding:
    ``pool.recycle(f)``.  Both sides are GIL-atomic (deque append/pop), so
    any worker thread may acquire/recycle concurrently.

    ``NNS_FRAME_POOL`` sizes the default pool (frames retained per class;
    0 disables recycling entirely)."""

    __slots__ = (
        "_free", "_free_batch", "_max_refs", "enabled", "reused", "recycled",
    )

    def _probe_refs(self, x) -> int:
        """Observed refcount of an object held by exactly one caller local,
        seen from inside a method call — the method-call machinery's
        contribution varies across CPython versions (3.10 keeps an extra
        stack reference), so the recycle threshold is calibrated, not
        assumed."""
        return sys.getrefcount(x)

    def __init__(self, maxsize: int = 1024):
        self._free: _deque = _deque(maxlen=max(0, maxsize))
        self._free_batch: _deque = _deque(maxlen=max(0, maxsize // 8))
        self.enabled = maxsize > 0
        probe = object()
        self._max_refs = self._probe_refs(probe)
        # stats (racy best-effort counters; tests/monitoring only)
        self.reused = 0
        self.recycled = 0

    def acquire(
        self,
        tensors: List[Any],
        pts: Optional[float] = None,
        duration: Optional[float] = None,
        meta: Optional[Dict[str, Any]] = None,
    ) -> "TensorFrame":
        """A TensorFrame with the given payload: recycled when a carcass
        is free, freshly constructed otherwise.  Same signature/cost
        either way; ``seq`` is always fresh."""
        try:
            f = self._free.pop()
        except IndexError:
            return TensorFrame(
                tensors, pts=pts, duration=duration,
                meta={} if meta is None else meta,
            )
        f.tensors = tensors
        f.pts = pts
        f.duration = duration
        f.meta = {} if meta is None else meta
        f.seq = next(_seq)
        self.reused += 1
        return f

    def acquire_batch(
        self,
        tensors: List[Any],
        pts: Optional[float] = None,
        duration: Optional[float] = None,
        meta: Optional[Dict[str, Any]] = None,
        frames_info: Optional[List] = None,
    ) -> "BatchFrame":
        try:
            f = self._free_batch.pop()
        except IndexError:
            return BatchFrame(
                tensors, pts=pts, duration=duration,
                meta={} if meta is None else meta,
                frames_info=frames_info or [],
            )
        f.tensors = tensors
        f.pts = pts
        f.duration = duration
        f.meta = {} if meta is None else meta
        f.frames_info = frames_info or []
        f.seq = next(_seq)
        self.reused += 1
        return f

    def recycle(self, frame: Any) -> bool:
        """Return ``frame``'s carcass to the free list iff the caller holds
        the only remaining reference; payload/meta references are dropped
        immediately either way the frame is accepted.  Safe to call
        speculatively — a still-referenced or foreign object is refused."""
        if not self.enabled:
            return False
        t = type(frame)  # exact types only: subclasses own extra state
        if t is TensorFrame:
            if sys.getrefcount(frame) > self._max_refs:
                return False
            frame.tensors = None  # type: ignore[assignment] — re-set on acquire
            frame.meta = None  # type: ignore[assignment]
            frame.pts = frame.duration = None
            self._free.append(frame)
        elif t is BatchFrame:
            if sys.getrefcount(frame) > self._max_refs:
                return False
            frame.tensors = None  # type: ignore[assignment]
            frame.meta = None  # type: ignore[assignment]
            frame.frames_info = None  # type: ignore[assignment]
            frame.pts = frame.duration = None
            self._free_batch.append(frame)
        else:
            return False
        self.recycled += 1
        return True

    def trim(self) -> int:
        """Drop every retained carcass (memory-pressure relief valve —
        the watermark monitor calls this at the high watermark).  The
        pool keeps recycling afterwards; returns the carcasses freed."""
        n = len(self._free) + len(self._free_batch)
        self._free.clear()
        self._free_batch.clear()
        return n


#: process-wide default pool used by the scheduler dispatch loop,
#: BatchFrame.split, and tensor_filter's batch emitter
FRAME_POOL = FramePool(int(os.environ.get("NNS_FRAME_POOL", "1024")))


# ---------------------------------------------------------------------------
# Device/staging buffer pool (async device feed — zero-alloc steady state)
# ---------------------------------------------------------------------------
class DeviceBufferPool:
    """Free-list of STAGING buffers keyed by ``(shape, dtype, placement)``.

    The host->device ingest lane stacks every micro-batch into a host
    staging array before the transfer; allocating that array per batch is
    a steady hidden cost (a 128x224x224x3 uint8 batch is ~19 MB of fresh
    pages per invoke) and, on platforms with pinned-host staging, defeats
    transfer pinning entirely.  This pool keeps a small ring per
    (shape, dtype) so steady-state serving reuses the same buffers —
    together with XLA buffer donation on the jax-xla invoke path
    (``invoke_batch_donated``) the hot loop performs zero per-batch
    allocations once warm.

    Ownership contract: a buffer acquired here is exclusively the
    caller's until ``release()``.  Callers must release only when nothing
    can still read the memory — the filter releases a staging buffer when
    the batch it carried has been *emitted* (outputs materialized), which
    is strictly after any async transfer/compute consuming it finished.
    ``release()`` on a foreign array is accepted (it just joins the pool
    under its own key) but the double-release of a buffer still in use is
    the caller's bug — never release early.

    Placement domains: ``acquire``/``release`` take an optional hashable
    ``placement`` token (``FilterBackend.staging_placement()`` — a device
    ordinal, a mesh spec) that joins the ring key, so a buffer staged for
    one placement is never recycled into a caller staging for another.
    Shape+dtype alone is NOT an identity once meshes exist: a replicated
    carcass handed to a dp-sharded caller would be re-placed with the
    wrong scatter (and, on platforms with pinned-host staging, carry the
    wrong pinning).  Callers must pass the SAME token to release that
    they acquired under — the ring key is derived per call, not stored
    on the buffer.

    Key-space bound: the ring DICT itself is LRU-bounded at
    ``MAX_KEYS`` distinct ``(shape, dtype, placement)`` keys — a
    flexible-shape or mesh-config sweep mints a fresh key per
    configuration and each ring pins full-size staging buffers, the
    same slow-leak class the jit-cache LRU bounds (an evicted ring just
    re-allocates on next use).  ``rings_evicted`` counts dropped rings
    so truncation is never silent.

    Thread-safe; counters (``allocated``/``reused``) are exact under the
    lock and drive the perf smoke's reuse-rate floor.
    """

    __slots__ = ("_free", "_lock", "_max_per_key", "enabled",
                 "allocated", "reused", "rings_evicted", "trims")

    #: max distinct (shape, dtype, placement) rings kept live (LRU)
    MAX_KEYS = 32

    def __init__(self, max_per_key: int = 8):
        import threading
        from collections import OrderedDict

        self._free: "OrderedDict[Tuple, List[np.ndarray]]" = OrderedDict()
        self._lock = threading.Lock()
        self._max_per_key = max(0, max_per_key)
        self.enabled = self._max_per_key > 0
        self.allocated = 0
        self.reused = 0
        self.rings_evicted = 0  # whole rings dropped by the key LRU
        self.trims = 0          # memory-pressure trim() calls

    @staticmethod
    def _key(shape, dtype, placement=None) -> Tuple:
        return (tuple(int(d) for d in shape), np.dtype(dtype).str, placement)

    def acquire(self, shape, dtype, placement=None) -> np.ndarray:
        """A writable host buffer of exactly (shape, dtype) for the given
        placement domain: recycled when one is free, freshly allocated
        otherwise (contents undefined)."""
        key = self._key(shape, dtype, placement)
        if self.enabled:
            with self._lock:
                lst = self._free.get(key)
                if lst is not None:
                    self._free.move_to_end(key)  # ring touched = ring live
                    if lst:
                        self.reused += 1
                        return lst.pop()
                self.allocated += 1
        return np.empty(shape, np.dtype(dtype))

    def release(self, buf: np.ndarray, placement=None) -> bool:
        """Return ``buf`` to its placement domain's free list (True) or
        drop it when the per-key ring is full / pooling is disabled
        (False).  ``placement`` must match the acquire-side token."""
        if not self.enabled or not isinstance(buf, np.ndarray):
            return False
        key = self._key(buf.shape, buf.dtype, placement)
        with self._lock:
            lst = self._free.get(key)
            if lst is None:
                lst = self._free[key] = []
                while len(self._free) > self.MAX_KEYS:
                    # evict the least-recently-touched ring wholesale
                    # (its buffers are plain host arrays; dropping the
                    # references IS the free)
                    self._free.popitem(last=False)
                    self.rings_evicted += 1
            else:
                self._free.move_to_end(key)
            if len(lst) >= self._max_per_key:
                return False
            lst.append(buf)
        return True

    def trim(self) -> int:
        """Drop every pooled staging buffer (memory-pressure relief
        valve: the watermark monitor and the filter's OOM recovery both
        call this).  Outstanding (acquired) buffers are untouched —
        ownership is the caller's until release.  Returns buffers
        freed."""
        with self._lock:
            n = sum(len(lst) for lst in self._free.values())
            self._free.clear()
            self.trims += 1
        return n

    @property
    def reuse_rate(self) -> float:
        """reused / (reused + allocated) — 1.0 means zero-alloc steady
        state."""
        total = self.reused + self.allocated
        return self.reused / total if total else 0.0


#: process-wide default staging-buffer pool (``NNS_DEVICE_POOL`` sizes the
#: per-(shape,dtype) ring; 0 disables reuse)
DEVICE_POOL = DeviceBufferPool(int(os.environ.get("NNS_DEVICE_POOL", "8")))


# ---------------------------------------------------------------------------
# In-band events (flow through the same queues as frames, in order)
# ---------------------------------------------------------------------------
class Event:
    """Base class for in-band stream events (≙ GstEvent)."""

    __slots__ = ()

    def __repr__(self):
        return f"<{type(self).__name__}>"


class EOS(Event):
    """End of stream: no more frames will follow (≙ GST_EVENT_EOS)."""


class Flush(Event):
    """Drop queued data, reset element state (≙ FLUSH_START/STOP)."""


@dataclass(repr=True)
class SegmentEvent(Event):
    """New time segment (≙ GST_EVENT_SEGMENT)."""

    start: float = 0.0
    rate: float = 1.0


@dataclass(repr=True)
class CapsEvent(Event):
    """Announce the downstream schema (≙ GST_EVENT_CAPS).

    Sent before the first frame and whenever the schema changes; elements
    negotiate by intersecting with what they accept.
    """

    spec: StreamSpec = field(default_factory=StreamSpec)


@dataclass(repr=True)
class CustomEvent(Event):
    """Application/element-defined event (e.g. model RELOAD, epoch stats)."""

    name: str = ""
    data: Dict[str, Any] = field(default_factory=dict)


StreamItem = Any  # TensorFrame | Event


def now() -> float:
    return time.monotonic()
