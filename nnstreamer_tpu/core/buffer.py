"""Stream payload and event objects.

Reference analogs:

- ``TensorFrame`` ≙ a GstBuffer holding up to 256 GstMemory tensor chunks plus
  pts/dts/duration timestamps (reference
  ``gst/nnstreamer/nnstreamer_plugin_api_impl.c:1541`` nth-memory access).
- ``meta`` dict ≙ GstMeta attachments; key ``"client_id"`` mirrors the query
  meta that routes answers back to the right client
  (reference ``gst/nnstreamer/tensor_meta.c``).
- Event classes ≙ GstEvent EOS / FLUSH / SEGMENT / CAPS.

TPU-first notes: tensor payloads may be numpy arrays *or* ``jax.Array``s —
elements that chain JAX computation keep data on device between elements
(the zero-copy analog of mapped GstMemory), and only sinks/serializers pull
to host.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .types import StreamSpec, TensorSpec, FORMAT_STATIC

# monotonic frame sequence for debugging/tracing
_seq = itertools.count()


@dataclass
class TensorFrame:
    """One frame of a tensor stream: N tensors + timestamps + metadata."""

    tensors: List[Any]  # np.ndarray | jax.Array, len <= TENSOR_COUNT_LIMIT
    pts: Optional[float] = None  # presentation timestamp, seconds
    duration: Optional[float] = None
    meta: Dict[str, Any] = field(default_factory=dict)
    seq: int = field(default_factory=lambda: next(_seq))

    def __len__(self) -> int:
        return len(self.tensors)

    def nth(self, i: int):
        """Reference: gst_tensor_buffer_get_nth_memory."""
        return self.tensors[i]

    def pick(self, indices: Sequence[int]) -> "TensorFrame":
        """input-combination / tensorpick subset-reorder."""
        return replace(self, tensors=[self.tensors[i] for i in indices])

    def with_tensors(self, tensors: Sequence[Any]) -> "TensorFrame":
        """New frame with same timestamps/meta, different payload."""
        return replace(self, tensors=list(tensors))

    def spec(self) -> StreamSpec:
        """Derive the concrete schema of this frame."""
        return StreamSpec(
            tuple(TensorSpec(tuple(t.shape), np.dtype(t.dtype)) for t in self.tensors),
            FORMAT_STATIC,
        )

    def nbytes(self) -> int:
        return sum(int(np.prod(t.shape)) * np.dtype(t.dtype).itemsize for t in self.tensors)

    def to_host(self) -> "TensorFrame":
        """Materialize all payloads as numpy arrays (device -> host)."""
        return self.with_tensors([np.asarray(t) for t in self.tensors])


# ---------------------------------------------------------------------------
# In-band events (flow through the same queues as frames, in order)
# ---------------------------------------------------------------------------
class Event:
    """Base class for in-band stream events (≙ GstEvent)."""

    __slots__ = ()

    def __repr__(self):
        return f"<{type(self).__name__}>"


class EOS(Event):
    """End of stream: no more frames will follow (≙ GST_EVENT_EOS)."""


class Flush(Event):
    """Drop queued data, reset element state (≙ FLUSH_START/STOP)."""


@dataclass(repr=True)
class SegmentEvent(Event):
    """New time segment (≙ GST_EVENT_SEGMENT)."""

    start: float = 0.0
    rate: float = 1.0


@dataclass(repr=True)
class CapsEvent(Event):
    """Announce the downstream schema (≙ GST_EVENT_CAPS).

    Sent before the first frame and whenever the schema changes; elements
    negotiate by intersecting with what they accept.
    """

    spec: StreamSpec = field(default_factory=StreamSpec)


@dataclass(repr=True)
class CustomEvent(Event):
    """Application/element-defined event (e.g. model RELOAD, epoch stats)."""

    name: str = ""
    data: Dict[str, Any] = field(default_factory=dict)


StreamItem = Any  # TensorFrame | Event


def now() -> float:
    return time.monotonic()
