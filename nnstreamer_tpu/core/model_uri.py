"""Model-URI resolution for the ``model=`` property.

Reference: ``gst/nnstreamer/ml_agent.c:106`` (``mlagent_parse_uri_string``
resolves ``mlagent://model/<name>/<version>`` against the Tizen model
repository).  The TPU analog resolves:

* plain paths — returned as-is;
* ``file://<path>`` — stripped;
* ``model://<name>[/<version>]`` — looked up in the local model repo dir
  (config ``[model-repo] path`` or env ``NNS_TPU_MODEL_REPO``, default
  ``~/.nnstreamer_tpu/models``): ``<repo>/<name>/<version>/`` with
  ``latest`` = highest numeric version.  A repo entry is whatever the
  backend accepts (msgpack file, orbax dir, .py, .so, ...) — single file
  in the version dir, or the dir itself.
"""

from __future__ import annotations

import os
from typing import Optional

from . import config
from .log import get_logger

log = get_logger("model-uri")


def repo_dir() -> str:
    env = os.environ.get("NNS_TPU_MODEL_REPO")
    if env:
        return env
    return config.get_value(
        "model-repo", "path", os.path.expanduser("~/.nnstreamer_tpu/models")
    )


def _resolve_version(name_dir: str, version: str) -> Optional[str]:
    if version != "latest":
        d = os.path.join(name_dir, version)
        return d if os.path.exists(d) else None
    versions = []
    try:
        entries = os.listdir(name_dir)
    except OSError:
        return None
    for entry in entries:
        try:
            key = [int(p) for p in entry.split(".")]
        except ValueError:  # non-numeric or malformed ('1.', 'v2', ...)
            continue
        versions.append((key, entry))
    if not versions:
        return None
    return os.path.join(name_dir, max(versions)[1])


def resolve_model_uri(uri: str) -> str:
    """Resolve a model= value to a concrete path (or return it unchanged
    when it is not a URI).  Raises FileNotFoundError for a model:// URI
    that does not resolve."""
    if uri.startswith("file://"):
        return uri[len("file://"):]
    if not uri.startswith("model://"):
        return uri
    rest = uri[len("model://"):].strip("/")
    if not rest:
        raise FileNotFoundError("model:// URI needs a model name")
    name, _, version = rest.partition("/")
    vdir = _resolve_version(os.path.join(repo_dir(), name), version or "latest")
    if vdir is None:
        raise FileNotFoundError(
            f"{uri}: not found under model repo {repo_dir()!r}"
        )
    if os.path.isdir(vdir):
        entries = sorted(os.listdir(vdir))
        files = [e for e in entries if not e.startswith(".")]
        if len(files) == 1:
            return os.path.join(vdir, files[0])
    return vdir
