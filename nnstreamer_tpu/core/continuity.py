"""Stream continuity: checkpointed resume and live migration for
generation streams (Documentation/resilience.md "Stream continuity").

A generation stream used to die with the server it started on — a crash
mid-decode lost every remaining token, and a rolling restart had to
choose between cutting live streams and waiting them out.  This module
is the shared vocabulary that lets a stream OUTLIVE its server:

* every chunk a slotted :class:`~.slots.SlotEngine` emits carries a
  **resume state** in meta (:data:`RESUME_META`): an opaque model/
  sampling signature, the prompt digest, and the server's chunk size —
  alongside the ``tokens_done`` / ``chunk_index`` counters the chunks
  already carried.  Because the per-step sampling key is folded at the
  ABSOLUTE token index (``models/transformer.py``), prompt + generated
  prefix is a complete checkpoint: re-prefilling it on any server with
  the same signature reproduces the remaining tokens bit-identically;
* the query client accumulates the delivered tokens per stream in a
  :class:`StreamContinuity` ledger.  On a mid-stream transport break —
  or a draining server's resumable GOAWAY handoff chunk — it builds a
  **RESUME request** (:data:`RESUME_REQ_META` + [prompt, prefix]
  tensors) and re-routes it to a healthy server;
* resume points snap DOWN to the last full chunk boundary, so the
  resumed server's chunk grid stays aligned with an uninterrupted run —
  the ledger dedupes the re-decoded overlap by ``tokens_done``
  (``duplicate_tokens_dropped``), keeping delivered tokens EXACTLY-ONCE
  and the emitted chunk indices contiguous across the migration.

The resume state is ordinary JSON meta and the prefix an ordinary int32
tensor, so the protocol rides both transports with zero wire changes.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, Optional

#: chunk meta: the resume state stamped on every resumable chunk
RESUME_META = "_nns_resume"
#: request meta: marks a stream request as a RESUME of an earlier one
RESUME_REQ_META = "_nns_resume_req"
#: chunk meta: a draining server handed this stream off (resumable
#: final chunk — partial tokens + resume state; a migration, NOT a
#: failure: breaker-immune, no crash cooldown)
GOAWAY_META = "goaway"
#: chunk meta: the server refused a RESUME request (signature/digest/
#: shape mismatch) with a typed terminal chunk instead of an error —
#: the server pipeline survives, the client tries elsewhere
RESUME_REJECT_META = "resume_reject"


def prompt_digest(prompt) -> str:
    """Stable digest of a normalized (1, Tp) int32 prompt: the resumed
    server verifies the prefix it is asked to re-prefill belongs to THIS
    prompt (a mismatched resume must refuse, not decode garbage)."""
    import numpy as np

    a = np.ascontiguousarray(np.asarray(prompt, dtype=np.int32))
    h = hashlib.sha1()
    h.update(str(a.shape).encode())
    h.update(a.tobytes())
    return h.hexdigest()


#: default shared-prefix chunk grain (tokens).  Chosen as a multiple of
#: the slot engine's default ``prefill_chunk`` (32): a warm attach must
#: leave the REMAINING prompt on the exact chunk grid a cold run would
#: have used, or XLA program identity (and thus bit-exactness) breaks.
#: Servers round their configured grain UP to a prefill_chunk multiple;
#: clients only need a consistent value to compute the same route key.
PREFIX_GRAIN = 64


def prefix_digests(prompt, grain: int) -> list:
    """Chain digests at every FULL ``grain``-token boundary of a
    normalized (1, Tp) int32 prompt: ``d_0 = H(g, 0, chunk_0)``,
    ``d_i = H(d_{i-1}, g, i, chunk_i)``.

    Each digest identifies its chunk AND the chunk's entire left
    context — KV pages for positions ``[i*g, (i+1)*g)`` depend on every
    token before them, so a flat per-chunk hash would alias pages from
    different prefixes.  The trailing partial chunk (and the final
    token, which must always be prefilled to produce first-token
    logits) gets no digest."""
    import numpy as np

    a = np.ascontiguousarray(np.asarray(prompt, dtype=np.int32))
    a = a.reshape(1, -1)
    g = max(1, int(grain))
    out = []
    prev = b""
    for i in range(int(a.shape[1]) // g):
        h = hashlib.sha1()
        h.update(prev)
        h.update(f"|g={g}|i={i}|".encode())
        h.update(a[:, i * g:(i + 1) * g].tobytes())
        d = h.hexdigest()
        out.append(d)
        prev = d.encode()
    return out


def prefix_route_key(prompt, grain: int = PREFIX_GRAIN,
                     declared: int = 0) -> str:
    """Fleet routing key for ``affinity-key=prefix``: the chain digest of
    the prompt's shared-prefix region, so every prompt sharing that
    prefix rendezvous-hashes (``core/routing.py``) to the SAME server
    and the prefix cache actually hits at fleet scale.

    ``declared`` is the client-declared prefix length in tokens (0 =
    undeclared: assume the first grain is the shared region).  Prompts
    shorter than one grain fall back to the whole-prompt digest — they
    can never share cached pages, so spreading them is correct."""
    import numpy as np

    a = np.ascontiguousarray(np.asarray(prompt, dtype=np.int32))
    a = a.reshape(1, -1)
    g = max(1, int(grain))
    n = int(declared) if declared else g
    k = min(max(0, n), int(a.shape[1])) // g
    if k <= 0:
        return prompt_digest(a)
    return prefix_digests(a[:, :k * g], g)[-1]


def resume_signature(kind: str, **cfg: Any) -> str:
    """Opaque signature of everything that determines the TOKEN sequence
    (model family + params seed + sampling rule + generation length).
    Servers stamp it on chunks and verify it on resume; clients only
    echo it — two servers produce interchangeable streams iff their
    signatures match."""
    h = hashlib.sha1()
    h.update(kind.encode())
    for k in sorted(cfg):
        h.update(f"|{k}={cfg[k]}".encode())
    return h.hexdigest()


class ChunkVerdict:
    """What :meth:`StreamContinuity.accept` decided about one incoming
    chunk: the (possibly trimmed/renumbered) frame to emit downstream
    (or None), how many duplicate tokens were dropped, and whether the
    chunk was a migration handoff, a resume rejection, or the stream's
    true completion."""

    __slots__ = ("emit", "dup", "handoff", "finished", "reject")

    def __init__(self):
        self.emit = None
        self.dup = 0
        self.handoff = False
        self.finished = False
        self.reject: Optional[str] = None


class StreamContinuity:
    """Client-side ledger of ONE logical generation stream across any
    number of servers.

    Feed every received chunk through :meth:`accept`; it passes
    non-resumable streams through untouched (``capable`` stays False and
    the legacy no-replay semantics apply).  Once a chunk carries
    :data:`RESUME_META` the ledger latches the stream's signature /
    digest / chunk size, accumulates the delivered tokens, renumbers
    emitted ``chunk_index`` contiguously, and dedupes any re-decoded
    overlap after a resume.  :meth:`build_resume_frame` produces the
    RESUME request for the next attempt."""

    __slots__ = (
        "frame", "capable", "sig", "digest", "chunk", "delivered",
        "duplicates_dropped", "emit_idx", "_tokens", "_stream_seq",
        "_handoff",
    )

    def __init__(self, frame):
        self.frame = frame
        self.capable = False
        self.sig = ""
        self.digest = ""
        self.chunk = 1
        self.delivered = 0          # tokens delivered downstream
        self.duplicates_dropped = 0
        self.emit_idx = 0           # contiguous downstream chunk numbering
        self._tokens = []           # np (1, n) pieces, concat == delivered
        self._stream_seq = None     # latched: one seq for the whole stream
        self._handoff = False

    def accept(self, ans) -> ChunkVerdict:
        """Classify one received chunk and compute what (if anything) to
        emit downstream.  Exactly-once contract: tokens past the
        ledger's ``delivered`` mark are new (emitted + appended), tokens
        at or below it are duplicates from a post-resume overlap
        (dropped + counted)."""
        import numpy as np

        v = ChunkVerdict()
        meta = ans.meta
        rj = meta.get(RESUME_REJECT_META)
        if rj is not None:
            v.reject = str(rj)
            return v
        rs = meta.get(RESUME_META)
        if rs is not None and not self.capable:
            try:
                self.sig = str(rs["sig"])
                self.digest = str(rs["digest"])
                self.chunk = max(1, int(rs["chunk"]))
                self.capable = True
            except (KeyError, TypeError, ValueError):
                self.capable = False
        if not self.capable:
            # legacy / non-generator stream: emit untouched
            v.emit = ans
            v.finished = bool(meta.get("final", True))
            return v
        toks = None
        n = 0
        if ans.tensors:
            toks = np.asarray(ans.tensors[0])
            if toks.ndim == 1:
                toks = toks[None]
            n = int(toks.shape[1])
        done = meta.get("tokens_done")
        done = int(done) if done is not None else self.delivered + n
        start = done - n  # this chunk covers tokens (start, done]
        final = bool(meta.get("final", True))
        handoff = final and bool(meta.get(GOAWAY_META))
        dup = min(max(0, self.delivered - start), n)
        if dup:
            self.duplicates_dropped += dup
            v.dup = dup
            toks = toks[:, dup:]
            n -= dup
        if n > 0:
            self._tokens.append(np.ascontiguousarray(toks, dtype=np.int32))
            if done > self.delivered:
                self.delivered = done
        v.handoff = handoff
        if handoff:
            self._handoff = True
        v.finished = final and not handoff
        if n > 0 or v.finished:
            out = ans.with_tensors(
                [np.ascontiguousarray(toks, dtype=np.int32)] if n > 0
                else [])
            # contiguous downstream view across migrations: one chunk
            # numbering, one stream_seq, cumulative tokens_done; the
            # handoff markers never leave the client
            out.meta["chunk_index"] = self.emit_idx
            self.emit_idx += 1
            out.meta["tokens_done"] = self.delivered
            out.meta["final"] = v.finished
            if self._stream_seq is None:
                self._stream_seq = out.meta.get("stream_seq")
            elif "stream_seq" in out.meta:
                out.meta["stream_seq"] = self._stream_seq
            if handoff:
                out.meta.pop(GOAWAY_META, None)
                out.meta.pop("evicted", None)
            v.emit = out
        return v

    def take_handoff(self) -> bool:
        """True once after a handoff chunk arrived (migration trigger)."""
        h, self._handoff = self._handoff, False
        return h

    def resume_point(self) -> int:
        """Where the next attempt resumes: the last FULL chunk boundary
        at or below ``delivered``.  Snapping down keeps the resumed
        server's chunk grid aligned with an uninterrupted run; the
        overlap (partial tokens past the boundary that were already
        delivered) is re-decoded and deduped by :meth:`accept`."""
        return (self.delivered // self.chunk) * self.chunk

    def build_resume_frame(self):
        """The RESUME request for the next attempt: tensors = [original
        prompt, generated prefix (1, R)], meta = the original request's
        meta (trace id, tenant, priority, deadline, affinity key all
        carry over) plus :data:`RESUME_REQ_META`."""
        import numpy as np

        from .buffer import TensorFrame

        if not self.capable:
            raise RuntimeError("stream carries no resume state")
        total = (np.concatenate(self._tokens, axis=1) if self._tokens
                 else np.zeros((1, 0), np.int32))
        if int(total.shape[1]) != self.delivered:
            # the ledger lost coherence (out-of-order / gapped chunks):
            # resuming could violate exactly-once — refuse loudly
            self.capable = False
            raise RuntimeError(
                f"resume ledger incoherent: {total.shape[1]} tokens held "
                f"vs {self.delivered} delivered")
        r = self.resume_point()
        meta: Dict[str, Any] = dict(self.frame.meta)
        meta[RESUME_REQ_META] = {
            "v": 1, "sig": self.sig, "digest": self.digest,
            "chunk": int(self.chunk), "tokens_done": int(r),
        }
        tensors = [np.asarray(self.frame.tensors[0])]
        if r > 0:
            tensors.append(
                np.ascontiguousarray(total[:, :r], dtype=np.int32))
        # r == 0 (broken before the first full chunk): a fresh full
        # replay — NO prefix tensor, because the wire refuses (1, 0)
        # shapes and the server's resume validation expects the prefix
        # only when tokens_done > 0
        return TensorFrame(tensors, meta=meta)
