"""Async device feed: the completion-driven dispatch window and the
double-buffered host->device staging lane.

The problem both pieces attack is the same (ROADMAP item 1): the filter
hot path used to *block the dispatch thread* on device I/O — once the
in-flight window filled it sat inside the oldest batch's ``device_get``,
and every host-sourced batch paid its host->device transfer inline before
dispatch.  Either wait idles the only thread that can stack and dispatch
the next batch, so depth-4 pipelining barely beat depth-1 on TPU
(BENCH_r05: 1821 vs 1806 fps against a 13.5k fps raw ceiling).

* :class:`CompletionWindow` parks dispatched micro-batches FIFO and hands
  the blocking device->host materialization to a dedicated **reaper
  thread** per window (≙ one per fused filter segment).  The dispatch
  thread only ever *polls* completed entries off the front; when the
  window is full it waits on a completion event — never inside
  ``device_get`` — and the wait is cooperatively interruptible, which the
  old in-C blocking sync was not.
* :class:`HostStagingLane` runs host-side batch stacking and the
  (async) ``device_put`` on a lane worker thread, double-buffered through
  :class:`~.buffer.DeviceBufferPool` staging arrays: while batch k
  computes, batch k+1 is stacked and its transfer issued.  The filter
  defers dispatch by exactly one batch, so by the time it needs batch k's
  device arrays the transfer has been overlapping with k-1's compute.

Emission order stays strictly FIFO through both; drain()/stop()/hot-swap
boundary contracts account every parked frame (the filter's
``pending_frames`` hook sums window payloads plus the staged batch).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from .buffer import DEVICE_POOL, materialize as _materialize
from .liveness import ThreadBeat
from .telemetry import Log2Histogram


class _WindowEntry:
    __slots__ = ("out_b", "payload", "mats", "error", "done", "claimed",
                 "t_park")

    def __init__(self, out_b, payload):
        self.out_b = out_b
        self.payload = payload
        self.mats: Optional[List[np.ndarray]] = None
        self.error: Optional[BaseException] = None
        self.done = False
        self.claimed = False
        self.t_park = time.perf_counter()


class CompletionWindow:
    """FIFO window of in-flight micro-batches, drained by completion.

    ``park()`` appends a dispatched batch's (device) outputs; a lazy
    **reaper thread** materializes entries strictly in park order — the
    blocking device->host sync happens there, overlapped with whatever
    the dispatch thread does next.  ``pop_ready()`` returns the completed
    prefix without blocking; ``wait_oldest()`` is the bounded backpressure
    wait for a full window (completion-event wait, not ``device_get``).

    A materialization error is stored on its entry and re-raised from
    ``pop_ready()`` on the *dispatch* thread, once the completed entries
    ahead of it have been handed out — so supervision attributes the
    failure to the owning element exactly as a synchronous invoke error.

    ``clear()`` discards all entries (Flush semantics); a reaper mid-sync
    on a cleared entry finishes harmlessly into the discarded carcass.
    ``close()`` additionally stops the reaper thread; a later ``park()``
    transparently reopens (restart-after-stop).
    """

    __slots__ = ("name", "_materialize", "_dq", "_cv", "_reaper", "_closed",
                 "reaped", "dispatch_waits", "dwell", "heartbeat")

    def __init__(self, name: str = "window",
                 materialize: Optional[Callable] = None):
        self.name = name
        self._materialize = materialize or _materialize
        self._dq: "deque[_WindowEntry]" = deque()
        self._cv = threading.Condition()
        self._reaper: Optional[threading.Thread] = None
        self._closed = False
        # background-thread liveness: the reaper beats once per loop —
        # a reaper with parked entries and a stale beat is wedged
        # inside a device sync (named-thread census in filter health)
        self.heartbeat = ThreadBeat(f"{name}-reaper")
        # stats (exact under the cv; perf smoke reads them)
        self.reaped = 0
        self.dispatch_waits = 0
        # park -> pop_ready dwell distribution (always on: one
        # perf_counter per micro-batch pop, off the per-frame path;
        # single-writer — only the dispatch thread pops)
        self.dwell = Log2Histogram()

    def __len__(self) -> int:
        return len(self._dq)

    def park(self, out_b: Sequence[Any], payload: Any) -> None:
        with self._cv:
            self._closed = False
            self._dq.append(_WindowEntry(out_b, payload))
            if self._reaper is None or not self._reaper.is_alive():
                self._reaper = threading.Thread(
                    target=self._reap_loop,
                    name=f"{self.name}-reaper", daemon=True,
                )
                self.heartbeat.bind(self._reaper)
                self.heartbeat.beat()
                self._reaper.start()
            self._cv.notify_all()

    def _reap_loop(self) -> None:
        while True:
            self.heartbeat.beat()
            with self._cv:
                entry = None
                while entry is None:
                    if self._closed:
                        return
                    for cand in self._dq:
                        if not cand.claimed:
                            entry = cand
                            break
                    if entry is None:
                        self._cv.wait()
                entry.claimed = True
            # beat AFTER claiming, before the blocking sync: the loop-top
            # beat precedes an unbounded idle wait, so without this a
            # healthy first job after a long idle would show the exact
            # stale-beat-while-busy signature the census calls wedged
            self.heartbeat.beat()
            try:
                mats = self._materialize(entry.out_b)
                err = None
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — crosses threads
                mats, err = None, e
            with self._cv:
                entry.mats, entry.error, entry.done = mats, err, True
                entry.out_b = None  # device refs released as soon as synced
                self.reaped += 1
                self._cv.notify_all()

    def pop_ready(self) -> List[Tuple[Optional[List[np.ndarray]], Any]]:
        """(materialized outputs, payload) for every completed entry at
        the FRONT of the window, in order; never blocks.  An errored
        entry at the front raises (after any completed entries ahead of
        it were returned by the previous call)."""
        popped: List[_WindowEntry] = []
        err: Optional[BaseException] = None
        with self._cv:
            while self._dq and self._dq[0].done:
                if self._dq[0].error is not None:
                    if popped:
                        break  # deliver the good prefix first
                    err = self._dq.popleft().error
                    break
                popped.append(self._dq.popleft())
        if err is not None:
            raise err
        if popped:
            now = time.perf_counter()
            for e in popped:
                self.dwell.record(now - e.t_park)
        return [(e.mats, e.payload) for e in popped]

    def oldest_ready(self) -> bool:
        with self._cv:
            return not self._dq or self._dq[0].done

    def wait_oldest(self, timeout: float = 0.1) -> bool:
        """Bounded wait for the oldest entry's completion EVENT (the
        backpressure path for a full window).  True when the front is
        ready (or the window emptied)."""
        with self._cv:
            if self._dq and not self._dq[0].done:
                self.dispatch_waits += 1
            return self._cv.wait_for(
                lambda: not self._dq or self._dq[0].done, timeout=timeout
            )

    def payloads(self) -> List[Any]:
        """Snapshot of parked payloads, oldest first (drain accounting)."""
        with self._cv:
            return [e.payload for e in self._dq]

    def clear(self) -> List[Any]:
        """Discard every parked entry (Flush); returns their payloads."""
        with self._cv:
            dropped = [e.payload for e in self._dq]
            self._dq.clear()
            self._cv.notify_all()
        return dropped

    def close(self) -> None:
        """Drop all entries and stop the reaper thread (element stop)."""
        with self._cv:
            self._dq.clear()
            self._closed = True
            self._cv.notify_all()
            reaper, self._reaper = self._reaper, None
        if reaper is not None and reaper.is_alive():
            reaper.join(timeout=2.0)


class StagedBatch:
    """Handle for one in-flight staging job: the lane thread stacks the
    frames into pooled staging buffers, runs ``to_device`` (which must
    return only once the buffer contents are fully copied/staged — the
    aliasing rule below), releases the buffers back to the pool, and
    publishes the device arrays here.  The dispatch thread collects them
    via :meth:`wait` / :meth:`result`; ``discard()`` drops the result of
    a job whose batch will never be dispatched (Flush/stop)."""

    __slots__ = ("_cv", "_dev", "_err", "_done", "_discarded")

    def __init__(self):
        self._cv = threading.Condition()
        self._dev: Optional[List[Any]] = None
        self._err: Optional[BaseException] = None
        self._done = False
        self._discarded = False

    # -- lane side ----------------------------------------------------------
    def _finish(self, dev, err) -> None:
        with self._cv:
            self._dev = None if self._discarded else dev
            self._err = err
            self._done = True
            self._cv.notify_all()

    # -- dispatch side ------------------------------------------------------
    def wait(self, timeout: Optional[float] = None) -> bool:
        with self._cv:
            return self._cv.wait_for(lambda: self._done, timeout=timeout)

    def result(self) -> List[Any]:
        """The staged device arrays; raises the staging error if any.
        Callers wanting interruptibility poll :meth:`wait` first."""
        with self._cv:
            self._cv.wait_for(lambda: self._done)
            if self._err is not None:
                raise self._err
            return self._dev

    def discard(self) -> None:
        """The job's batch will never be dispatched (Flush/stop): drop
        the device references as soon as they exist."""
        with self._cv:
            self._discarded = True
            self._dev = None


class HostStagingLane:
    """Double-buffered host->device staging on a dedicated lane thread.

    ``submit(per_frame_tensors)`` enqueues one micro-batch: the lane
    thread stacks each tensor index into a pooled staging buffer
    (``np.stack(..., out=buf)`` — no per-batch allocation once warm) and
    calls ``to_device`` (the backend's placement hook) on the stacked
    buffers.  The dispatch thread collects the device arrays one batch
    *later* (the filter's staged double-buffer), so the transfer overlaps
    the previous batch's compute instead of serializing with it.

    Aliasing rule: ``to_device`` must return only once the buffer
    contents have been fully copied/staged off the host arrays (jax-xla
    runs ``device_put`` + ``block_until_ready`` ON THE LANE THREAD — the
    wait is exactly the overlapped transfer).  The lane releases each
    staging buffer back to the pool the moment ``to_device`` returns, so
    steady state reuses the same ring of buffers with zero allocations.
    """

    __slots__ = ("name", "_to_device", "_pool", "_placement", "_q", "_cv",
                 "_worker", "_closed", "staged", "heartbeat")

    def __init__(self, to_device: Callable[[List[np.ndarray]], List[Any]],
                 pool=None, name: str = "lane", placement=None):
        self.name = name
        self._to_device = to_device
        self._pool = pool if pool is not None else DEVICE_POOL
        # placement-domain token (FilterBackend.staging_placement): the
        # pool keys its rings on it so this lane's buffers never recycle
        # into a lane staging for a different device/mesh
        self._placement = placement
        self._q: "deque[Tuple[StagedBatch, List[List[np.ndarray]]]]" = deque()
        self._cv = threading.Condition()
        self._worker: Optional[threading.Thread] = None
        self._closed = False
        self.staged = 0  # stats
        # background-thread liveness: the worker beats once per job —
        # a lane with work and a stale beat is wedged inside to_device
        # (named-thread census in filter health)
        self.heartbeat = ThreadBeat(f"{name}-stage")

    def submit(self, per_frame: List[List[np.ndarray]]) -> StagedBatch:
        """Stage one micro-batch: ``per_frame`` is a list of per-frame
        tensor lists (all host arrays, uniform shapes/dtypes)."""
        job = StagedBatch()
        with self._cv:
            self._closed = False
            self._q.append((job, per_frame))
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._run, name=f"{self.name}-stage", daemon=True,
                )
                self.heartbeat.bind(self._worker)
                self.heartbeat.beat()
                self._worker.start()
            self._cv.notify_all()
        return job

    def _run(self) -> None:
        while True:
            self.heartbeat.beat()
            with self._cv:
                while not self._q:
                    if self._closed:
                        return
                    self._cv.wait()
                job, per_frame = self._q.popleft()
            # beat after the (possibly long-idle) dequeue — see the
            # reaper's matching comment
            self.heartbeat.beat()
            bufs: List[np.ndarray] = []
            try:
                n = len(per_frame)
                ntensors = len(per_frame[0])
                for t in range(ntensors):
                    rows = [pf[t] for pf in per_frame]
                    a0 = np.asarray(rows[0])
                    buf = self._pool.acquire(
                        (n,) + a0.shape, a0.dtype,
                        placement=self._placement)
                    np.stack([np.asarray(r) for r in rows], out=buf)
                    bufs.append(buf)
                dev = self._to_device(bufs)
                self.staged += 1
                job._finish(list(dev), None)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — crosses threads
                job._finish(None, e)
            finally:
                # to_device returned (or failed): the staging buffers are
                # no longer readable by anyone — back to the ring
                for b in bufs:
                    self._pool.release(b, placement=self._placement)

    def pending(self) -> int:
        with self._cv:
            return len(self._q)

    def close(self) -> None:
        with self._cv:
            abandoned = [job for job, _ in self._q]
            self._q.clear()
            self._closed = True
            self._cv.notify_all()
            worker, self._worker = self._worker, None
        for job in abandoned:
            job._finish(None, RuntimeError("staging lane closed"))
        if worker is not None and worker.is_alive():
            worker.join(timeout=2.0)
