"""Hardware capability probe.

Reference: ``gst/nnstreamer/hw_accel.c`` (runtime NEON/SIMD detection via
hwcap, 64 LoC) — used to pick accelerated code paths.  The TPU analog
probes the XLA backend: platform, device kind/count, and whether a real
accelerator (vs host CPU) is attached; backends use it to choose dtypes
(bfloat16 on TPU) and batching defaults.

The probe is time-bounded: remote/tunneled accelerator backends can hang
indefinitely inside device enumeration (an uninterruptible C call), and a
capability *probe* must never wedge the caller — tools like confchk run it
on hosts whose accelerator may be unreachable.  On timeout the probe
reports an unaccelerated host so callers degrade to CPU defaults.
"""

from __future__ import annotations

import os
import threading
from typing import Dict

_cache: Dict[str, object] = {}
_cache_lock = threading.Lock()
_neg_cache: Dict[str, object] = {}  # last failed probe result
_neg_cache_ts = 0.0
_NEG_TTL_S = 60.0  # re-probe failures after this (the tunnel may recover)


_PROBE_SRC = (
    "import json, jax; d = jax.devices(); p = d[0].platform if d else 'none';"
    "print('HWPROBE ' + json.dumps({'platform': p,"
    "'device_kind': d[0].device_kind if d else 'none',"
    "'num_devices': len(d), 'accelerated': p not in ('cpu', 'none'),"
    "'devices': [str(x) for x in d]}))"
)


def _fail(err: str) -> Dict[str, object]:
    return {
        "platform": "none",
        "device_kind": "none",
        "num_devices": 0,
        "accelerated": False,
        "devices": [],
        "error": err,
    }


def _query_devices(timeout_s: float) -> Dict[str, object]:
    """Enumerate devices from a THROWAWAY subprocess.

    Never in-process: a wedged ``jax.devices()`` holds jax's global
    backend lock, so a parked probe thread would block every later jax
    call in the process — the exact hang the probe exists to prevent.  A
    subprocess is killable and leaves this process's jax state untouched.
    """
    import json
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return _fail(f"device probe timed out after {timeout_s:.0f}s")
    except OSError as e:
        return _fail(f"device probe failed to launch: {e}")
    for line in reversed(r.stdout.splitlines()):
        if line.startswith("HWPROBE "):
            return json.loads(line[len("HWPROBE "):])
    tail = (r.stderr or r.stdout).strip().splitlines()
    return _fail(
        f"device probe rc={r.returncode}: {tail[-1] if tail else 'no output'}"
    )


def probe(timeout_s: float = None) -> Dict[str, object]:
    """One-time device probe: {'platform', 'device_kind', 'num_devices',
    'accelerated', 'devices'[, 'error']}.

    Successful results are cached for the process; timeouts are NOT, so a
    backend that comes up later is still discovered.
    """
    global _neg_cache_ts
    import time

    with _cache_lock:
        if _cache:
            return dict(_cache)
        # failures are cached with a TTL: a host whose backend is broken
        # must not pay a multi-second subprocess probe on EVERY model
        # build, but a recovering tunnel is still re-discovered
        if _neg_cache and time.monotonic() - _neg_cache_ts < _NEG_TTL_S:
            return dict(_neg_cache)
    if timeout_s is None:
        timeout_s = float(os.environ.get("NNS_TPU_HW_PROBE_TIMEOUT", "30"))
    result = _query_devices(timeout_s)
    with _cache_lock:
        if "error" in result:
            _neg_cache.clear()
            _neg_cache.update(result)
            _neg_cache_ts = time.monotonic()
        else:
            _cache.update(result)
    return dict(result)


def reset() -> None:
    """Drop the cached probe (tests / after backend reconfiguration)."""
    global _neg_cache_ts
    with _cache_lock:
        _cache.clear()
        _neg_cache.clear()
        _neg_cache_ts = 0.0


def has_accelerator() -> bool:
    return bool(probe()["accelerated"])


def preferred_dtype() -> str:
    """bfloat16 on accelerators (MXU-native), float32 on host CPU."""
    return "bfloat16" if has_accelerator() else "float32"
