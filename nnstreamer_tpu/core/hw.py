"""Hardware capability probe.

Reference: ``gst/nnstreamer/hw_accel.c`` (runtime NEON/SIMD detection via
hwcap, 64 LoC) — used to pick accelerated code paths.  The TPU analog
probes the XLA backend: platform, device kind/count, and whether a real
accelerator (vs host CPU) is attached; backends use it to choose dtypes
(bfloat16 on TPU) and batching defaults.

The probe is time-bounded: remote/tunneled accelerator backends can hang
indefinitely inside device enumeration (an uninterruptible C call), and a
capability *probe* must never wedge the caller — tools like confchk run it
on hosts whose accelerator may be unreachable.  On timeout the probe
reports an unaccelerated host so callers degrade to CPU defaults.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Dict

_cache: Dict[str, object] = {}
_cache_lock = threading.Lock()


def _query_devices(out: "queue.Queue") -> None:
    try:
        import jax

        devs = jax.devices()
        platform = devs[0].platform if devs else "none"
        out.put({
            "platform": platform,
            "device_kind": devs[0].device_kind if devs else "none",
            "num_devices": len(devs),
            "accelerated": platform not in ("cpu", "none"),
            "devices": [str(d) for d in devs],
        })
    except Exception as e:  # backend init failure = no accelerator
        out.put({
            "platform": "none",
            "device_kind": "none",
            "num_devices": 0,
            "accelerated": False,
            "devices": [],
            "error": f"{type(e).__name__}: {e}",
        })


def probe(timeout_s: float = None) -> Dict[str, object]:
    """One-time device probe: {'platform', 'device_kind', 'num_devices',
    'accelerated', 'devices'[, 'error']}.

    Successful results are cached for the process; timeouts are NOT, so a
    backend that comes up later is still discovered.
    """
    with _cache_lock:
        if _cache:
            return dict(_cache)
    if timeout_s is None:
        timeout_s = float(os.environ.get("NNS_TPU_HW_PROBE_TIMEOUT", "30"))
    out: "queue.Queue" = queue.Queue()
    t = threading.Thread(target=_query_devices, args=(out,), daemon=True)
    t.start()
    try:
        result = out.get(timeout=timeout_s)
    except queue.Empty:
        # leave the stuck enumeration thread parked (daemon); report an
        # unaccelerated host but do not cache — the tunnel may recover
        return {
            "platform": "none",
            "device_kind": "none",
            "num_devices": 0,
            "accelerated": False,
            "devices": [],
            "error": f"device probe timed out after {timeout_s:.0f}s",
        }
    with _cache_lock:
        _cache.update(result)
    return dict(result)


def reset() -> None:
    """Drop the cached probe (tests / after backend reconfiguration)."""
    with _cache_lock:
        _cache.clear()


def has_accelerator() -> bool:
    return bool(probe()["accelerated"])


def preferred_dtype() -> str:
    """bfloat16 on accelerators (MXU-native), float32 on host CPU."""
    return "bfloat16" if has_accelerator() else "float32"
