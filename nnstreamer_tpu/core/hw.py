"""Hardware capability probe.

Reference: ``gst/nnstreamer/hw_accel.c`` (runtime NEON/SIMD detection via
hwcap, 64 LoC) — used to pick accelerated code paths.  The TPU analog
probes the XLA backend: platform, device kind/count, and whether a real
accelerator (vs host CPU) is attached; backends use it to choose dtypes
(bfloat16 on TPU) and batching defaults.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Dict, List


@lru_cache(maxsize=1)
def probe() -> Dict[str, object]:
    """One-time device probe: {'platform', 'device_kind', 'num_devices',
    'accelerated', 'devices'}."""
    import jax

    devs = jax.devices()
    platform = devs[0].platform if devs else "none"
    return {
        "platform": platform,
        "device_kind": devs[0].device_kind if devs else "none",
        "num_devices": len(devs),
        "accelerated": platform not in ("cpu", "none"),
        "devices": [str(d) for d in devs],
    }


def has_accelerator() -> bool:
    return bool(probe()["accelerated"])


def preferred_dtype() -> str:
    """bfloat16 on accelerators (MXU-native), float32 on host CPU."""
    return "bfloat16" if has_accelerator() else "float32"
