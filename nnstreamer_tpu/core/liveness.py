"""Liveness primitives: stall watchdog, deadline QoS, admission control.

PR-1's supervision layer (``core/resilience.py``) handles elements that
*crash*.  This module covers the failures that never raise: an element
that silently hangs, a frame that arrives too late to matter, and a
query server drowning in more in-flight work than it can serve.
Reference analogs: GStreamer QoS events (``gsttensor_rate.c`` throttle
feedback) and queue watermarks; the serving-stack version detects
stalls, sheds late work deterministically, and refuses overload at
admission instead of timing out deep in the stack.

Design rules (same as resilience.py):

* **Injectable time.**  ``Watchdog`` and the deadline helpers take
  ``clock`` so tests run on a fake clock.
* **Zero hot-path cost when idle.**  Heartbeat pings are two attribute
  stores; the deadline check is one dict lookup on frames that carry no
  deadline.
* **Cooperative interruption.**  A hung call cannot be killed from
  outside; escalation sets the element's interrupt flag and relies on
  the hung site (an armed ``hang=`` fault, a backend polling
  ``Element.interrupted``) to surface :class:`StallError`, which the
  scheduler's restart machinery then handles like any transient fault.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .log import get_logger
from .resilience import RemoteApplicationError, TransientError

log = get_logger("liveness")


class StallError(TransientError):
    """A hung call was interrupted by the liveness layer.

    Subclasses :class:`TransientError`: a stall is exactly the failure
    class a restart can cure, so ``error-policy=restart`` /
    ``stall-policy=restart`` treat it as retryable."""


class ServerBusyError(RemoteApplicationError):
    """The server refused the request at ADMISSION (load shed).

    Subclasses :class:`RemoteApplicationError`: the server answered, so
    breakers/cooldowns must not count it against the remote's health.
    Admission-refused requests provably never executed, which makes a
    resend safe even under at-most-once delivery — clients retry these
    on a RetryPolicy-paced budget separate from ``retries``."""

    def __init__(self, msg: str = "server busy", retry_after: float = 0.05):
        super().__init__(msg)
        self.retry_after = float(retry_after)


# ---------------------------------------------------------------------------
# Deadline QoS
# ---------------------------------------------------------------------------
#: frame.meta key holding the absolute expiry instant on the LOCAL
#: monotonic clock.  Process-local by design: monotonic instants are
#: meaningless on another host, so transports strip this key and carry a
#: remaining-budget DURATION on the wire instead (tcp_query header
#: ``deadline_s`` / gRPC ``context.time_remaining()``); the receiver
#: re-stamps on its own clock.
DEADLINE_META = "deadline_ts"


def stamp_deadline(
    frame: Any,
    budget_s: float,
    clock: Callable[[], float] = time.monotonic,
    anchor: Optional[float] = None,
) -> Any:
    """Stamp ``frame`` with an absolute deadline.

    Wall-anchored (``anchor=None``): expires ``budget_s`` from now —
    the serving contract ("answer within X of ingest").  Pts-anchored
    (``anchor`` = the stream epoch on this clock): expires at
    ``anchor + pts + budget_s`` — the live-playback contract (a frame
    due at pts is worthless ``budget_s`` after its slot)."""
    if anchor is not None and frame.pts is not None:
        frame.meta[DEADLINE_META] = anchor + frame.pts + float(budget_s)
    else:
        frame.meta[DEADLINE_META] = clock() + float(budget_s)
    return frame


def deadline_remaining(
    frame: Any, clock: Callable[[], float] = time.monotonic
) -> Optional[float]:
    """Seconds of budget left (may be negative); None = no deadline.
    Tolerates meta-less payloads (wire batches hand opaque objects
    through the same code paths)."""
    meta = getattr(frame, "meta", None)
    ts = meta.get(DEADLINE_META) if meta is not None else None
    if ts is None:
        return None
    return ts - clock()


def is_expired(
    frame: Any,
    now: Optional[float] = None,
    clock: Callable[[], float] = time.monotonic,
) -> bool:
    """True when the frame's budget is exhausted.

    Boundary contract (pinned by the deadline truth table test): a frame
    is DELIVERED while any budget remains and DROPPED from the instant
    ``now >= deadline`` — zero remaining budget cannot pay for any
    downstream work, so the boundary frame is already late."""
    meta = getattr(frame, "meta", None)
    ts = meta.get(DEADLINE_META) if meta is not None else None
    if ts is None:
        return False
    return (clock() if now is None else now) >= ts


# ---------------------------------------------------------------------------
# Watchdog
# ---------------------------------------------------------------------------
class _Watch:
    """Per-element watchdog entry: config + heartbeat + counters."""

    __slots__ = (
        "name", "stall_timeout", "frame_deadline", "policy", "qsize",
        "on_event", "busy_since", "last_progress", "frames_done",
        "stalls", "overruns", "_overrun_flagged", "_last_stall_flag",
    )

    def __init__(self, name, stall_timeout, frame_deadline, policy,
                 qsize, on_event, now):
        self.name = name
        self.stall_timeout = float(stall_timeout)
        self.frame_deadline = float(frame_deadline)
        self.policy = policy
        self.qsize = qsize
        self.on_event = on_event
        self.busy_since: Optional[float] = None
        self.last_progress = now
        self.frames_done = 0
        self.stalls = 0
        self.overruns = 0
        self._overrun_flagged: Optional[float] = None  # busy episode token
        self._last_stall_flag = float("-inf")


def _check_stall_policy(v: str) -> str:
    if v not in ("warn", "restart", "fail"):
        raise ValueError(f"stall-policy {v!r} (want warn | restart | fail)")
    return v


class Watchdog:
    """Per-element heartbeat registry + stall/overrun monitor.

    The scheduler pings :meth:`begin`/:meth:`done` around every frame
    call; :meth:`check` sweeps the registry and fires ``on_event(watch,
    kind, elapsed)`` for each finding:

    * ``"overrun"`` — a single call has been running longer than
      ``frame_deadline`` (the hung-``handle_frame`` case; flagged once
      per busy episode).
    * ``"stall"`` — work is pending (input queued, or a call in flight)
      but nothing has COMPLETED for ``stall_timeout``: covers both a
      hang inside a call and a worker wedged outside processing (e.g.
      blocked pushing downstream).  Re-flagged every ``stall_timeout``;
      an in-call hang that also overruns is reported as the overrun in
      that sweep (overrun wins the tie, once per episode).

    Passive by design: no thread of its own.  The pipeline polls
    :meth:`check` from a sweeper thread; tests call it directly on a
    fake clock.  Pings are lock-free (two attribute stores on the GIL —
    a torn read in the sweeper costs one late/spurious finding, never a
    crash), registration is locked."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._watches: Dict[str, _Watch] = {}

    def register(
        self,
        name: str,
        stall_timeout: float = 0.0,
        frame_deadline: float = 0.0,
        policy: str = "warn",
        qsize: Callable[[], int] = lambda: 0,
        on_event: Optional[Callable[[_Watch, str, float], None]] = None,
    ) -> _Watch:
        w = _Watch(name, stall_timeout, frame_deadline,
                   _check_stall_policy(policy), qsize, on_event,
                   self._clock())
        with self._lock:
            self._watches[name] = w
        return w

    def unregister(self, name: str) -> None:
        with self._lock:
            self._watches.pop(name, None)

    def watch(self, name: str) -> Optional[_Watch]:
        with self._lock:
            return self._watches.get(name)

    # -- heartbeat pings (hot path: no lock) --------------------------------
    def begin(self, w: Optional[_Watch]) -> None:
        if w is not None:
            w.busy_since = self._clock()

    def done(self, w: Optional[_Watch]) -> None:
        if w is not None:
            w.busy_since = None
            w.last_progress = self._clock()
            w.frames_done += 1
            w._overrun_flagged = None

    # -- monitor -------------------------------------------------------------
    def min_interval(self) -> float:
        """Suggested poll period: a quarter of the tightest armed bound."""
        with self._lock:
            bounds = [
                b for w in self._watches.values()
                for b in (w.stall_timeout, w.frame_deadline) if b > 0
            ]
        if not bounds:
            return 0.5
        return min(0.5, max(0.01, min(bounds) / 4.0))

    def check(self, now: Optional[float] = None) -> List[Tuple[str, str, float]]:
        """One sweep; returns ``[(element, kind, elapsed_s), ...]`` and
        fires each watch's ``on_event`` callback."""
        now = self._clock() if now is None else now
        with self._lock:
            watches = list(self._watches.values())
        findings: List[Tuple[str, str, float]] = []
        for w in watches:
            busy = w.busy_since
            if (busy is not None and w.frame_deadline > 0
                    and now - busy >= w.frame_deadline
                    and w._overrun_flagged != busy):
                w._overrun_flagged = busy  # once per episode
                w.overruns += 1
                findings.append((w.name, "overrun", now - busy))
                self._fire(w, "overrun", now - busy)
            elif (w.stall_timeout > 0
                    and now - w.last_progress >= w.stall_timeout
                    and now - w._last_stall_flag >= w.stall_timeout):
                # pending work = queued input OR a call in flight — an
                # element hung INSIDE handle_frame must be detectable by
                # stall-timeout alone (frame-deadline is the per-call
                # refinement, not a prerequisite)
                if busy is not None:
                    pending = 1
                else:
                    try:
                        pending = w.qsize()
                    except Exception:  # allow-silent: mailbox mid-teardown
                        pending = 0
                if pending > 0:
                    w._last_stall_flag = now
                    w.stalls += 1
                    elapsed = now - w.last_progress
                    findings.append((w.name, "stall", elapsed))
                    self._fire(w, "stall", elapsed)
        return findings

    def _fire(self, w: _Watch, kind: str, elapsed: float) -> None:
        log.warning(
            "watchdog: %s %s for %.3fs (policy=%s)",
            w.name, kind, elapsed, w.policy,
        )
        if w.on_event is not None:
            try:
                w.on_event(w, kind, elapsed)
            except Exception:
                log.exception("watchdog escalation for %s failed", w.name)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            watches = list(self._watches.values())
        return {
            w.name: {
                "busy": w.busy_since is not None,
                "frames_done": w.frames_done,
                "stalls": w.stalls,
                "overruns": w.overruns,
            }
            for w in watches
        }


# ---------------------------------------------------------------------------
# Admission control
# ---------------------------------------------------------------------------
class AdmissionController:
    """Bounded in-flight slots with high/low watermark hysteresis.

    ``try_admit`` refuses once ``high`` requests are in flight and keeps
    refusing until the backlog drains to ``low`` — the hysteresis band
    prevents admit/refuse flapping right at the limit (reference analog:
    GstQueue's high/low watermark signals).  ``high <= 0`` = unlimited
    (admission disabled; counters still track in-flight).

    Thread-safe; refusals are O(1) and allocation-free — the overload
    path must be the cheapest path in the server."""

    def __init__(self, high: int = 0, low: Optional[int] = None):
        self.high = int(high)
        if self.high > 0:
            # default low = high//2; an explicit 0 is legal and honored
            # (drain fully before re-admitting — the only choice when
            # high is 1)
            self.low = self.high // 2 if low is None else int(low)
            if not 0 <= self.low < self.high:
                # a negative low could never clear the shedding band:
                # the first overload would brick the server into BUSY
                raise ValueError(
                    f"low watermark {self.low} must be in [0, "
                    f"high={self.high})"
                )
        else:
            self.low = 0
        self._lock = threading.Lock()
        self._inflight = 0
        self._shedding = False
        self.admitted = 0
        self.shed = 0

    def try_admit(self, n: int = 1) -> bool:
        with self._lock:
            if self.high > 0:
                if self._shedding and self._inflight > self.low:
                    self.shed += n
                    return False
                if self._inflight + n > self.high:
                    self._shedding = True
                    self.shed += n
                    return False
                self._shedding = False
            self._inflight += n
            self.admitted += n
            return True

    def release(self, n: int = 1) -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - n)
            if self._shedding and self._inflight <= self.low:
                self._shedding = False

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "inflight": self._inflight,
                "high": self.high,
                "low": self.low,
                "shedding": self._shedding,
                "admitted": self.admitted,
                "shed": self.shed,
            }
