"""Liveness primitives: stall watchdog, deadline QoS, admission control.

PR-1's supervision layer (``core/resilience.py``) handles elements that
*crash*.  This module covers the failures that never raise: an element
that silently hangs, a frame that arrives too late to matter, and a
query server drowning in more in-flight work than it can serve.
Reference analogs: GStreamer QoS events (``gsttensor_rate.c`` throttle
feedback) and queue watermarks; the serving-stack version detects
stalls, sheds late work deterministically, and refuses overload at
admission instead of timing out deep in the stack.

Design rules (same as resilience.py):

* **Injectable time.**  ``Watchdog`` and the deadline helpers take
  ``clock`` so tests run on a fake clock.
* **Zero hot-path cost when idle.**  Heartbeat pings are two attribute
  stores; the deadline check is one dict lookup on frames that carry no
  deadline.
* **Cooperative interruption.**  A hung call cannot be killed from
  outside; escalation sets the element's interrupt flag and relies on
  the hung site (an armed ``hang=`` fault, a backend polling
  ``Element.interrupted``) to surface :class:`StallError`, which the
  scheduler's restart machinery then handles like any transient fault.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .log import get_logger
from .resilience import RemoteApplicationError, TransientError

log = get_logger("liveness")


class StallError(TransientError):
    """A hung call was interrupted by the liveness layer.

    Subclasses :class:`TransientError`: a stall is exactly the failure
    class a restart can cure, so ``error-policy=restart`` /
    ``stall-policy=restart`` treat it as retryable."""


class ServerBusyError(RemoteApplicationError):
    """The server refused the request at ADMISSION (load shed).

    Subclasses :class:`RemoteApplicationError`: the server answered, so
    breakers/cooldowns must not count it against the remote's health —
    tenant-quota refusals included (one tenant over ITS quota says
    nothing about the server's ability to serve anyone else).
    Admission-refused requests provably never executed, which makes a
    resend safe even under at-most-once delivery — clients retry these
    on a RetryPolicy-paced budget separate from ``retries``.

    ``tenant``/``reason`` identify WHY the shed happened (``"quota"`` =
    the tenant's own quota, ``"priority"`` = priority-class headroom,
    ``"load"`` = the global watermark, ``"memory"`` = the memory
    watermark — the chip is near HBM exhaustion, so the server sheds
    BEFORE it OOMs): diagnostics only, the client contract is identical
    for all four."""

    def __init__(self, msg: str = "server busy", retry_after: float = 0.05,
                 tenant: str = "", reason: str = "load"):
        super().__init__(msg)
        self.retry_after = float(retry_after)
        self.tenant = tenant
        self.reason = reason


# -- tenant identity on the wire --------------------------------------------
#: frame.meta key carrying the requesting tenant's name.  An ORDINARY
#: meta key (no TL_ prefix): it crosses both transports inside the JSON
#: meta blob, so per-tenant admission needs no wire-format change.
TENANT_META = "_nns_tenant"
#: frame.meta key carrying the request's priority class, 0..3 (3 =
#: highest).  Requests without it are treated as priority 3 — the exact
#: pre-tenancy admission semantics.
PRIORITY_META = "_nns_priority"
#: priority classes (inclusive bounds)
PRIORITY_MIN, PRIORITY_MAX = 0, 3


def clamp_priority(p) -> int:
    try:
        p = int(p)
    except (TypeError, ValueError):
        return PRIORITY_MAX
    return max(PRIORITY_MIN, min(PRIORITY_MAX, p))


# ---------------------------------------------------------------------------
# Deadline QoS
# ---------------------------------------------------------------------------
#: frame.meta key holding the absolute expiry instant on the LOCAL
#: monotonic clock.  Process-local by design: monotonic instants are
#: meaningless on another host, so transports strip this key and carry a
#: remaining-budget DURATION on the wire instead (tcp_query header
#: ``deadline_s`` / gRPC ``context.time_remaining()``); the receiver
#: re-stamps on its own clock.
DEADLINE_META = "deadline_ts"


def stamp_deadline(
    frame: Any,
    budget_s: float,
    clock: Callable[[], float] = time.monotonic,
    anchor: Optional[float] = None,
) -> Any:
    """Stamp ``frame`` with an absolute deadline.

    Wall-anchored (``anchor=None``): expires ``budget_s`` from now —
    the serving contract ("answer within X of ingest").  Pts-anchored
    (``anchor`` = the stream epoch on this clock): expires at
    ``anchor + pts + budget_s`` — the live-playback contract (a frame
    due at pts is worthless ``budget_s`` after its slot)."""
    if anchor is not None and frame.pts is not None:
        frame.meta[DEADLINE_META] = anchor + frame.pts + float(budget_s)
    else:
        frame.meta[DEADLINE_META] = clock() + float(budget_s)
    return frame


def deadline_remaining(
    frame: Any, clock: Callable[[], float] = time.monotonic
) -> Optional[float]:
    """Seconds of budget left (may be negative); None = no deadline.
    Tolerates meta-less payloads (wire batches hand opaque objects
    through the same code paths)."""
    meta = getattr(frame, "meta", None)
    ts = meta.get(DEADLINE_META) if meta is not None else None
    if ts is None:
        return None
    return ts - clock()


def is_expired(
    frame: Any,
    now: Optional[float] = None,
    clock: Callable[[], float] = time.monotonic,
) -> bool:
    """True when the frame's budget is exhausted.

    Boundary contract (pinned by the deadline truth table test): a frame
    is DELIVERED while any budget remains and DROPPED from the instant
    ``now >= deadline`` — zero remaining budget cannot pay for any
    downstream work, so the boundary frame is already late."""
    meta = getattr(frame, "meta", None)
    ts = meta.get(DEADLINE_META) if meta is not None else None
    if ts is None:
        return False
    return (clock() if now is None else now) >= ts


# ---------------------------------------------------------------------------
# Background-thread heartbeats
# ---------------------------------------------------------------------------
class ThreadBeat:
    """Watchdog heartbeat for one NAMED background framework thread
    (slot-engine pump, completion-window reaper, staging-lane worker).

    The owning thread calls :meth:`beat` once per loop iteration —
    lock-free, one clock read + two GIL-atomic stores (the watchdog-ping
    discipline) — and the element-side consumer asks
    :meth:`check_stall` ``(busy=...)`` from its dispatch thread: a
    thread that has WORK (``busy``) but has not beaten for
    ``stall_after_s`` is wedged (stuck inside a device call / C
    extension), which a sticky error can never surface because the
    thread never returns.  ``check_stall`` is edge-triggered — one True
    per stall episode — so the caller can fire a single flight-recorder
    incident instead of a dump storm.  :meth:`snapshot` feeds the
    named-thread census in ``health()``."""

    __slots__ = ("name", "stall_after_s", "_clock", "_last", "beats",
                 "stalls", "_flagged", "_thread")

    def __init__(self, name: str, stall_after_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.stall_after_s = float(stall_after_s)
        self._clock = clock
        self._last = clock()
        self.beats = 0
        self.stalls = 0
        self._flagged = False
        self._thread: Optional[threading.Thread] = None

    def bind(self, thread: Optional[threading.Thread]) -> None:
        """Attach the live Thread object (liveness census reads
        ``is_alive``)."""
        self._thread = thread

    def beat(self) -> None:
        self._last = self._clock()
        self.beats += 1  # single-writer: the beating thread itself

    def alive(self) -> bool:
        t = self._thread
        return bool(t is not None and t.is_alive())

    def age_s(self) -> float:
        return max(0.0, self._clock() - self._last)

    def check_stall(self, busy: bool) -> bool:
        """True ONCE per stall episode: the thread has pending work but
        has not beaten within ``stall_after_s``.  An idle thread (or a
        beat arriving again) re-arms the edge."""
        if not busy or self.age_s() < self.stall_after_s:
            self._flagged = False
            return False
        if self._flagged:
            return False
        self._flagged = True
        self.stalls += 1
        return True

    def snapshot(self) -> Dict[str, Any]:
        return {
            "alive": self.alive(),
            "age_s": round(self.age_s(), 3),
            "beats": self.beats,
            "stalls": self.stalls,
        }


def thread_census(*beats: Optional["ThreadBeat"]) -> Dict[str, Any]:
    """``health()`` census of an element's background threads: one row
    per :class:`ThreadBeat`, keyed by thread name (Nones skipped)."""
    return {b.name: b.snapshot() for b in beats if b is not None}


# ---------------------------------------------------------------------------
# Watchdog
# ---------------------------------------------------------------------------
class _Watch:
    """Per-element watchdog entry: config + heartbeat + counters."""

    __slots__ = (
        "name", "stall_timeout", "frame_deadline", "policy", "qsize",
        "on_event", "busy_since", "last_progress", "frames_done",
        "stalls", "overruns", "_overrun_flagged", "_last_stall_flag",
    )

    def __init__(self, name, stall_timeout, frame_deadline, policy,
                 qsize, on_event, now):
        self.name = name
        self.stall_timeout = float(stall_timeout)
        self.frame_deadline = float(frame_deadline)
        self.policy = policy
        self.qsize = qsize
        self.on_event = on_event
        self.busy_since: Optional[float] = None
        self.last_progress = now
        self.frames_done = 0
        self.stalls = 0
        self.overruns = 0
        self._overrun_flagged: Optional[float] = None  # busy episode token
        self._last_stall_flag = float("-inf")


def _check_stall_policy(v: str) -> str:
    if v not in ("warn", "restart", "fail"):
        raise ValueError(f"stall-policy {v!r} (want warn | restart | fail)")
    return v


class Watchdog:
    """Per-element heartbeat registry + stall/overrun monitor.

    The scheduler pings :meth:`begin`/:meth:`done` around every frame
    call; :meth:`check` sweeps the registry and fires ``on_event(watch,
    kind, elapsed)`` for each finding:

    * ``"overrun"`` — a single call has been running longer than
      ``frame_deadline`` (the hung-``handle_frame`` case; flagged once
      per busy episode).
    * ``"stall"`` — work is pending (input queued, or a call in flight)
      but nothing has COMPLETED for ``stall_timeout``: covers both a
      hang inside a call and a worker wedged outside processing (e.g.
      blocked pushing downstream).  Re-flagged every ``stall_timeout``;
      an in-call hang that also overruns is reported as the overrun in
      that sweep (overrun wins the tie, once per episode).

    Passive by design: no thread of its own.  The pipeline polls
    :meth:`check` from a sweeper thread; tests call it directly on a
    fake clock.  Pings are lock-free (two attribute stores on the GIL —
    a torn read in the sweeper costs one late/spurious finding, never a
    crash), registration is locked."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._watches: Dict[str, _Watch] = {}

    def register(
        self,
        name: str,
        stall_timeout: float = 0.0,
        frame_deadline: float = 0.0,
        policy: str = "warn",
        qsize: Callable[[], int] = lambda: 0,
        on_event: Optional[Callable[[_Watch, str, float], None]] = None,
    ) -> _Watch:
        w = _Watch(name, stall_timeout, frame_deadline,
                   _check_stall_policy(policy), qsize, on_event,
                   self._clock())
        with self._lock:
            self._watches[name] = w
        return w

    def unregister(self, name: str) -> None:
        with self._lock:
            self._watches.pop(name, None)

    def watch(self, name: str) -> Optional[_Watch]:
        with self._lock:
            return self._watches.get(name)

    # -- heartbeat pings (hot path: no lock) --------------------------------
    def begin(self, w: Optional[_Watch]) -> None:
        if w is not None:
            w.busy_since = self._clock()

    def done(self, w: Optional[_Watch]) -> None:
        if w is not None:
            w.busy_since = None
            w.last_progress = self._clock()
            w.frames_done += 1
            w._overrun_flagged = None

    # -- monitor -------------------------------------------------------------
    def min_interval(self) -> float:
        """Suggested poll period: a quarter of the tightest armed bound."""
        with self._lock:
            bounds = [
                b for w in self._watches.values()
                for b in (w.stall_timeout, w.frame_deadline) if b > 0
            ]
        if not bounds:
            return 0.5
        return min(0.5, max(0.01, min(bounds) / 4.0))

    def check(self, now: Optional[float] = None) -> List[Tuple[str, str, float]]:
        """One sweep; returns ``[(element, kind, elapsed_s), ...]`` and
        fires each watch's ``on_event`` callback."""
        now = self._clock() if now is None else now
        with self._lock:
            watches = list(self._watches.values())
        findings: List[Tuple[str, str, float]] = []
        for w in watches:
            busy = w.busy_since
            if (busy is not None and w.frame_deadline > 0
                    and now - busy >= w.frame_deadline
                    and w._overrun_flagged != busy):
                w._overrun_flagged = busy  # once per episode
                w.overruns += 1
                findings.append((w.name, "overrun", now - busy))
                self._fire(w, "overrun", now - busy)
            elif (w.stall_timeout > 0
                    and now - w.last_progress >= w.stall_timeout
                    and now - w._last_stall_flag >= w.stall_timeout):
                # pending work = queued input OR a call in flight — an
                # element hung INSIDE handle_frame must be detectable by
                # stall-timeout alone (frame-deadline is the per-call
                # refinement, not a prerequisite)
                if busy is not None:
                    pending = 1
                else:
                    try:
                        pending = w.qsize()
                    except Exception:  # allow-silent: mailbox mid-teardown
                        pending = 0
                if pending > 0:
                    w._last_stall_flag = now
                    w.stalls += 1
                    elapsed = now - w.last_progress
                    findings.append((w.name, "stall", elapsed))
                    self._fire(w, "stall", elapsed)
        return findings

    def _fire(self, w: _Watch, kind: str, elapsed: float) -> None:
        log.warning(
            "watchdog: %s %s for %.3fs (policy=%s)",
            w.name, kind, elapsed, w.policy,
        )
        if w.on_event is not None:
            try:
                w.on_event(w, kind, elapsed)
            except Exception:
                log.exception("watchdog escalation for %s failed", w.name)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            watches = list(self._watches.values())
        return {
            w.name: {
                "busy": w.busy_since is not None,
                "frames_done": w.frames_done,
                "stalls": w.stalls,
                "overruns": w.overruns,
            }
            for w in watches
        }


# ---------------------------------------------------------------------------
# Memory-pressure watermark monitor
# ---------------------------------------------------------------------------
def host_rss_bytes() -> int:
    """Resident set size of THIS process (bytes), from /proc (Linux) —
    no psutil dependency; 0 where unreadable."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return 0


def host_total_bytes() -> int:
    """Total physical memory of the host (bytes); 0 where unreadable.
    The default denominator of the host-RSS watermark fallback, so the
    monitor stays meaningful on platforms whose devices report no
    ``memory_stats()`` (CPU) without any explicit limit configured."""
    try:
        return os.sysconf("SC_PHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError):
        return 0


def device_memory_sample() -> Tuple[int, int, int]:
    """``(bytes_in_use, bytes_limit, host_rss)`` for the most-loaded
    visible accelerator (the fraction that matters is the worst chip's).

    Consults jax ONLY when the process already imported it (the monitor
    must never be the reason jax initializes), and tolerates platforms
    whose ``Device.memory_stats()`` is absent/None (CPU) — those report
    (0, 0, rss) and the monitor falls back to the host-RSS watermark."""
    import sys

    in_use = limit = 0
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            best = -1.0
            for d in jax.devices():
                ms = getattr(d, "memory_stats", None)
                stats = ms() if callable(ms) else None
                if not stats:
                    continue
                bl = int(stats.get("bytes_limit", 0) or 0)
                bi = int(stats.get("bytes_in_use", 0) or 0)
                frac = (bi / bl) if bl else 0.0
                if frac > best:
                    best, in_use, limit = frac, bi, bl
        except Exception:  # allow-silent: a stats probe must never fault serving
            in_use = limit = 0
    return in_use, limit, host_rss_bytes()


class MemoryPressureMonitor:
    """High/low-watermark HBM + host-RSS pressure signal (the "shed
    BUSY *before* the chip OOMs" piece of the degrade-don't-die ladder).

    Polled from slow cadences only — the watchdog sweeper thread and the
    serversrc's idle request-pump tick — never from a per-frame path:
    :meth:`poll` is internally rate-limited to ``min_poll_s`` and the
    hot-path read is the plain :attr:`pressured` attribute (one bool).

    State machine (hysteresis, the admission-controller discipline):
    the watermark FRACTION (device ``bytes_in_use/bytes_limit`` when the
    platform reports it, else host RSS over ``host_limit_bytes``,
    itself defaulting to the host's physical RAM so an armed watermark
    is never silently inert) crossing ``high`` enters pressure; it
    persists until the
    fraction falls back to ``low``.  Entering pressure fires the
    ``trim_hooks`` (frame pool, staging-buffer pool, backend compile
    caches — memory the process can recreate); pressure SUSTAINED for
    ``sustain_s`` fires ``on_pressure(snapshot)`` once per
    ``incident_interval_s`` (the serversrc routes it into the flight
    recorder, which attaches the PR-11 thread profiler).

    ``sample``/``clock`` are injectable — tier-1 drives the whole ladder
    on fake samples with a fake clock."""

    def __init__(self, high: float = 0.90, low: float = 0.75,
                 sustain_s: float = 2.0, min_poll_s: float = 0.25,
                 incident_interval_s: float = 30.0,
                 host_limit_bytes: int = 0,
                 sample: Callable[[], Tuple[int, int, int]] = device_memory_sample,
                 clock: Callable[[], float] = time.monotonic,
                 on_pressure: Optional[Callable[[Dict[str, Any]], None]] = None,
                 trim_hooks: Tuple[Callable[[], int], ...] = ()):
        if not 0.0 <= low <= high:
            raise ValueError(
                f"memory watermarks low={low} high={high} "
                "(want 0 <= low <= high)")
        self.high = float(high)
        self.low = float(low)
        self.sustain_s = float(sustain_s)
        self.min_poll_s = float(min_poll_s)
        self.incident_interval_s = float(incident_interval_s)
        self.host_limit_bytes = int(host_limit_bytes)
        self._sample = sample
        self._clock = clock
        self.on_pressure = on_pressure
        self.trim_hooks: List[Callable[[], int]] = list(trim_hooks)
        #: the hot-path signal: one GIL-atomic bool read (admission)
        self.pressured = False
        self._pressured_since: Optional[float] = None
        self._last_poll = float("-inf")
        self._last_incident = float("-inf")
        # last sample (scrape-time gauges)
        self.bytes_in_use = 0
        self.bytes_limit = 0
        self.host_rss = 0
        self.fraction = 0.0
        # exact accounting
        self.polls = 0
        self.trims = 0           # trim-hook sweeps fired
        self.trimmed_entries = 0  # entries the hooks reported freeing
        self.incidents = 0

    def add_trim_hook(self, hook: Callable[[], int]) -> None:
        self.trim_hooks.append(hook)

    def _fraction(self) -> float:
        if self.bytes_limit > 0:
            return self.bytes_in_use / self.bytes_limit
        if self.host_limit_bytes > 0:
            return self.host_rss / self.host_limit_bytes
        # stats-less platform, no explicit limit: RSS over physical RAM
        # (never silently inert — an armed watermark must watch SOMETHING)
        total = host_total_bytes()
        if total > 0:
            return self.host_rss / total
        return 0.0

    def poll(self, now: Optional[float] = None) -> bool:
        """One watermark evaluation (rate-limited; safe from any slow
        cadence).  Returns the post-poll :attr:`pressured` state."""
        now = self._clock() if now is None else now
        if now - self._last_poll < self.min_poll_s:
            return self.pressured
        self._last_poll = now
        self.polls += 1
        self.bytes_in_use, self.bytes_limit, self.host_rss = self._sample()
        self.fraction = self._fraction()
        if not self.pressured:
            if self.fraction >= self.high:
                self.pressured = True
                self._pressured_since = now
                self._trim()
                log.warning(
                    "memory pressure ENTERED: fraction %.3f >= high %.3f "
                    "(in_use=%d limit=%d rss=%d)", self.fraction,
                    self.high, self.bytes_in_use, self.bytes_limit,
                    self.host_rss)
        elif self.fraction <= self.low:
            self.pressured = False
            self._pressured_since = None
            log.info("memory pressure cleared: fraction %.3f <= low %.3f",
                     self.fraction, self.low)
        if (self.pressured and self._pressured_since is not None
                and now - self._pressured_since >= self.sustain_s
                and now - self._last_incident >= self.incident_interval_s):
            self._last_incident = now
            self.incidents += 1
            if self.on_pressure is not None:
                try:
                    self.on_pressure(self.snapshot())
                except Exception:
                    log.exception("on_pressure hook failed")
        return self.pressured

    def _trim(self) -> None:
        freed = 0
        for hook in self.trim_hooks:
            try:
                freed += int(hook() or 0)
            except Exception:
                log.exception("memory trim hook failed")
        self.trims += 1
        self.trimmed_entries += freed
        if freed:
            log.info("memory pressure: trimmed %d pooled/cached entries",
                     freed)

    def snapshot(self) -> Dict[str, Any]:
        """``mem_*`` health keys (exported as ``nns.mem.*`` gauges via
        the health collector)."""
        return {
            "mem_bytes_in_use": int(self.bytes_in_use),
            "mem_bytes_limit": int(self.bytes_limit),
            "mem_host_rss": int(self.host_rss),
            "mem_fraction": round(float(self.fraction), 4),
            "mem_pressure": 1 if self.pressured else 0,
            "mem_polls": int(self.polls),
            "mem_trims": int(self.trims),
            "mem_trimmed_entries": int(self.trimmed_entries),
            "mem_incidents": int(self.incidents),
        }


# ---------------------------------------------------------------------------
# Admission control
# ---------------------------------------------------------------------------
class AdmissionController:
    """Bounded in-flight slots with high/low watermark hysteresis.

    ``try_admit`` refuses once ``high`` requests are in flight and keeps
    refusing until the backlog drains to ``low`` — the hysteresis band
    prevents admit/refuse flapping right at the limit (reference analog:
    GstQueue's high/low watermark signals).  ``high <= 0`` = unlimited
    (admission disabled; counters still track in-flight).

    Thread-safe; refusals are O(1) and allocation-free — the overload
    path must be the cheapest path in the server."""

    def __init__(self, high: int = 0, low: Optional[int] = None):
        self.high = int(high)
        if self.high > 0:
            # default low = high//2; an explicit 0 is legal and honored
            # (drain fully before re-admitting — the only choice when
            # high is 1)
            self.low = self.high // 2 if low is None else int(low)
            if not 0 <= self.low < self.high:
                # a negative low could never clear the shedding band:
                # the first overload would brick the server into BUSY
                raise ValueError(
                    f"low watermark {self.low} must be in [0, "
                    f"high={self.high})"
                )
        else:
            self.low = 0
        self._lock = threading.Lock()
        self._inflight = 0
        self._shedding = False
        self.admitted = 0
        self.shed = 0

    def try_admit(self, n: int = 1) -> bool:
        with self._lock:
            if self.high > 0:
                if self._shedding and self._inflight > self.low:
                    self.shed += n
                    return False
                if self._inflight + n > self.high:
                    self._shedding = True
                    self.shed += n
                    return False
                self._shedding = False
            self._inflight += n
            self.admitted += n
            return True

    def release(self, n: int = 1) -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - n)
            if self._shedding and self._inflight <= self.low:
                self._shedding = False

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "inflight": self._inflight,
                "high": self.high,
                "low": self.low,
                "shedding": self._shedding,
                "admitted": self.admitted,
                "shed": self.shed,
            }


class TenantAdmissionController(AdmissionController):
    """Per-tenant quotas and priority classes layered on the watermark
    admission controller — the "one hot tenant must shed before
    starving the fleet" piece of fleet overload resilience.

    Check order (the shed truth table, pinned by tests):

    1. **Tenant quota** (``reason="quota"``): a named tenant may hold at
       most ``quota`` in-flight slots (per-tenant override in
       ``quotas``, else ``default_quota``; 0 = unlimited; unnamed
       requests are never quota-checked).  The refusal is weighted
       per-tenant: ``retry_after`` grows with the tenant's consecutive
       shed streak (capped 8x) so a tenant hammering its quota is paced
       harder than one that just grazed it, and an admit resets the
       pacing.
    2. **Priority headroom** (``reason="priority"``): with a global
       ``high`` watermark armed, priority class ``p`` (0..3) may only
       fill ``ceil(high * (p+1) / 4)`` slots — low-priority work hits
       its ceiling first, so under pressure it sheds while priority-3
       traffic still has headroom.  Requests without a priority class
       are priority 3: the exact pre-tenancy admission semantics.
    3. **Global watermark** (``reason="load"``): the inherited
       high/low-hysteresis band, applied to everything.

    All three refusals surface as :class:`ServerBusyError` — answered
    instantly at admission, provably never executed, breaker-immune.

    **Sustained-shed incidents**: a tenant whose QUOTA sheds persist
    beyond ``shed_window_s`` without a single admit fires
    ``on_sustained_shed(tenant)`` (rate-limited to once per window per
    tenant) — the serversrc routes it into the pipeline's flight
    recorder so "who is drowning this server" is answerable without a
    repro.

    Single-lock design: quota, priority, and watermark accounting
    update atomically, so per-tenant ``admitted/shed/inflight`` counts
    are exact even under concurrent admission (the acceptance contract
    of the fleet chaos e2e)."""

    def __init__(self, high: int = 0, low: Optional[int] = None,
                 default_quota: int = 0,
                 quotas: Optional[Dict[str, int]] = None,
                 shed_window_s: float = 5.0,
                 on_sustained_shed: Optional[Callable[[str], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        super().__init__(high, low)
        self.default_quota = max(0, int(default_quota))
        self.quotas: Dict[str, int] = {
            str(k): max(0, int(v)) for k, v in (quotas or {}).items()
        }
        self.shed_window_s = float(shed_window_s)
        self.on_sustained_shed = on_sustained_shed
        self._clock = clock
        # priority-class admission ceilings (high > 0 only); p=3 equals
        # `high` so the top class is governed by the watermark alone
        if self.high > 0:
            self._pri_high = [
                -(-self.high * (p + 1) // 4) for p in range(4)
            ]
        else:
            self._pri_high = None
        # memory-watermark coupling (MemoryPressureMonitor): when set
        # and True, every admission sheds with reason="memory" — the
        # server refuses work BEFORE the chip OOMs.  One attribute read
        # + (armed only) one bool call per admission; breaker-immune
        # like every other shed.
        self.pressure: Optional[Callable[[], bool]] = None
        self.memory_shed = 0
        # LRU-ordered so the bound below can evict the LEAST-recently
        # active idle tenant: the tenant name comes straight off the
        # wire (client-controlled), so an unbounded dict would let a
        # hostile peer grow server memory and metric cardinality forever
        self._tenants: "Dict[str, Dict[str, Any]]" = {}
        self.tenants_evicted = 0

    #: cap on the streak-scaled retry-after multiplier (quota sheds)
    RETRY_AFTER_CAP = 8.0
    #: max tracked tenant ledgers; idle (inflight == 0) least-recently
    #: active entries are evicted beyond this (their admitted/shed
    #: history stays in the aggregate counters; `tenants_evicted`
    #: counts the dropped rows so truncation is never silent)
    TENANT_MAP_MAX = 1024

    def quota_for(self, tenant: str) -> int:
        """The in-flight quota governing ``tenant`` (0 = unlimited;
        unnamed tenants are never quota-bound)."""
        if not tenant:
            return 0
        return self.quotas.get(tenant, self.default_quota)

    def _tenant_entry(self, tenant: str) -> Dict[str, Any]:
        t = self._tenants.get(tenant)
        if t is None:
            if len(self._tenants) >= self.TENANT_MAP_MAX:
                # evict the least-recently ACTIVE idle ledger (dicts
                # iterate in insertion order; _touch re-inserts on every
                # admit/shed, so iteration order IS activity order) —
                # in-flight tenants are never evicted, their release
                # accounting must find them
                for name, row in self._tenants.items():
                    if row["inflight"] == 0:
                        del self._tenants[name]
                        self.tenants_evicted += 1
                        break
            t = {
                "inflight": 0, "admitted": 0, "shed": 0,
                "streak": 0, "shed_since": None,
                "last_incident": float("-inf"),
            }
            self._tenants[tenant] = t
        return t

    def _touch(self, tenant: str, t: Dict[str, Any]) -> None:
        """Move the ledger to the back of the activity order (cheap
        LRU: delete + re-insert on the plain dict)."""
        if next(reversed(self._tenants), None) != tenant:
            del self._tenants[tenant]
            self._tenants[tenant] = t

    def admit(self, n: int = 1, tenant: str = "",
              priority: int = PRIORITY_MAX,
              retry_after: float = 0.05) -> None:
        """Admit ``n`` slots for ``tenant`` at ``priority`` or raise
        :class:`ServerBusyError` carrying the per-tenant retry-after.
        Pair every successful call with :meth:`release`."""
        tenant = str(tenant or "")
        p = clamp_priority(priority)
        fire: Optional[str] = None
        err: Optional[ServerBusyError] = None
        with self._lock:
            t = self._tenant_entry(tenant)
            quota = self.quota_for(tenant)
            reason = None
            if quota > 0 and t["inflight"] + n > quota:
                reason = "quota"
            elif self.pressure is not None and self.pressure():
                # memory watermark: shed EVERYTHING (all tenants, all
                # priority classes) — HBM exhaustion takes the whole
                # chip down, so no class has headroom against it
                reason = "memory"
                self.memory_shed += n
            elif self._pri_high is not None:
                # base watermark semantics first (identical to
                # AdmissionController for priority 3), then the
                # priority-class ceiling — a hard threshold with no
                # hysteresis of its own (the global band supplies that)
                if self._shedding and self._inflight > self.low:
                    reason = "load"
                elif self._inflight + n > self.high:
                    self._shedding = True
                    reason = "load"
                elif (p < PRIORITY_MAX
                        and self._inflight + n > self._pri_high[p]):
                    reason = "priority"
                else:
                    self._shedding = False
            if reason is None:
                t["inflight"] += n
                t["admitted"] += n
                t["streak"] = 0
                t["shed_since"] = None
                self._inflight += n
                self.admitted += n
                self._touch(tenant, t)
            else:
                t["shed"] += n
                self.shed += n
                self._touch(tenant, t)
                pace = float(retry_after)
                if reason == "quota":
                    # streak-scaled pacing is a QUOTA property: a tenant
                    # hammering its own quota backs off harder.  Global
                    # load/priority sheds keep the flat pre-tenancy
                    # retry-after — otherwise unnamed clients sharing
                    # the "" ledger would couple each other's pacing
                    t["streak"] += 1
                    pace *= min(self.RETRY_AFTER_CAP, float(t["streak"]))
                    now = self._clock()
                    if t["shed_since"] is None:
                        t["shed_since"] = now
                    elif (now - t["shed_since"] >= self.shed_window_s
                            and now - t["last_incident"]
                            >= self.shed_window_s):
                        t["last_incident"] = now
                        fire = tenant
                err = ServerBusyError(
                    f"server busy ({reason}"
                    + (f", tenant={tenant}" if tenant else "") + ")",
                    retry_after=pace, tenant=tenant, reason=reason,
                )
        if fire is not None and self.on_sustained_shed is not None:
            try:
                self.on_sustained_shed(fire)
            except Exception:  # accounting hook must never break admission
                log.exception("on_sustained_shed(%r) failed", fire)
        if err is not None:
            raise err

    def release(self, n: int = 1, tenant: str = "") -> None:
        tenant = str(tenant or "")
        with self._lock:
            self._inflight = max(0, self._inflight - n)
            if self._shedding and self._inflight <= self.low:
                self._shedding = False
            t = self._tenants.get(tenant)
            if t is not None:
                t["inflight"] = max(0, t["inflight"] - n)

    def tenant_snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Exact per-tenant accounting for health()/metrics: {tenant:
        {inflight, admitted, shed, quota}}."""
        with self._lock:
            return {
                name: {
                    "inflight": t["inflight"],
                    "admitted": t["admitted"],
                    "shed": t["shed"],
                    "quota": self.quota_for(name),
                }
                for name, t in self._tenants.items()
            }

    def snapshot(self) -> Dict[str, Any]:
        snap = super().snapshot()
        snap["tenants"] = self.tenant_snapshot()
        snap["tenants_evicted"] = self.tenants_evicted
        snap["memory_shed"] = self.memory_shed
        return snap


def parse_tenant_quotas(raw: str, owner: str = "") -> Dict[str, int]:
    """Parse a ``"tenantA:8,tenantB:4"`` property value into a quota
    dict (shared by the serversrc prop and the chaos harness)."""
    out: Dict[str, int] = {}
    for part in (raw or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, q = part.rpartition(":")
        if not sep or not name or not q.lstrip("-").isdigit() or int(q) < 0:
            raise ValueError(
                f"{owner or 'tenant-quotas'}: bad entry {part!r} "
                "(want tenant:quota, quota >= 0)")
        out[name] = int(q)
    return out
