"""Fleet autoscaling: close the loop from observatory to actuation.

PR-15 built the sensing half (``core/fleet.py``): every server publishes
a telemetry digest on the discovery plane and :class:`FleetObservatory`
rolls the fleet up — slot headroom, memory headroom, per-tenant SLO burn
rates.  This module is the acting half, in three layers that keep the
decision logic pure and the side effects pluggable:

* :func:`plan` — a PURE decision function ``(snapshot, policy, state,
  now) -> [Action]``: given one observatory snapshot and an explicit
  clock value it decides spawn / drain / resize, with hysteresis
  streaks, per-action-kind cooldowns, a min/max fleet envelope, and a
  one-action-in-flight-per-server invariant (the controller can never
  flap a server it is already draining).  Every suppressed impulse is
  COUNTED (``hysteresis_holds``, ``cooldown_skips``,
  ``envelope_clamps``, ``inflight_skips``) so a quiet controller is
  distinguishable from a blind one.  Fully deterministic under a fake
  clock — the decision truth table in ``tests/test_autoscale.py`` pins
  every boundary.
* :class:`PerfModel` — a least-squares fit (normal equations over the
  banked observations; numpy only) of fleet throughput and worst p95
  TTFT as functions of slot occupancy and fleet size, per "A Learned
  Performance Model for Tensor Processing Units" scaled down to the
  digest features we actually have.  The TTFT observable is the PR-11
  log2 histogram estimate carried in each digest (``ttft_p95_ms``);
  bench rows bank through :meth:`PerfModel.feed_bench_row`.  When the
  model has enough samples the planner acts on PROJECTED SLO burn
  (scale before the burn, not after it); below ``min_samples`` the
  reactive path is the always-correct fallback.
* :class:`FleetController` — the loop: reap finished actuator tickets,
  snapshot the observatory, feed the model, :func:`plan`, dispatch
  through a pluggable :class:`FleetActuator` (the chaos harness
  implements it in-process; a real deployment plane implements the same
  three verbs).  Every dispatched action raises a flight-recorder
  incident, and the whole decision ledger exports as
  ``nns.autoscale.*`` through the one registry path.

Zero-loss by construction: scale-down actuates the serversrc's
``request_drain()`` — live generation streams hand off via the
resumable GOAWAY machinery (remaining tokens bit-identical on the
resuming server) and the fleet never drops below the envelope floor.
Scale-up absorbs bursts; the chaos ``--mode autoscale`` script proves a
victim tenant's goodput floor through a hot-tenant burst.

Stale rows (``core/fleet.py`` stale tier) are excluded from every
capacity decision: a wedged-but-announcing server neither counts as
headroom nor gets chosen as a drain/resize target (it could not
complete a zero-loss drain).
"""

from __future__ import annotations

import json
import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from .log import get_logger
from .telemetry import METRICS, REGISTRY, Sample, metric_kind

log = get_logger("autoscale")

#: action kinds (the FleetActuator verbs)
SCALE_UP = "scale_up"
SCALE_DOWN = "scale_down"
RESIZE = "resize"

#: control-plane view levels (the fail-static ladder, worst first)
PLANE_OK = "ok"
PLANE_DEGRADED = "degraded"
PLANE_BLIND = "blind"
_PLANE_RANK = {PLANE_OK: 0, PLANE_DEGRADED: 1, PLANE_BLIND: 2}


# ---------------------------------------------------------------------------
# Fencing (controller duplication safety)
# ---------------------------------------------------------------------------
class StaleEpochError(RuntimeError):
    """Typed reject: a fenced control command carried a lease epoch older
    than one this target already accepted — the sender is a deposed
    controller (partitioned old leader, duplicated deployment).  The
    command is REFUSED before it can touch any stream or ledger."""

    def __init__(self, offered: int, current: int):
        super().__init__(
            f"stale lease epoch {offered} < fence {current}: command "
            "refused (issuer no longer holds the leader lease)")
        self.offered = int(offered)
        self.current = int(current)


class FencingToken:
    """A target's side of lease fencing: remember the highest lease
    epoch ever accepted and refuse anything older.  ``epoch=None`` is
    the local/operator bypass (a human on the box outranks the lease
    machinery); every refusal is counted exactly."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.epoch = 0
        self.rejects = 0

    def check(self, epoch: Optional[int]) -> None:
        """Admit ``epoch`` (advancing the fence) or raise
        :class:`StaleEpochError`.  Same-epoch commands are admitted:
        the lease guarantees one holder per epoch."""
        if epoch is None:
            return
        epoch = int(epoch)
        with self._lock:
            if epoch < self.epoch:
                self.rejects += 1
                raise StaleEpochError(epoch, self.epoch)
            self.epoch = epoch


# ---------------------------------------------------------------------------
# Leader lease (at most one actuating controller, by construction)
# ---------------------------------------------------------------------------
class LeaderLease:
    """Epoch-numbered, TTL'd leader lease over one retained document.

    Pure local logic under explicit clock values (the fake-clock truth
    table in ``tests/test_autoscale.py`` pins every transition); the
    transport is a pluggable ``publish(payload) -> bool`` callable
    (:class:`LeaseChannel` binds it to the retained MQTT topic).

    Rules:

    * **acquire** — only when the lease topic is provably vacant: the
      last seen lease has outlived its TTL, or nothing was seen for a
      full TTL of watching (retained redelivery must get its chance).
      The new epoch is ``max(every epoch ever seen) + 1`` — strictly
      monotonic across takeovers.
    * **renew** — the holder re-publishes every ``ttl/3``; a renewal is
      confirmed by a successful publish or by observing its own
      retained echo.
    * **self-fence** — a holder whose renewals go unconfirmed for a
      full TTL steps down on its own: a partitioned old leader stops
      actuating BEFORE the standby's takeover epoch can land
      (fail-static, not split-brain).
    * **split lease** — a same-epoch foreign lease (amnesiac broker,
      dueling brokers) resolves deterministically: the lower owner id
      wins everywhere; a fresh foreign lease always refuses an acquire.
    """

    def __init__(self, owner: str, ttl_s: float = 5.0,
                 publish: Optional[Callable[[dict], bool]] = None):
        self.owner = str(owner)
        self.ttl_s = float(ttl_s)
        self.publish = publish
        self.held = False
        self.epoch = 0
        self._max_epoch = 0
        self._seen: Optional[Dict[str, Any]] = None
        self._seen_ts = 0.0
        self._watch_start: Optional[float] = None
        self._confirmed_ts: Optional[float] = None
        self._renew_due_ts = 0.0
        self._lock = threading.RLock()
        # exact transition ledger (exported as nns.autoscale.lease_*)
        self.acquires = 0
        self.renewals = 0
        self.steals = 0
        self.losses = 0
        self.refusals = 0
        self.self_fences = 0

    def payload(self) -> dict:
        return {"owner": self.owner, "epoch": self.epoch,
                "ttl_s": self.ttl_s}

    def _try_publish(self) -> bool:
        if self.publish is None:
            return True
        try:
            return bool(self.publish(self.payload()))
        except OSError:
            return False

    def observe(self, payload: dict, now: float) -> None:
        """Inbound retained lease doc (subscription callback, or the
        truth table injecting a peer's view)."""
        try:
            owner = str(payload["owner"])
            epoch = int(payload["epoch"])
            ttl = float(payload.get("ttl_s", self.ttl_s))
        except (KeyError, TypeError, ValueError):
            return
        with self._lock:
            self._max_epoch = max(self._max_epoch, epoch)
            if owner == self.owner:
                if self.held and epoch == self.epoch:
                    self._confirmed_ts = now  # our own retained echo
                return
            self._seen = {"owner": owner, "epoch": epoch, "ttl_s": ttl}
            self._seen_ts = now
            if not self.held:
                return
            if epoch > self.epoch:
                # a higher-epoch leader exists: we were deposed while
                # partitioned — step down instantly
                self.held = False
                self.losses += 1
            elif epoch == self.epoch and owner < self.owner:
                # split lease: deterministic winner is the lower owner
                # id, on BOTH sides — exactly one controller survives
                self.held = False
                self.losses += 1

    def note_connected(self, now: float) -> None:
        """Transport (re)connected: restart the vacancy watch so a
        standby waits out retained redelivery before declaring the
        topic empty, and re-assert a held lease into an amnesiac
        broker."""
        with self._lock:
            self._watch_start = now
            if self.held and self._try_publish():
                self._confirmed_ts = now

    def release(self) -> None:
        """Voluntary stepdown (tests/operator): not counted as a loss."""
        with self._lock:
            self.held = False

    def attempt(self, now: float) -> bool:
        """One lease step per controller tick: renew when held, acquire
        when provably vacant, self-fence when unconfirmed past a full
        TTL.  Returns whether the lease is held after the step."""
        with self._lock:
            if self._watch_start is None:
                self._watch_start = now
            if self.held:
                if now >= self._renew_due_ts and self._try_publish():
                    self.renewals += 1
                    self._confirmed_ts = now
                    self._renew_due_ts = now + self.ttl_s / 3.0
                if (self._confirmed_ts is not None
                        and now - self._confirmed_ts > self.ttl_s):
                    self.held = False
                    self.self_fences += 1
                    self.losses += 1
                return self.held
            # -- standby: is the topic provably vacant? -------------------
            foreign = False
            if self._seen is not None:
                if now - self._seen_ts <= float(self._seen["ttl_s"]):
                    self.refusals += 1
                    return False
                foreign = self._seen["owner"] != self.owner
            elif now - self._watch_start < self.ttl_s:
                return False
            prev = self.epoch
            self.epoch = max(self._max_epoch, self.epoch) + 1
            if not self._try_publish():
                self.epoch = prev  # transport refused; stay standby
                return False
            self._max_epoch = max(self._max_epoch, self.epoch)
            self.held = True
            self.acquires += 1
            if foreign:
                self.steals += 1
            self._confirmed_ts = now
            self._renew_due_ts = now + self.ttl_s / 3.0
            return True


class LeaseChannel:
    """MQTT binding for :class:`LeaderLease`: one retained lease doc on
    ``nns/ctl/<fleet>/lease`` — deliberately OUTSIDE the ``nns/query/#``
    announce prefix, so discovery subscribers never try to parse it.
    Subscribing to the same topic the lease publishes on gives every
    controller (holder and standby) the same retained view, and the
    reconnect hook re-arms the vacancy watch + re-asserts a held lease
    after broker amnesia."""

    def __init__(self, host: str, port: int, fleet_topic: str,
                 lease: LeaderLease,
                 brokers: Optional[List[Tuple[str, int]]] = None,
                 clock: Callable[[], float] = time.monotonic):
        from ..distributed.mqtt import MqttClient

        self.topic = f"nns/ctl/{fleet_topic or 'all'}/lease"
        self.lease = lease
        self._clock = clock
        self._client = MqttClient(host, port, brokers=brokers)
        lease.publish = self._publish
        self._client.subscribe(self.topic, self._on_msg, qos=1)
        self._client.on_connect(
            lambda: lease.note_connected(self._clock()))

    @property
    def connected(self) -> bool:
        return self._client.connected.is_set()

    def _publish(self, payload: dict) -> bool:
        if not self._client.connected.is_set():
            return False
        self._client.publish(
            self.topic, json.dumps(payload).encode(), retain=True, qos=1)
        return True

    def _on_msg(self, topic: str, payload: bytes) -> None:
        if not payload:
            return
        try:
            doc = json.loads(payload.decode())
        except (ValueError, UnicodeDecodeError):
            log.warning("undecodable lease doc on %s", topic)
            return
        self.lease.observe(doc, self._clock())

    def close(self) -> None:
        self._client.close()


@dataclass
class FleetPolicy:
    """Policy knobs for :func:`plan` (Documentation/resilience.md
    "Fleet autoscaling" documents each one)."""

    #: fleet-size envelope — the planner never steers outside it
    min_servers: int = 1
    max_servers: int = 8
    #: reactive scale-up triggers: fleet occupancy at/above high water,
    #: admittable slot headroom below the floor, or any tenant's SLO
    #: burn rate at/above ``burn_high``
    occupancy_high: float = 0.85
    slot_headroom_min: int = 1
    burn_high: float = 1.0
    #: reactive scale-down trigger: occupancy at/below low water with
    #: no waiting prompts and no burning tenant
    occupancy_low: float = 0.30
    #: hysteresis: consecutive pressured ticks before acting (scale-up
    #: reacts fast, scale-down deliberately slow)
    up_streak: int = 2
    down_streak: int = 5
    #: per-action-kind cooldowns, seconds of fake/mono clock
    cooldown_up_s: float = 10.0
    cooldown_down_s: float = 30.0
    cooldown_resize_s: float = 30.0
    #: per-server slot-width ceiling for resize escalation when the
    #: fleet is already at ``max_servers`` (0 = resize disabled)
    resize_max_slots: int = 0
    #: predictive path: observations banked before the model may act,
    #: and the TTFT objective it projects against (0 = never predict)
    predict_min_samples: int = 8
    ttft_slo_ms: float = 0.0
    #: fail-static ladder thresholds (:func:`assess_plane`): the view is
    #: DEGRADED once more than this fraction of present rows is stale,
    #: or fresh coverage falls below this fraction of the last-known
    #: fleet (BLIND = no fresh rows at all)
    plane_stale_fraction_max: float = 0.5
    plane_quorum_fraction: float = 0.5


@dataclass
class Action:
    """One planned actuation.  ``target`` is the server's announce
    topic ("" for spawn — the actuator picks placement); ``slots`` is
    the new width for resize."""

    kind: str
    target: str = ""
    slots: int = 0
    reason: str = ""
    predictive: bool = False


@dataclass
class ControllerState:
    """Mutable planning state threaded through :func:`plan` — explicit
    so the truth table replays decisions deterministically.  The skip
    counters accumulate across ticks (they back the ``nns.autoscale.*``
    counters)."""

    up_streak: int = 0
    down_streak: int = 0
    #: per-kind monotonic timestamp of the last emitted action
    last_action_ts: Dict[str, float] = field(default_factory=dict)
    #: inflight ledger: target key -> action kind (the controller
    #: mirrors its ticket table here; plan() never touches a listed
    #: target and counts inflight spawns toward the fleet size)
    inflight: Dict[str, str] = field(default_factory=dict)
    #: fleet size the last plan steered toward
    target_servers: int = 0
    # -- suppressed-impulse accounting (quiet != blind) ------------------
    decisions: int = 0
    hysteresis_holds: int = 0
    cooldown_skips: int = 0
    envelope_clamps: int = 0
    inflight_skips: int = 0
    predictive_decisions: int = 0
    reactive_decisions: int = 0
    # -- fail-static ladder (assess_plane + plan(plane=...)) --------------
    #: actions the ladder froze instead of dispatching, total and by
    #: assessed reason (backs the reason-labeled ``nns.autoscale.frozen``)
    frozen: int = 0
    frozen_by_reason: Dict[str, int] = field(default_factory=dict)
    #: fleet size of the last TRUSTED view (grown on any fresh sighting,
    #: shrunk only by observed tombstone retirements) — the quorum
    #: baseline that makes "half the fleet went invisible" detectable
    known_fleet: int = 0
    #: rollup retirement counter baseline (-1 = not yet baselined)
    seen_retired: int = -1


def _fresh_rows(snapshot: Dict[str, Any]) -> List[Dict[str, Any]]:
    return [r for r in snapshot.get("servers", ())
            if not r.get("stale")]


@dataclass(frozen=True)
class PlaneStatus:
    """One assessed control-plane view level with its exact reasons —
    what :func:`plan` gates on and what the freeze counter labels."""

    level: str = PLANE_OK
    reasons: Tuple[str, ...] = ()

    @property
    def ok(self) -> bool:
        return self.level == PLANE_OK


def assess_plane(snapshot: Dict[str, Any], policy: FleetPolicy,
                 state: ControllerState,
                 connected: bool = True) -> PlaneStatus:
    """Grade the observatory view for the fail-static ladder.

    DEGRADED (freeze destructive actions — drain/resize/ceiling) when
    the broker is disconnected, more than ``plane_stale_fraction_max``
    of present rows is stale, or fresh coverage fell below
    ``plane_quorum_fraction`` of the last-known fleet without observed
    tombstones explaining the departures.  BLIND (freeze everything)
    when not a single fresh row remains — a cold or fully blinded
    controller is no controller.

    ``state.known_fleet`` is the quorum baseline: it grows on any fresh
    sighting and shrinks only by tombstone retirements counted in the
    rollup — so an intentional drain never reads as coverage loss, but
    a partition that silently ages half the fleet into eviction does."""
    rows = list(snapshot.get("servers") or ())
    fresh = [r for r in rows if not r.get("stale")]
    roll = snapshot.get("rollup") or {}
    retired = int(roll.get("retired", 0) or 0)
    if state.seen_retired < 0:
        state.seen_retired = retired  # first sight: baseline only
    elif retired > state.seen_retired:
        state.known_fleet = max(
            0, state.known_fleet - (retired - state.seen_retired))
        state.seen_retired = retired
    elif retired < state.seen_retired:
        # resurrection reversal: a retired server re-announced and the
        # rollup un-counted it — re-baseline DOWN too, or the next real
        # retirement would be swallowed by the stale baseline
        state.seen_retired = retired
    state.known_fleet = max(state.known_fleet, len(fresh))

    reasons: List[str] = []
    if not connected:
        reasons.append("broker_disconnected")
    if rows:
        stale_fraction = 1.0 - len(fresh) / len(rows)
        if stale_fraction > policy.plane_stale_fraction_max:
            reasons.append("stale_fraction")
    if state.known_fleet > 0:
        quorum = max(1, math.ceil(
            state.known_fleet * policy.plane_quorum_fraction))
        if len(fresh) < quorum:
            reasons.append("below_quorum")
    if not fresh:
        return PlaneStatus(PLANE_BLIND, tuple(reasons) + ("no_fresh_rows",))
    if reasons:
        return PlaneStatus(PLANE_DEGRADED, tuple(reasons))
    return PlaneStatus(PLANE_OK)


def _freeze(state: ControllerState, plane: PlaneStatus) -> List[Action]:
    """Count one impulse the fail-static ladder froze (per assessed
    reason, so the labeled counter tells outage causes apart)."""
    state.frozen += 1
    for r in plane.reasons or (plane.level,):
        state.frozen_by_reason[r] = state.frozen_by_reason.get(r, 0) + 1
    return []


def _drain_target(fresh: List[Dict[str, Any]],
                  state: ControllerState) -> Optional[Dict[str, Any]]:
    """Least-loaded fresh server not already draining and with no
    action in flight (one action in flight per server, ever — skips
    are counted so a blocked drain is visible)."""
    cands = []
    for r in fresh:
        if r.get("draining"):
            continue
        if r.get("topic") in state.inflight:
            state.inflight_skips += 1
            continue
        cands.append(r)
    if not cands:
        return None
    return min(cands, key=lambda r: (int(r.get("occupied", 0) or 0),
                                     float(r.get("tokens_per_s", 0.0)
                                           or 0.0),
                                     str(r.get("addr", ""))))


def _cool(state: ControllerState, policy: FleetPolicy, kind: str,
          now: float) -> bool:
    """True while ``kind`` is still cooling down."""
    cool = {SCALE_UP: policy.cooldown_up_s,
            SCALE_DOWN: policy.cooldown_down_s,
            RESIZE: policy.cooldown_resize_s}[kind]
    last = state.last_action_ts.get(kind)
    return last is not None and (now - last) < cool


def _emit(state: ControllerState, now: float, action: Action
          ) -> List[Action]:
    state.last_action_ts[action.kind] = now
    state.decisions += 1
    if action.predictive:
        state.predictive_decisions += 1
    else:
        state.reactive_decisions += 1
    return [action]


def plan(snapshot: Dict[str, Any], policy: FleetPolicy,
         state: Optional[ControllerState] = None, now: float = 0.0,
         model: Optional["PerfModel"] = None,
         plane: Optional[PlaneStatus] = None) -> List[Action]:
    """ONE decision step: pure in its inputs (snapshot + policy +
    explicit state and clock), deterministic, side-effect-free beyond
    the explicit ``state``.  Returns the actions to dispatch this tick
    (at most one — a controller that batches corrections flaps).

    Decision order: envelope floor (immediate — a fleet below
    ``min_servers`` is an outage, not a trend) → scale-up pressure
    (reactive observed signals first, then the predictive projection)
    → scale-down pressure.  Hysteresis streaks gate both directions,
    cooldowns gate re-fire, the envelope clamps the result, and no
    target with an action already in flight is ever picked again.

    ``plane`` (from :func:`assess_plane`) arms the fail-static ladder:
    a DEGRADED view freezes the destructive kinds (drain, resize, the
    ceiling drain), a BLIND view freezes everything — a telemetry
    outage must never amplify into a fleet outage.  ``plane=None``
    (the pure truth table, operators driving plan() by hand) means a
    trusted view.  Frozen impulses are counted, never silently lost;
    hysteresis streaks keep accumulating under a freeze so a healed
    plane acts on the first trusted tick."""
    if state is None:
        state = ControllerState()
    frozen: Tuple[str, ...] = ()
    if plane is not None and plane.level == PLANE_BLIND:
        frozen = (SCALE_UP, SCALE_DOWN, RESIZE)
    elif plane is not None and plane.level == PLANE_DEGRADED:
        frozen = (SCALE_DOWN, RESIZE)
    roll = snapshot.get("rollup") or {}
    fresh = _fresh_rows(snapshot)
    n = len(fresh)
    inflight_spawns = sum(
        1 for k in state.inflight.values() if k == SCALE_UP)
    inflight_drains = sum(
        1 for k in state.inflight.values() if k == SCALE_DOWN)
    # a spawning server is capacity-to-be; a draining one is already gone
    n_eff = n + inflight_spawns - inflight_drains
    state.target_servers = max(n_eff, policy.min_servers)

    slots = sum(int(r.get("slots", 0) or 0) for r in fresh)
    occupied = sum(int(r.get("occupied", 0) or 0) for r in fresh)
    waiting = sum(int(r.get("waiting", 0) or 0) for r in fresh)
    occupancy = (occupied / slots) if slots else 0.0
    # demand occupancy counts queued prompts — the predictive feature
    demand = ((occupied + waiting) / slots) if slots else 0.0
    headroom = int(roll.get("slot_headroom", 0) or 0)
    burn = max([float(b) for b in (roll.get("slo_burn") or {}).values()],
               default=0.0)

    # -- envelope floor: below min is an outage, act immediately --------
    if n_eff < policy.min_servers:
        if SCALE_UP in frozen:
            # a blind controller seeing "zero servers" must NOT spawn:
            # the fleet may be fine and merely invisible (cold start,
            # broker death) — cold/blind controller == no controller
            return _freeze(state, plane)
        if _cool(state, policy, SCALE_UP, now):
            state.cooldown_skips += 1
            return []
        state.target_servers = policy.min_servers
        return _emit(state, now, Action(
            SCALE_UP, reason=f"fleet {n_eff} below floor "
            f"{policy.min_servers}"))

    # -- envelope ceiling: the operator shrank the bound — converge by
    # zero-loss drains (no hysteresis: the envelope is a hard edict;
    # the cooldown still paces it to one drain per window) ---------------
    if n_eff > policy.max_servers:
        if SCALE_DOWN in frozen:
            return _freeze(state, plane)
        if _cool(state, policy, SCALE_DOWN, now):
            state.cooldown_skips += 1
            return []
        tgt = _drain_target(fresh, state)
        if tgt is None:
            return []
        state.target_servers = n_eff - 1
        return _emit(state, now, Action(
            SCALE_DOWN, target=str(tgt.get("topic", "")),
            reason=f"fleet {n_eff} above ceiling {policy.max_servers}; "
            f"draining {tgt.get('addr')} (occupied "
            f"{int(tgt.get('occupied', 0) or 0)})"))

    # -- scale-up pressure ----------------------------------------------
    up_reason = ""
    predictive = False
    if slots and occupancy >= policy.occupancy_high:
        up_reason = (f"occupancy {occupancy:.2f} >= "
                     f"{policy.occupancy_high:.2f}")
    elif slots and headroom < policy.slot_headroom_min:
        up_reason = (f"slot headroom {headroom} < "
                     f"{policy.slot_headroom_min}")
    elif burn >= policy.burn_high:
        up_reason = f"slo burn {burn:.2f} >= {policy.burn_high:.2f}"
    elif (model is not None and model.ready and policy.ttft_slo_ms > 0
          and slots):
        projected = model.predict_ttft_ms(demand, n_eff)
        if projected >= policy.ttft_slo_ms:
            up_reason = (f"projected ttft {projected:.0f}ms >= slo "
                         f"{policy.ttft_slo_ms:.0f}ms at demand "
                         f"{demand:.2f}")
            predictive = True

    if up_reason:
        state.down_streak = 0
        state.up_streak += 1
        if state.up_streak < policy.up_streak:
            state.hysteresis_holds += 1
            return []
        if n_eff >= policy.max_servers:
            # resize escalation: the envelope is full but a server can
            # grow its slot batch in place (zero-loss: live streams
            # hand off resumably around the rebuild)
            if policy.resize_max_slots > 0:
                cands = [
                    r for r in fresh
                    if r.get("topic") not in state.inflight
                    and not r.get("draining")
                    and 0 < int(r.get("slots", 0) or 0)
                    < policy.resize_max_slots
                ]
                if cands:
                    if RESIZE in frozen:
                        return _freeze(state, plane)
                    if _cool(state, policy, RESIZE, now):
                        state.cooldown_skips += 1
                        return []
                    tgt = min(cands,
                              key=lambda r: (int(r.get("slots", 0) or 0),
                                             str(r.get("addr", ""))))
                    cur = int(tgt.get("slots", 0) or 0)
                    new = min(policy.resize_max_slots, max(cur + 1,
                                                           cur * 2))
                    state.up_streak = 0
                    return _emit(state, now, Action(
                        RESIZE, target=str(tgt.get("topic", "")),
                        slots=new, predictive=predictive,
                        reason=f"{up_reason}; fleet at max "
                        f"{policy.max_servers}, widening "
                        f"{tgt.get('addr')} {cur}->{new}"))
            state.envelope_clamps += 1
            return []
        if SCALE_UP in frozen:
            return _freeze(state, plane)
        if _cool(state, policy, SCALE_UP, now):
            state.cooldown_skips += 1
            return []
        state.up_streak = 0
        state.target_servers = n_eff + 1
        return _emit(state, now, Action(
            SCALE_UP, reason=up_reason, predictive=predictive))

    # -- scale-down pressure --------------------------------------------
    state.up_streak = 0
    calm = (slots > 0 and occupancy <= policy.occupancy_low
            and waiting == 0 and burn < policy.burn_high)
    if not calm:
        state.down_streak = 0
        return []
    state.down_streak += 1
    if state.down_streak < policy.down_streak:
        state.hysteresis_holds += 1
        return []
    if n_eff <= policy.min_servers:
        state.envelope_clamps += 1
        return []
    if SCALE_DOWN in frozen:
        return _freeze(state, plane)
    if _cool(state, policy, SCALE_DOWN, now):
        state.cooldown_skips += 1
        return []
    tgt = _drain_target(fresh, state)
    if tgt is None:
        return []
    state.down_streak = 0
    state.target_servers = n_eff - 1
    return _emit(state, now, Action(
        SCALE_DOWN, target=str(tgt.get("topic", "")),
        reason=f"occupancy {occupancy:.2f} <= {policy.occupancy_low:.2f}"
        f" for {policy.down_streak} ticks; draining "
        f"{tgt.get('addr')} (occupied "
        f"{int(tgt.get('occupied', 0) or 0)})"))


# ---------------------------------------------------------------------------
# Predictive model
# ---------------------------------------------------------------------------
class PerfModel:
    """Least-squares fleet performance model: worst p95 TTFT (ms) and
    aggregate tokens/s as functions of slot occupancy and fleet size.

    Features ``[1, occ, n, occ·n]`` fit by normal equations (numpy
    ``lstsq`` — tiny, no solver dependency); observations come from
    observatory snapshots (the digest's ``ttft_p95_ms`` is the PR-11
    log2-histogram estimate) and from banked bench rows
    (:meth:`feed_bench_row`).  ``ready`` only once ``min_samples``
    observations spanning at least two distinct occupancies are banked —
    below that the controller's reactive path is the only authority
    (predictive-path fallback, pinned by the truth table)."""

    MAX_SAMPLES = 512

    def __init__(self, min_samples: int = 8):
        self.min_samples = max(2, int(min_samples))
        self._rows: Deque[Tuple[float, float, float, float]] = deque(
            maxlen=self.MAX_SAMPLES)
        self._w_ttft: Optional[Any] = None
        self._w_tps: Optional[Any] = None
        self._dirty = False
        self.bench_rows = 0

    def __len__(self) -> int:
        return len(self._rows)

    def add_sample(self, occupancy: float, servers: float,
                   tokens_per_s: float, ttft_ms: float) -> None:
        """Bank one observation (zero-TTFT rows are banked for the
        throughput fit but carry no latency signal — they are excluded
        from the TTFT fit)."""
        self._rows.append((float(occupancy), float(servers),
                           float(tokens_per_s), float(ttft_ms)))
        self._dirty = True

    def feed_bench_row(self, row: Dict[str, Any]) -> bool:
        """Bank one banked-bench evidence row (``tools/bench.py``
        attaches ``pipeline_digest_stats`` evidence): needs occupancy
        (or slots+occupied) and at least one of tokens/s / TTFT."""
        try:
            if "occupancy" in row:
                occ = float(row["occupancy"])
            else:
                slots = float(row["slots"])
                occ = float(row["occupied"]) / slots if slots else 0.0
            servers = float(row.get("servers", 1) or 1)
            tps = float(row.get("tokens_per_s", 0.0) or 0.0)
            ttft = float(row.get("ttft_p95_ms", 0.0) or 0.0)
        except (KeyError, TypeError, ValueError):
            return False
        self.add_sample(occ, servers, tps, ttft)
        self.bench_rows += 1
        return True

    @staticmethod
    def _features(occ: float, servers: float):
        return (1.0, occ, servers, occ * servers)

    def _fit(self) -> None:
        import numpy as np

        self._dirty = False
        self._w_ttft = self._w_tps = None
        rows = list(self._rows)
        if len(rows) < self.min_samples:
            return
        if len({round(r[0], 6) for r in rows}) < 2:
            return  # no occupancy spread: the fit would extrapolate air
        x = np.array([self._features(o, s) for o, s, _, _ in rows])
        tps = np.array([r[2] for r in rows])
        self._w_tps = np.linalg.lstsq(x, tps, rcond=None)[0]
        lat = [(o, s, t) for o, s, _, t in rows if t > 0]
        if len(lat) >= self.min_samples:
            xl = np.array([self._features(o, s) for o, s, _ in lat])
            yl = np.array([t for _, _, t in lat])
            self._w_ttft = np.linalg.lstsq(xl, yl, rcond=None)[0]

    @property
    def ready(self) -> bool:
        if self._dirty:
            self._fit()
        return self._w_ttft is not None

    def predict_ttft_ms(self, occupancy: float, servers: float) -> float:
        if not self.ready:
            return 0.0
        v = float(sum(w * f for w, f in zip(
            self._w_ttft, self._features(occupancy, servers))))
        return max(0.0, v)

    def predict_tokens_per_s(self, occupancy: float,
                             servers: float) -> float:
        if self._dirty:
            self._fit()
        if self._w_tps is None:
            return 0.0
        v = float(sum(w * f for w, f in zip(
            self._w_tps, self._features(occupancy, servers))))
        return max(0.0, v)


# ---------------------------------------------------------------------------
# Actuation plane
# ---------------------------------------------------------------------------
class ActionTicket:
    """One dispatched action's completion handle.  The actuator resolves
    it asynchronously; the controller reaps it on a later tick (actions
    are minutes-scale — the decision loop must never block on one)."""

    def __init__(self) -> None:
        self._done = threading.Event()
        self.ok: Optional[bool] = None
        self.detail = ""

    def resolve(self, ok: bool, detail: str = "") -> None:
        self.ok = bool(ok)
        self.detail = detail
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)


class FleetActuator:
    """The three verbs a deployment plane implements.  The chaos
    harness's in-process implementation (``tools/chaos_fleet.py``
    ``HarnessActuator``) is the reference; a real plane maps them to
    its scheduler.  Every verb returns an :class:`ActionTicket` and
    must NEVER block the calling thread.

    ``epoch`` is the issuing controller's lease epoch (fencing): the
    actuator forwards it to the target's fenced entry points
    (``request_drain(epoch=...)``/``request_resize(..., epoch=...)``),
    which refuse stale epochs with :class:`StaleEpochError`.  ``0``
    (the no-lease default) is below every real epoch, so an unleased
    controller can never out-fence a leased one."""

    def spawn(self, epoch: int = 0) -> ActionTicket:
        raise NotImplementedError

    def drain(self, target: str, epoch: int = 0) -> ActionTicket:
        """Zero-loss decommission of the server announcing under
        ``target``: request_drain → GOAWAY handoffs → stop."""
        raise NotImplementedError

    def resize(self, target: str, slots: int,
               epoch: int = 0) -> ActionTicket:
        raise NotImplementedError


class NullActuator(FleetActuator):
    """Records every verb and resolves instantly — the armed-but-idle
    controller of the perf pin, and the truth table's probe."""

    def __init__(self) -> None:
        self.calls: List[Tuple[str, str, int]] = []
        self.epochs: List[int] = []

    def _ticket(self, kind: str, target: str = "", slots: int = 0,
                epoch: int = 0) -> ActionTicket:
        self.calls.append((kind, target, slots))
        self.epochs.append(int(epoch))
        t = ActionTicket()
        t.resolve(True)
        return t

    def spawn(self, epoch: int = 0) -> ActionTicket:
        return self._ticket(SCALE_UP, epoch=epoch)

    def drain(self, target: str, epoch: int = 0) -> ActionTicket:
        return self._ticket(SCALE_DOWN, target, epoch=epoch)

    def resize(self, target: str, slots: int,
               epoch: int = 0) -> ActionTicket:
        return self._ticket(RESIZE, target, slots, epoch=epoch)


# ---------------------------------------------------------------------------
# Controller
# ---------------------------------------------------------------------------
class FleetController:
    """The closed loop: observatory snapshot → :func:`plan` →
    actuator dispatch, with exact ``nns.autoscale.*`` accounting and a
    flight-recorder incident on every scale action.

    Drive :meth:`tick` from any slow cadence: :meth:`attach` rides a
    pipeline's watchdog sweeper (``register_sweep`` — zero per-frame
    hot-path cost, pinned by the perf floor), the chaos harness calls
    it directly, and tests drive it under a fake clock."""

    def __init__(self, observatory, actuator: FleetActuator,
                 policy: Optional[FleetPolicy] = None,
                 clock: Callable[[], float] = time.monotonic,
                 recorder=None, model: Optional[PerfModel] = None,
                 lease: Optional[LeaderLease] = None):
        self.observatory = observatory
        self.actuator = actuator
        self.policy = policy or FleetPolicy()
        self.clock = clock
        self.state = ControllerState()
        self.model = model or PerfModel(
            min_samples=self.policy.predict_min_samples)
        #: leader lease (None = single-controller deployment): a
        #: controller without the lease is a pure standby — it reaps
        #: its old tickets but neither plans nor actuates
        self.lease = lease
        self._recorder = recorder
        self._pipe = None
        self._lock = threading.Lock()
        self._inflight: Dict[str, Tuple[Action, ActionTicket]] = {}
        self._spawn_seq = 0
        #: recent decisions for the fleet_top column (ts, action, status)
        self.recent: Deque[Tuple[float, Action, str]] = deque(maxlen=16)
        self.ticks = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.resizes = 0
        self.actions_failed = 0
        self.standby_ticks = 0
        #: last assessed plane status (freeze-entry incidents fire on
        #: transitions to a WORSE level, once per episode)
        self.plane = PlaneStatus()
        self._collector_registered = False

    # -- wiring -----------------------------------------------------------
    def start(self) -> "FleetController":
        if not self._collector_registered:
            REGISTRY.register_collector(self._collect)
            self._collector_registered = True
        return self

    def stop(self) -> None:
        if self._collector_registered:
            REGISTRY.unregister_collector(self._collect)
            self._collector_registered = False

    def attach(self, pipe, interval_s: float = 1.0) -> "FleetController":
        """Arm the loop on a pipeline's watchdog-sweeper cadence (the
        same slow path the digest publisher rides): no new thread, zero
        per-frame cost."""
        self._pipe = pipe
        pipe.register_sweep(self._sweep, min_poll_s=max(0.05,
                                                        float(interval_s)))
        return self.start()

    def _sweep(self) -> None:
        try:
            self.tick()
        except Exception:  # noqa: BLE001 — the sweeper must survive us
            log.exception("autoscale tick failed")

    # -- the loop ---------------------------------------------------------
    def tick(self) -> List[Action]:
        """One decision step: reap tickets, renew/acquire the lease,
        assess the plane, snapshot, feed the model, plan, dispatch.
        Returns the actions dispatched this tick.  Without the lease
        the tick is a standby heartbeat (reap only); with a degraded
        or blind plane the planner runs but the fail-static ladder
        freezes (and counts) what it would have done."""
        now = self.clock()
        with self._lock:
            self.ticks += 1
            self._reap_locked(now)
            if self.lease is not None and not self.lease.attempt(now):
                # standby: no plan, no actuation — at most one
                # actuating controller by construction
                self.standby_ticks += 1
                return []
            snap = self.observatory.snapshot()
            connected = bool(
                getattr(self.observatory, "plane_connected", True))
            plane = assess_plane(snap, self.policy, self.state,
                                 connected=connected)
            self._note_plane_locked(plane, now)
            self._feed_model(snap)
            actions = plan(snap, self.policy, self.state, now,
                           model=self.model, plane=plane)
            for a in actions:
                self._dispatch_locked(a, now)
            return actions

    def _note_plane_locked(self, plane: PlaneStatus, now: float) -> None:
        """Freeze-entry incident: fire once per degradation episode
        (every transition to a WORSE level), not per frozen impulse —
        the flight recorder's ring then holds the fleet context that
        led INTO the outage, and heals are logged, not dumped."""
        prev = self.plane
        self.plane = plane
        if _PLANE_RANK[plane.level] > _PLANE_RANK[prev.level]:
            detail = (f"plane {prev.level} -> {plane.level}: "
                      f"{','.join(plane.reasons) or 'unknown'}; "
                      "fail-static freeze armed")
            log.warning("autoscale %s", detail)
            if self._recorder is not None:
                self._recorder.dump("autoscale_freeze", "autoscale",
                                    detail=detail, logger=log)
            elif self._pipe is not None:
                self._pipe.incident("autoscale_freeze", "autoscale",
                                    detail)
        elif _PLANE_RANK[plane.level] < _PLANE_RANK[prev.level]:
            log.info("autoscale plane healed: %s -> %s", prev.level,
                     plane.level)

    def _feed_model(self, snap: Dict[str, Any]) -> None:
        roll = snap.get("rollup") or {}
        fresh = _fresh_rows(snap)
        slots = sum(int(r.get("slots", 0) or 0) for r in fresh)
        if not fresh or slots <= 0:
            return
        occupied = sum(int(r.get("occupied", 0) or 0) for r in fresh)
        self.model.add_sample(
            occupied / slots, len(fresh),
            float(roll.get("tokens_per_s", 0.0) or 0.0),
            float(roll.get("ttft_p95_ms", 0.0) or 0.0))

    def _dispatch_locked(self, a: Action, now: float) -> None:
        # fencing: every actuation carries the issuing lease epoch, so
        # a target that already saw a newer leader refuses this one
        epoch = self.lease.epoch if self.lease is not None else 0
        try:
            if a.kind == SCALE_UP:
                ticket = self.actuator.spawn(epoch=epoch)
                self._spawn_seq += 1
                key = f"!spawn:{self._spawn_seq}"
                self.scale_ups += 1
            elif a.kind == SCALE_DOWN:
                ticket = self.actuator.drain(a.target, epoch=epoch)
                key = a.target
                self.scale_downs += 1
            else:
                ticket = self.actuator.resize(a.target, a.slots,
                                              epoch=epoch)
                key = a.target
                self.resizes += 1
        except Exception as e:  # noqa: BLE001 — actuator bug must not kill the loop
            self.actions_failed += 1
            self.recent.append((now, a, f"dispatch-failed: {e}"))
            log.exception("actuator %s failed to dispatch", a.kind)
            self._incident(a, f"dispatch failed: {e}")
            return
        self._inflight[key] = (a, ticket)
        self.state.inflight[key] = a.kind
        self.recent.append((now, a, "dispatched"))
        log.info("autoscale %s %s: %s", a.kind, a.target or "<new>",
                 a.reason)
        self._incident(a, a.reason)

    def _reap_locked(self, now: float) -> None:
        for key, (a, ticket) in list(self._inflight.items()):
            if not ticket.done():
                continue
            self._inflight.pop(key, None)
            self.state.inflight.pop(key, None)
            if ticket.ok:
                self.recent.append((now, a, "ok"))
            else:
                self.actions_failed += 1
                self.recent.append((now, a, f"failed: {ticket.detail}"))
                log.warning("autoscale %s %s failed: %s", a.kind,
                            a.target or "<new>", ticket.detail)
                self._incident(a, f"failed: {ticket.detail}")

    def _incident(self, a: Action, detail: str) -> None:
        """Every scale action is an incident by design: the flight
        recorder's ring holds the fleet context that led to it."""
        msg = f"{a.kind} {a.target or '<new>'}: {detail}"
        if self._recorder is not None:
            self._recorder.dump(f"autoscale_{a.kind}", "autoscale",
                                detail=msg, logger=log)
        elif self._pipe is not None:
            self._pipe.incident(f"autoscale_{a.kind}", "autoscale", msg)

    # -- views ------------------------------------------------------------
    def inflight(self) -> Dict[str, str]:
        with self._lock:
            return dict(self.state.inflight)

    def snapshot(self) -> Dict[str, Any]:
        """The observatory snapshot plus the controller's decision
        block — what ``tools/fleet_top.py`` renders as the decision
        column."""
        snap = self.observatory.snapshot()
        with self._lock:
            snap["autoscale"] = {
                "ticks": self.ticks,
                "decisions": self.state.decisions,
                "target_servers": self.state.target_servers,
                "inflight": dict(self.state.inflight),
                "model_samples": len(self.model),
                "model_ready": self.model.ready,
                # control-plane column (fleet_top): plane level + why,
                # leader identity, frozen-impulse count
                "plane_level": self.plane.level,
                "plane_reasons": list(self.plane.reasons),
                "plane_connected": bool(
                    getattr(self.observatory, "plane_connected", True)),
                "frozen": self.state.frozen,
                "standby_ticks": self.standby_ticks,
                "lease": (
                    {"owner": self.lease.owner, "held": self.lease.held,
                     "epoch": self.lease.epoch}
                    if self.lease is not None else None),
                "recent": [
                    {"kind": a.kind, "target": a.target,
                     "reason": a.reason, "status": status,
                     "predictive": a.predictive}
                    for _, a, status in list(self.recent)[-5:]
                ],
            }
        return snap

    # -- registry export (ONE collector; scrape-time only) ----------------
    def _collect(self) -> List[Sample]:
        s = self.state
        lease = self.lease
        vals: Tuple[Tuple[str, float, str], ...] = (
            ("nns.autoscale.ticks", self.ticks, "counter"),
            ("nns.autoscale.decisions", s.decisions, "counter"),
            ("nns.autoscale.scale_ups", self.scale_ups, "counter"),
            ("nns.autoscale.scale_downs", self.scale_downs, "counter"),
            ("nns.autoscale.resizes", self.resizes, "counter"),
            ("nns.autoscale.actions_failed", self.actions_failed,
             "counter"),
            ("nns.autoscale.actions_inflight", len(self._inflight),
             "gauge"),
            ("nns.autoscale.cooldown_skips", s.cooldown_skips, "counter"),
            ("nns.autoscale.hysteresis_holds", s.hysteresis_holds,
             "counter"),
            ("nns.autoscale.envelope_clamps", s.envelope_clamps,
             "counter"),
            ("nns.autoscale.inflight_skips", s.inflight_skips, "counter"),
            ("nns.autoscale.predictive_decisions", s.predictive_decisions,
             "counter"),
            ("nns.autoscale.reactive_decisions", s.reactive_decisions,
             "counter"),
            ("nns.autoscale.model_samples", len(self.model), "gauge"),
            ("nns.autoscale.model_ready",
             1 if self.model.ready else 0, "gauge"),
            ("nns.autoscale.target_servers", s.target_servers, "gauge"),
            # fail-static ladder + leader lease (PR-17)
            ("nns.autoscale.frozen", s.frozen, "counter"),
            ("nns.autoscale.plane_level",
             _PLANE_RANK[self.plane.level], "gauge"),
            ("nns.autoscale.standby_ticks", self.standby_ticks,
             "counter"),
            ("nns.autoscale.lease_held",
             1 if (lease is not None and lease.held) else 0, "gauge"),
            ("nns.autoscale.lease_epoch",
             lease.epoch if lease is not None else 0, "gauge"),
            ("nns.autoscale.lease_acquires",
             lease.acquires if lease is not None else 0, "counter"),
            ("nns.autoscale.lease_steals",
             lease.steals if lease is not None else 0, "counter"),
            ("nns.autoscale.lease_losses",
             lease.losses if lease is not None else 0, "counter"),
            ("nns.autoscale.lease_refusals",
             lease.refusals if lease is not None else 0, "counter"),
        )
        base = {"fleet": getattr(self.observatory, "topic", "") or "all"}
        out: List[Sample] = []
        for mname, v, kind in vals:
            assert mname in METRICS and metric_kind(mname) == kind, mname
            out.append(Sample(mname, dict(base), float(v), kind))
        # reason-labeled freeze breakdown (same catalogued name; the
        # unlabeled total above is the sum across reasons)
        for reason, count in sorted(s.frozen_by_reason.items()):
            out.append(Sample(
                "nns.autoscale.frozen", dict(base, reason=reason),
                float(count), "counter"))
        return out
