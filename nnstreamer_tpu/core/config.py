"""Configuration system: ini file + environment-variable overrides.

Reference: ``gst/nnstreamer/nnstreamer_conf.{h,c}`` + ``nnstreamer.ini.in`` —
subplugin search paths per kind, framework-priority-per-model-extension,
per-subplugin custom value strings, env overrides gated by ``enable_envvar``.

TPU-native shape: an ``nnstreamer_tpu.ini`` (searched in $NNS_TPU_CONF,
./nnstreamer_tpu.ini, ~/.config/nnstreamer_tpu.ini) with sections::

    [common]
    enable_envvar = True
    [filter]
    modules = mypkg.backends            ; extra modules scanned for backends
    [framework-priority]
    tflite = jax-xla,tflite             ; model-extension -> backend priority
    [jax-xla]
    default_batch = 8                   ; per-subplugin custom values

Environment overrides use ``NNS_TPU_<SECTION>_<KEY>`` (uppercased).
"""

from __future__ import annotations

import configparser
import os
import threading
from typing import Dict, List, Optional

_ENV_PREFIX = "NNS_TPU_"

# serialized jax.export artifact extensions — the ONE list the auto-detect
# allowlist (elements/filter.py), the priority defaults below, and the
# jax-xla loader all derive from
EXPORTED_MODEL_EXTS = (".jaxexport", ".stablehlo")
_lock = threading.RLock()
_parser: Optional[configparser.ConfigParser] = None
_loaded_from: Optional[str] = None


def _candidate_paths() -> List[str]:
    paths = []
    env = os.environ.get("NNS_TPU_CONF")
    if env:
        paths.append(env)
    paths.append(os.path.join(os.getcwd(), "nnstreamer_tpu.ini"))
    paths.append(os.path.expanduser("~/.config/nnstreamer_tpu.ini"))
    return paths


def load(path: Optional[str] = None, *, force: bool = False) -> None:
    """Load the ini file (first existing candidate). Idempotent unless force."""
    global _parser, _loaded_from
    with _lock:
        if _parser is not None and not force and path is None:
            return
        cp = configparser.ConfigParser(inline_comment_prefixes=(";", "#"))
        src = None
        for p in [path] if path else _candidate_paths():
            if p and os.path.isfile(p):
                cp.read(p)
                src = p
                break
        _parser = cp
        _loaded_from = src


def reset() -> None:
    global _parser, _loaded_from
    with _lock:
        _parser = None
        _loaded_from = None


def loaded_from() -> Optional[str]:
    load()
    return _loaded_from


def _envvar_enabled() -> bool:
    # reference: conf value enable_envvar gates env overrides
    raw = _parser.get("common", "enable_envvar", fallback="true") if _parser else "true"
    return raw.strip().lower() in ("1", "true", "yes", "on")


def get_value(section: str, key: str, default: Optional[str] = None) -> Optional[str]:
    """Config lookup with env override NNS_TPU_<SECTION>_<KEY>.

    Reference: ``nnsconf_get_custom_value_string``.
    """
    load()
    with _lock:
        if _envvar_enabled():
            env_key = f"{_ENV_PREFIX}{section}_{key}".upper().replace("-", "_")
            env = os.environ.get(env_key)
            if env is not None:
                return env
        assert _parser is not None
        return _parser.get(section, key, fallback=default)


def get_bool(section: str, key: str, default: bool = False) -> bool:
    v = get_value(section, key, None)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def get_int(section: str, key: str, default: int = 0) -> int:
    v = get_value(section, key, None)
    return default if v is None else int(v)


def get_list(section: str, key: str) -> List[str]:
    v = get_value(section, key, None)
    if not v:
        return []
    return [s.strip() for s in v.replace(";", ",").split(",") if s.strip()]


def framework_priority(model_ext: str) -> List[str]:
    """Backend priority for a model file extension.

    Reference: ini ``framework_priority_<ext>`` consulted by framework=auto
    detection (``tensor_filter_common.c:1171-1196``).
    """
    ext = model_ext.lstrip(".").lower()
    pri = get_list("framework-priority", ext)
    if pri:
        return pri
    defaults: Dict[str, List[str]] = {
        "tflite": ["jax-xla", "tflite"],
        "onnx": ["jax-xla", "onnx"],
        "msgpack": ["jax-xla"],
        "orbax": ["jax-xla"],
        "jax": ["jax-xla"],
        **{e.lstrip("."): ["jax-xla"] for e in EXPORTED_MODEL_EXTS},
        "pt": ["torch"],
        "pth": ["torch"],
        "py": ["python3"],
        "so": ["custom"],
    }
    return defaults.get(ext, [])
