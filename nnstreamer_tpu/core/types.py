"""Tensor type system and stream-schema ("caps") negotiation.

This is the TPU-native re-design of the reference's L1 core type layer:

- element types / formats / rank+count limits:
  reference ``gst/nnstreamer/include/tensor_typedef.h:34-298``
- info init/copy/validate/equality + dim/type string parse/print:
  reference ``gst/nnstreamer/nnstreamer_plugin_api_util_impl.c:121-710``
- caps intersection / negotiation:
  reference ``gst/nnstreamer/nnstreamer_plugin_api_impl.c:1092-1159``
- flexible-tensor self-describing meta header:
  reference ``tensor_typedef.h`` (GstTensorMetaInfo) and
  ``nnstreamer_plugin_api_impl.c:1464-1539``

Design notes (TPU-first, not a port):

* Shapes are stored in standard row-major (outermost-first) order, the order
  JAX/XLA and numpy use.  The reference stores dimensions innermost-first
  ("3:224:224:1" = C:W:H:N); the string parse/print helpers below speak that
  dialect so reference pipeline descriptions map 1:1, but everything internal
  is numpy order.
* ``None`` in a shape marks a run-time-variable ("flexible") dimension.  XLA
  wants static shapes, so the filter layer buckets/pads flexible dims before
  compilation; the type layer only carries the declaration.
* dtypes are numpy dtypes (shared vocabulary with JAX).  bfloat16 is a
  first-class citizen here (TPU native) even though the reference has no such
  type — it is an extension, flagged so schemas stay round-trippable.
"""

from __future__ import annotations

import math
import struct
from dataclasses import dataclass, field, replace
from fractions import Fraction
from typing import Iterable, Optional, Sequence, Tuple, Union

import numpy as np

try:  # bfloat16 rides on ml_dtypes (always present with jax)
    import ml_dtypes

    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover
    _BFLOAT16 = None

# ---------------------------------------------------------------------------
# Limits — reference tensor_typedef.h:
#   NNS_TENSOR_RANK_LIMIT = 16, NNS_TENSOR_SIZE_LIMIT = 16 (+240 extra)
# ---------------------------------------------------------------------------
RANK_LIMIT = 16
TENSOR_COUNT_LIMIT = 256  # 16 primary + 240 "extra" in the reference

# Element types (reference tensor_typedef.h enum _nns_tensor_type, 11 types).
# bfloat16 is a TPU-native extension (not in the reference).
_TYPE_NAMES = {
    "int8": np.dtype(np.int8),
    "uint8": np.dtype(np.uint8),
    "int16": np.dtype(np.int16),
    "uint16": np.dtype(np.uint16),
    "int32": np.dtype(np.int32),
    "uint32": np.dtype(np.uint32),
    "int64": np.dtype(np.int64),
    "uint64": np.dtype(np.uint64),
    "float16": np.dtype(np.float16),
    "float32": np.dtype(np.float32),
    "float64": np.dtype(np.float64),
}
if _BFLOAT16 is not None:
    _TYPE_NAMES["bfloat16"] = _BFLOAT16

_NAME_BY_DTYPE = {v: k for k, v in _TYPE_NAMES.items()}

# Formats (reference tensor_typedef.h enum _tensor_format)
FORMAT_STATIC = "static"
FORMAT_FLEXIBLE = "flexible"
FORMAT_SPARSE = "sparse"
FORMATS = (FORMAT_STATIC, FORMAT_FLEXIBLE, FORMAT_SPARSE)

DimsT = Tuple[Optional[int], ...]


def dtype_from_name(name: str) -> np.dtype:
    """Map a type name ("float32") to a numpy dtype.

    Reference: ``gst_tensor_get_type`` in nnstreamer_plugin_api_util_impl.c.
    """
    key = name.strip().lower()
    if key not in _TYPE_NAMES:
        raise ValueError(f"unknown tensor element type: {name!r}")
    return _TYPE_NAMES[key]


def dtype_to_name(dtype) -> str:
    """Map a numpy/JAX dtype to its canonical name.

    Reference: ``gst_tensor_get_type_string``.
    """
    dt = np.dtype(dtype)
    if dt not in _NAME_BY_DTYPE:
        raise ValueError(f"unsupported tensor element type: {dtype!r}")
    return _NAME_BY_DTYPE[dt]


def all_type_names() -> Tuple[str, ...]:
    return tuple(_TYPE_NAMES)


def parse_dims_string(text: str) -> DimsT:
    """Parse a reference-dialect dimension string into a numpy-order shape.

    "3:224:224:1" (innermost-first, reference
    ``gst_tensor_parse_dimension`` / ``..._parse_dimensions_string``
    nnstreamer_plugin_api_util_impl.c:572) becomes ``(1, 224, 224, 3)``.
    A 0 or '?' component marks a flexible (unknown) dimension -> ``None``.
    """
    parts = [p.strip() for p in text.strip().split(":") if p.strip() != ""]
    if not parts:
        raise ValueError(f"empty dimension string: {text!r}")
    if len(parts) > RANK_LIMIT:
        raise ValueError(f"rank {len(parts)} exceeds limit {RANK_LIMIT}")
    dims: list = []
    for p in parts:
        if p in ("?", "*"):
            dims.append(None)
            continue
        v = int(p)
        if v < 0:
            raise ValueError(f"negative dimension in {text!r}")
        dims.append(None if v == 0 else v)
    return tuple(reversed(dims))


def ref_dim_to_axis(ref_dim: int, rank: int) -> int:
    """Convert a reference-dialect dimension index (innermost-first, as in
    ``parse_dims_string``) to a numpy axis, validating the range.

    The single owner of the ``rank - 1 - dim`` conversion used by every
    element that takes a reference dim property (merge/split/aggregator/
    transform)."""
    axis = rank - 1 - int(ref_dim)
    if not 0 <= axis < rank:
        raise ValueError(f"dimension index {ref_dim} out of range for rank {rank}")
    return axis


def dims_to_string(shape: Sequence[Optional[int]]) -> str:
    """Inverse of :func:`parse_dims_string` (innermost-first, reference
    ``gst_tensor_get_dimension_string``)."""
    return ":".join("0" if d is None else str(d) for d in reversed(tuple(shape)))


@dataclass(frozen=True)
class TensorSpec:
    """Static description of one tensor in a stream.

    Reference analog: ``GstTensorInfo`` (tensor_typedef.h) — name, type, dims.
    """

    shape: DimsT
    dtype: np.dtype = np.dtype(np.float32)
    name: str = ""

    def __post_init__(self):
        norm = []
        for d in self.shape:
            if d is None:
                norm.append(None)
                continue
            if isinstance(d, bool) or (
                not isinstance(d, (int, np.integer)) or int(d) <= 0
            ):
                raise ValueError(f"bad dimension {d!r} in shape {tuple(self.shape)!r}")
            norm.append(int(d))
        object.__setattr__(self, "shape", tuple(norm))
        object.__setattr__(self, "dtype", np.dtype(self.dtype))
        if len(self.shape) > RANK_LIMIT:
            raise ValueError(f"rank {len(self.shape)} exceeds limit {RANK_LIMIT}")
        if np.dtype(self.dtype) not in _NAME_BY_DTYPE:
            raise ValueError(f"unsupported dtype {self.dtype!r}")

    # -- predicates ---------------------------------------------------------
    @property
    def is_static(self) -> bool:
        return all(d is not None for d in self.shape)

    @property
    def rank(self) -> int:
        return len(self.shape)

    @property
    def num_elements(self) -> Optional[int]:
        """prod(dims); None if any dim is flexible."""
        if not self.is_static:
            return None
        return int(math.prod(self.shape)) if self.shape else 1

    @property
    def nbytes(self) -> Optional[int]:
        """Byte size of one frame of this tensor.

        Reference: ``gst_tensor_info_get_size``
        (nnstreamer_plugin_api_util_impl.c:156).
        """
        n = self.num_elements
        return None if n is None else n * self.dtype.itemsize

    # -- negotiation --------------------------------------------------------
    def is_compatible(self, other: "TensorSpec") -> bool:
        """True if a buffer described by `other` can flow where `self` is
        expected (flexible dims act as wildcards)."""
        if np.dtype(self.dtype) != np.dtype(other.dtype):
            return False
        if len(self.shape) != len(other.shape):
            return False
        return all(
            a is None or b is None or a == b for a, b in zip(self.shape, other.shape)
        )

    def intersect(self, other: "TensorSpec") -> Optional["TensorSpec"]:
        """Most-specific common spec, or None if incompatible.

        Reference analog: caps intersection
        (``gst_tensor_caps_can_intersect`` nnstreamer_plugin_api_impl.c:1092).
        """
        if not self.is_compatible(other):
            return None
        shape = tuple(a if a is not None else b for a, b in zip(self.shape, other.shape))
        return TensorSpec(shape, self.dtype, self.name or other.name)

    def matches(self, array) -> bool:
        """True if a concrete array conforms to this spec."""
        if np.dtype(array.dtype) != np.dtype(self.dtype):
            return False
        if len(array.shape) != len(self.shape):
            return False
        return all(s is None or s == a for s, a in zip(self.shape, array.shape))

    # -- strings ------------------------------------------------------------
    def to_string(self) -> str:
        return f"{dtype_to_name(self.dtype)}:{dims_to_string(self.shape)}"

    @classmethod
    def from_string(cls, text: str, name: str = "") -> "TensorSpec":
        """Parse "float32:3:224:224:1" (type:dims, reference dialect)."""
        head, _, rest = text.strip().partition(":")
        return cls(parse_dims_string(rest), dtype_from_name(head), name)

    def with_batch(self, batch: int) -> "TensorSpec":
        """Prepend a batch dimension (micro-batching helper)."""
        return replace(self, shape=(batch,) + self.shape)


@dataclass(frozen=True)
class StreamSpec:
    """Schema of a tensor stream: N tensors per frame + format + rate.

    Reference analog: ``GstTensorsConfig`` = ``GstTensorsInfo`` + format +
    framerate (tensor_typedef.h), rendered as `other/tensors` caps.
    """

    tensors: Tuple[TensorSpec, ...] = ()
    fmt: str = FORMAT_STATIC
    framerate: Optional[Fraction] = None

    def __post_init__(self):
        object.__setattr__(self, "tensors", tuple(self.tensors))
        if self.fmt not in FORMATS:
            raise ValueError(f"unknown stream format {self.fmt!r}")
        if len(self.tensors) > TENSOR_COUNT_LIMIT:
            raise ValueError(
                f"{len(self.tensors)} tensors exceeds limit {TENSOR_COUNT_LIMIT}"
            )
        if self.framerate is not None:
            object.__setattr__(self, "framerate", Fraction(self.framerate))

    # -- basics -------------------------------------------------------------
    @property
    def num_tensors(self) -> int:
        return len(self.tensors)

    @property
    def is_static(self) -> bool:
        return self.fmt == FORMAT_STATIC and all(t.is_static for t in self.tensors)

    @property
    def is_flexible(self) -> bool:
        return self.fmt == FORMAT_FLEXIBLE

    def validate(self) -> bool:
        """Reference: ``gst_tensors_config_validate``."""
        if self.fmt == FORMAT_STATIC:
            return self.num_tensors > 0 and all(t.is_static for t in self.tensors)
        return True  # flexible/sparse: schema resolved per-buffer via header

    # -- negotiation --------------------------------------------------------
    @property
    def is_any(self) -> bool:
        """A zero-tensor flexible schema is the wildcard (≙ ANY caps)."""
        return self.fmt == FORMAT_FLEXIBLE and not self.tensors

    def is_compatible(self, other: "StreamSpec") -> bool:
        if self.is_any or other.is_any:
            return True
        if self.fmt != other.fmt:
            return False
        if self.is_flexible or self.fmt == FORMAT_SPARSE:
            return True
        if self.num_tensors != other.num_tensors:
            return False
        return all(a.is_compatible(b) for a, b in zip(self.tensors, other.tensors))

    def intersect(self, other: "StreamSpec") -> Optional["StreamSpec"]:
        if self.is_any:
            return other
        if other.is_any:
            return self
        if not self.is_compatible(other):
            return None
        if self.fmt != FORMAT_STATIC:
            return self
        merged = []
        for a, b in zip(self.tensors, other.tensors):
            m = a.intersect(b)
            if m is None:
                return None
            merged.append(m)
        fr = self.framerate if self.framerate is not None else other.framerate
        return StreamSpec(tuple(merged), self.fmt, fr)

    def __eq__(self, other) -> bool:  # reference: gst_tensors_config_is_equal
        return (
            isinstance(other, StreamSpec)
            and self.fmt == other.fmt
            and self.tensors == other.tensors
            and self.framerate == other.framerate
        )

    def __hash__(self):
        return hash((self.tensors, self.fmt, self.framerate))

    # -- strings ------------------------------------------------------------
    def to_string(self) -> str:
        """Render reference-caps-like text, e.g.
        ``tensors,format=static,num=2,dimensions=3:224:224:1.10:1,types=uint8.float32,framerate=30/1``
        """
        parts = [f"tensors,format={self.fmt}", f"num={self.num_tensors}"]
        if self.tensors:
            parts.append(
                "dimensions=" + ".".join(dims_to_string(t.shape) for t in self.tensors)
            )
            parts.append("types=" + ".".join(dtype_to_name(t.dtype) for t in self.tensors))
        if self.framerate is not None:
            parts.append(
                f"framerate={self.framerate.numerator}/{self.framerate.denominator}"
            )
        return ",".join(parts)

    @classmethod
    def from_string(cls, text: str) -> "StreamSpec":
        fields = {}
        head, *rest = [p.strip() for p in text.strip().split(",")]
        if head not in ("tensors", "other/tensors"):
            raise ValueError(f"not a tensors schema: {text!r}")
        for item in rest:
            k, _, v = item.partition("=")
            fields[k.strip()] = v.strip()
        fmt = fields.get("format", FORMAT_STATIC)
        fr = None
        if "framerate" in fields:
            n, _, d = fields["framerate"].partition("/")
            fr = Fraction(int(n), int(d or "1"))
        tensors: Tuple[TensorSpec, ...] = ()
        if "dimensions" in fields:
            dims = [parse_dims_string(s) for s in fields["dimensions"].split(".")]
            types = [dtype_from_name(s) for s in fields.get("types", "").split(".")]
            if len(dims) != len(types):
                raise ValueError("dimensions/types count mismatch")
            tensors = tuple(TensorSpec(d, t) for d, t in zip(dims, types))
        return cls(tensors, fmt, fr)

    # -- helpers ------------------------------------------------------------
    def pick(self, indices: Iterable[int]) -> "StreamSpec":
        """Subset/reorder tensors — `input-combination` semantics
        (reference tensor_filter.c:723-765)."""
        return replace(self, tensors=tuple(self.tensors[i] for i in indices))

    def nbytes(self) -> Optional[int]:
        sizes = [t.nbytes for t in self.tensors]
        return None if any(s is None for s in sizes) else sum(sizes)


# Wildcard schema: matches anything (reference: ANY caps).
ANY = StreamSpec((), FORMAT_FLEXIBLE, None)


# ---------------------------------------------------------------------------
# Flexible-tensor self-describing header
# Reference: GstTensorMetaInfo (tensor_typedef.h) serialized per-memory for
# format=flexible streams; append/parse at nnstreamer_plugin_api_impl.c:1464.
# ---------------------------------------------------------------------------
_FLEX_MAGIC = 0x5450534E  # "NSPT"
_FLEX_VERSION = 1
# layout: magic u32 | version u32 | dtype-name-len u8 | rank u8 | pad u16 |
#         dims i32 * rank | dtype-name bytes
_FLEX_FIXED = struct.Struct("<IIBBH")


def pack_flex_header(spec: TensorSpec) -> bytes:
    """Serialize a per-tensor self-describing header (flexible streams)."""
    if not spec.is_static:
        raise ValueError("flex header requires concrete shape")
    name = dtype_to_name(spec.dtype).encode()
    head = _FLEX_FIXED.pack(_FLEX_MAGIC, _FLEX_VERSION, len(name), spec.rank, 0)
    dims = struct.pack(f"<{spec.rank}i", *spec.shape) if spec.rank else b""
    return head + dims + name


class FlexHeaderTruncated(ValueError):
    """Flex header declared more bytes than the buffer holds.

    Distinguishable from semantic corruption (bad magic, unknown dtype,
    absurd rank) so the wire layer can map the two onto its typed
    ``WireTruncationError`` / ``WireCorruptionError`` split."""


def unpack_flex_header(buf: bytes) -> Tuple[TensorSpec, int]:
    """Parse a flex header; returns (spec, header_size).

    Hostile-input contract: every declared size (rank, dtype-name
    length, dims) is validated against limits and the buffer BEFORE any
    use, so a corrupted header raises :class:`ValueError` (or
    :class:`FlexHeaderTruncated`) — never a raw ``struct.error`` and
    never an oversized allocation."""
    try:
        magic, version, nlen, rank, _ = _FLEX_FIXED.unpack_from(buf, 0)
    except struct.error:
        raise FlexHeaderTruncated(
            f"truncated flexible-tensor header: {len(buf)} byte(s), "
            f"need {_FLEX_FIXED.size}"
        ) from None
    if magic != _FLEX_MAGIC:
        raise ValueError("bad flexible-tensor header magic")
    if version != _FLEX_VERSION:
        raise ValueError(f"unsupported flex header version {version}")
    if rank > RANK_LIMIT:
        raise ValueError(f"flex header rank {rank} exceeds limit {RANK_LIMIT}")
    off = _FLEX_FIXED.size
    try:
        dims = struct.unpack_from(f"<{rank}i", buf, off) if rank else ()
    except struct.error:
        raise FlexHeaderTruncated(
            "truncated flexible-tensor header: dims"
        ) from None
    off += 4 * rank
    name = bytes(buf[off : off + nlen])  # bytes() so memoryviews work
    if len(name) != nlen:
        raise FlexHeaderTruncated("truncated flexible-tensor header: dtype name")
    dtype = dtype_from_name(name.decode())  # UnicodeDecodeError ⊂ ValueError
    off += nlen
    return TensorSpec(tuple(dims), dtype), off


# ---------------------------------------------------------------------------
# Sparse payload (CSR-like flat encoding)
# Reference: gsttensor_sparseutil.c:27-153 — values + linear indices + nnz.
# ---------------------------------------------------------------------------
def sparse_encode(dense: np.ndarray) -> Tuple[np.ndarray, np.ndarray, TensorSpec]:
    """Dense array -> (values, linear_indices) + original spec."""
    flat = np.ascontiguousarray(dense).reshape(-1)
    idx = np.flatnonzero(flat).astype(np.uint32)
    return flat[idx], idx, TensorSpec(tuple(dense.shape), dense.dtype)


def sparse_decode(values: np.ndarray, indices: np.ndarray, spec: TensorSpec) -> np.ndarray:
    """Inverse of :func:`sparse_encode`."""
    if not spec.is_static:
        raise ValueError("sparse decode requires concrete spec")
    flat = np.zeros(spec.num_elements, dtype=spec.dtype)
    flat[indices.astype(np.int64)] = values.astype(spec.dtype, copy=False)
    return flat.reshape(spec.shape)
