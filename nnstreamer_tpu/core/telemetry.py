"""Unified fleet telemetry: metrics registry, Prometheus exposition,
wire-propagated trace spans, and the stall flight recorder.

The reference delegates pipeline observability to ecosystem tracers
(GstShark proctime/interlatency — reproduced locally in ``core/tracer.py``)
plus per-filter latency/throughput props; every signal was trapped
in-process behind ``health()`` dicts and tracer rings.  This module gives
each of those signals a STABLE dotted name (``nns.filter.invoke_latency``,
``nns.feed.window_occupancy``, ``nns.query.inflight``, ...) in one
process-wide registry, exposes the registry as Prometheus text
(``Pipeline.serve_metrics(port)`` / ``NNS_METRICS_PORT``), and adds the
two cross-process pieces local tracing cannot provide:

* **Trace spans over the query wire** — per-request ``trace_id`` plus
  server-side duration stamps ride the frame meta (both transports, v1
  and v2 envelopes: meta is JSON either way, so v1 peers interoperate),
  letting one frame's end-to-end latency decompose into client-queue /
  wire / server-queue / device-dispatch / device-compute segments.
  Host-local timestamps never cross the wire: any meta key starting with
  :data:`TL_PREFIX` is stripped at encode (``wire._clean_meta``); only
  *durations* travel (``SRV_SPAN_META``).
* **Flight recorder** — a bounded ring of recent per-frame span events,
  dumped (rate-limited, to log + a JSON file) on watchdog stall,
  dead-letter, swap rollback, or breaker trip, so "where did the time
  go" is answerable without a repro.

Cost contract: the disabled path stays one branch per frame (the
scheduler's existing ``tracer is not None`` test — the recorder rides the
tracer); registry collection happens only at scrape/snapshot time.

Naming contract: every registry name is declared in :data:`METRICS`
(``tools/check_health_schema.py`` lints the catalog against the docs and
a snapshot file, so a rename can never be silent).  Numeric
``health_info()`` keys without an explicit mapping are exported as
``nns.health.<key>`` — the same lint covers those keys at their source.
"""

from __future__ import annotations

import itertools
import json
import os
import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from .log import get_logger

log = get_logger("telemetry")

# ---------------------------------------------------------------------------
# Trace-context meta keys
# ---------------------------------------------------------------------------
#: meta keys with this prefix are HOST-LOCAL (monotonic-clock stamps,
#: in-process handles) and are stripped by ``wire._clean_meta`` before any
#: frame is encoded — instants never cross the wire, only durations do
TL_PREFIX = "_nns_tl_"
#: per-request trace id (string); crosses the wire and is echoed back in
#: answers so client, server, and flight-recorder views correlate
TRACE_ID_META = "_nns_trace_id"
#: server receive stamp (perf_counter, host-local; stamped at admission)
TL_RX_META = "_nns_tl_rx"
#: filter invoke stamps: (dispatch_s, compute_s) durations, host-local
#: until ``QueryServerCore.process`` folds them into ``SRV_SPAN_META``
TL_INVOKE_META = "_nns_tl_invoke"
#: client enqueue stamp (perf_counter at the query client's doorstep)
TL_ENQ_META = "_nns_tl_enq"
#: mailbox enqueue stamp (perf_counter at _push/_put_many; popped at
#: dequeue into the consuming element's queue-wait histogram) — only
#: written while a tracer is armed, host-local like every TL_ key
TL_QPUT_META = "_nns_tl_qput"
#: the client-local end-to-end decomposition attached to answer frames:
#: {"client_queue","wire","server_queue","device_dispatch",
#:  "device_compute","total"} — seconds, summing exactly to "total"
SPAN_META = "_nns_tl_span"
#: server-side duration dict {"queue","dispatch","compute","total"}
#: (seconds) — crosses the wire in answer meta (JSON-safe, v1-compatible;
#: peers that predate it simply never stamp it and the client reports the
#: whole round trip as wire time)
SRV_SPAN_META = "_nns_srv_span"

_trace_seq = itertools.count(1)
_TRACE_PREFIX = f"{os.getpid():x}"


def new_trace_id() -> str:
    """Cheap per-request trace id, unique within a fleet window."""
    return f"{_TRACE_PREFIX}-{next(_trace_seq)}"


# ---------------------------------------------------------------------------
# Stable metric-name catalog
# ---------------------------------------------------------------------------
#: every registry name, with kind + one-line help.  PURE LITERAL: the
#: ``tools/check_health_schema.py`` lint parses this dict statically.
METRICS: Dict[str, Tuple[str, str]] = {
    # per-element dataplane (PipelineTracer-fed)
    "nns.element.frames": ("counter", "logical frames out of the element"),
    "nns.element.calls": ("counter", "handler calls (micro-batches count once)"),
    "nns.element.proctime_us": ("gauge", "mean handler wall time, us"),
    "nns.element.proctime_p99_us": ("gauge", "p99 handler wall time, us"),
    "nns.element.fps": ("gauge", "logical frames/sec out of the element"),
    "nns.element.interlatency_ms": ("gauge", "mean source-to-here latency, ms"),
    "nns.element.queue_depth": ("gauge", "mean mailbox depth at dequeue"),
    "nns.element.queue_capacity": ("gauge", "mailbox capacity"),
    "nns.element.bitrate_mbps": ("gauge", "payload megabits/sec through the element"),
    # supervision counters (Pipeline.health)
    "nns.element.restarts": ("counter", "lifetime supervisor restarts"),
    "nns.element.restarts_window": ("gauge", "restarts within the current restart-window"),
    "nns.element.dead_letters": ("counter", "frames dropped under error-policy=skip"),
    "nns.element.dead_letter_depth": ("gauge", "retained dead-letter frames"),
    "nns.element.deadline_drops": ("counter", "frames expired before processing"),
    "nns.element.stalls": ("counter", "watchdog stall episodes"),
    "nns.element.overruns": ("counter", "watchdog frame-deadline overruns"),
    # lifecycle states (numeric codes; see observability.md for the map)
    "nns.lifecycle.state": ("gauge", "element supervision state code"),
    "nns.lifecycle.server_state": ("gauge", "query-server serving/draining/stopped code"),
    "nns.lifecycle.swap_state": ("gauge", "hot-swap coordinator state code"),
    "nns.lifecycle.draining": ("gauge", "1 while the query server refuses with GOAWAY"),
    "nns.pipeline.delivered": ("counter", "logical frames consumed by terminal elements"),
    "nns.pipeline.errors": ("gauge", "recorded fatal element errors"),
    # tensor_filter + async device feed (core/feed.py)
    "nns.filter.invokes": ("counter", "backend invoke calls"),
    "nns.filter.invoked_frames": ("counter", "logical frames through the backend"),
    "nns.filter.invoke_latency": ("gauge", "mean per-frame invoke latency, seconds (latency=1)"),
    "nns.filter.model_version": ("gauge", "hot-swap model version"),
    "nns.filter.swaps": ("counter", "committed hot model swaps"),
    "nns.filter.swap_failures": ("counter", "staging/inline reload failures"),
    "nns.filter.rollbacks": ("counter", "observation-window rollbacks"),
    "nns.feed.window_occupancy": ("gauge", "micro-batches parked in the dispatch window"),
    "nns.feed.window_reaped": ("counter", "batches materialized by the window reaper"),
    "nns.feed.dispatch_waits": ("counter", "full-window backpressure waits"),
    "nns.feed.lane_pending": ("gauge", "staging jobs queued on the ingest lane"),
    "nns.feed.lane_staged": ("counter", "micro-batches staged by the ingest lane"),
    # always-on latency histograms (log2 buckets; armed with the tracer)
    "nns.element.handle_seconds": ("histogram", "per-element handler wall time, log2 buckets"),
    "nns.element.handle_p50_us": ("gauge", "p50 handler wall time, us (log2 estimate)"),
    "nns.element.handle_p95_us": ("gauge", "p95 handler wall time, us (log2 estimate)"),
    "nns.element.handle_p99_us": ("gauge", "p99 handler wall time, us (log2 estimate)"),
    "nns.element.queue_wait_seconds": ("histogram", "mailbox wait, producer handoff to dequeue, log2 buckets"),
    "nns.element.queue_wait_p50_us": ("gauge", "p50 mailbox queue wait, us (log2 estimate)"),
    "nns.element.queue_wait_p99_us": ("gauge", "p99 mailbox queue wait, us (log2 estimate)"),
    "nns.feed.window_dwell_seconds": ("histogram", "micro-batch dwell in the completion window, log2 buckets"),
    "nns.feed.window_dwell_p50_us": ("gauge", "p50 completion-window dwell, us (log2 estimate)"),
    "nns.feed.window_dwell_p99_us": ("gauge", "p99 completion-window dwell, us (log2 estimate)"),
    # profilers (jax trace session + incident-time thread sampler)
    "nns.profiler.active": ("gauge", "1 while the element holds a jax-profiler trace ref"),
    "nns.profiler.captures": ("counter", "thread-profile captures attached to incident dumps"),
    # tensor_query server (admission / wire integrity / rolling restart)
    "nns.query.inflight": ("gauge", "requests admitted and not yet answered"),
    "nns.query.admitted": ("counter", "requests admitted"),
    "nns.query.load_shed": ("counter", "requests refused with BUSY"),
    "nns.query.shedding": ("gauge", "1 while admission hysteresis refuses work"),
    "nns.query.admission_high": ("gauge", "admission high watermark"),
    "nns.query.admission_low": ("gauge", "admission low watermark"),
    "nns.query.ingress_depth": ("gauge", "frames queued for the server pipeline"),
    "nns.query.corrupt_requests": ("counter", "corrupt requests refused"),
    "nns.query.goaway_sent": ("counter", "requests refused with GOAWAY"),
    # per-tenant admission (TenantAdmissionController; tenant= label)
    "nns.query.tenant_inflight": ("gauge", "requests in flight for the tenant"),
    "nns.query.tenant_admitted": ("counter", "requests admitted for the tenant"),
    "nns.query.tenant_shed": ("counter", "requests shed for the tenant (quota/priority/load)"),
    "nns.query.tenant_quota": ("gauge", "in-flight quota governing the tenant (0 = unlimited)"),
    # tensor_query client (failover / integrity / degrade / spans)
    "nns.query.client_inflight": ("gauge", "client requests dispatched and unanswered"),
    "nns.query.affinity_remaps": ("counter", "consistent-hash affinity owner changes (fleet resizes)"),
    "nns.query.remote_inflight": ("gauge", "live client requests in flight to the remote"),
    "nns.query.delivered": ("counter", "logical frames answered by a server"),
    "nns.query.retried": ("counter", "extra attempts dispatched, all causes"),
    "nns.query.busy_replies": ("counter", "BUSY sheds seen"),
    "nns.query.goaway_replies": ("counter", "GOAWAY refusals seen"),
    "nns.query.deadline_expired": ("counter", "requests abandoned: budget ran out"),
    "nns.query.corruption_detected": ("counter", "corrupt exchanges detected"),
    "nns.query.degraded_frames": ("counter", "frames answered by degrade= instead of a server"),
    "nns.query.stream_resumes": ("counter", "generation streams resumed after a mid-stream break"),
    "nns.query.stream_migrations": ("counter", "generation streams migrated off a draining server"),
    "nns.query.duplicate_tokens_dropped": ("counter", "post-resume overlap tokens deduped (exactly-once)"),
    "nns.query.resume_failures": ("counter", "stream resume attempts that failed (reject/no-progress/exhaustion)"),
    "nns.query.breaker_trips_evicted": ("counter", "trips of breakers evicted on pool swaps"),
    "nns.query.breaker_open": ("gauge", "1 while the remote's breaker is open"),
    "nns.query.breaker_trips": ("counter", "lifetime breaker trips for the remote"),
    "nns.query.breaker_failures": ("gauge", "failures in the breaker's rolling window"),
    "nns.query.rtt_seconds": ("histogram", "client-observed round-trip time"),
    # per-remote span aggregation (the item-3 load signal)
    "nns.query.remote_requests": ("counter", "requests answered by the remote"),
    "nns.query.remote_e2e_ms": ("gauge", "EWMA end-to-end latency via the remote"),
    "nns.query.remote_rtt_ms": ("gauge", "EWMA wire round-trip via the remote"),
    "nns.query.remote_wire_ms": ("gauge", "EWMA wire-only segment via the remote"),
    "nns.query.remote_server_ms": ("gauge", "EWMA server-side time via the remote"),
    "nns.query.remote_client_queue_ms": ("gauge", "EWMA client-queue segment"),
    # sources/sinks, wire integrity, datarepo
    # -- continuous batching (core/slots.py + tensor_generator) ------------
    "nns.gen.slots": ("gauge", "configured slot-batch width"),
    "nns.gen.occupied": ("gauge", "slots held by live generation streams"),
    "nns.gen.waiting": ("gauge", "prompts queued for a free slot"),
    "nns.gen.joins": ("counter", "streams that claimed a slot"),
    "nns.gen.completed": ("counter", "streams that finished their tokens"),
    "nns.gen.evicted": ("counter", "streams evicted on deadline/pace (typed expiry)"),
    "nns.gen.cancelled": ("counter", "streams cancelled (consumer gone)"),
    "nns.gen.tokens": ("counter", "tokens decoded across all slots"),
    "nns.gen.decode_steps": ("counter", "slot-batch decode steps"),
    "nns.gen.prefill_chunks": ("counter", "chunked-prefill pieces interleaved"),
    "nns.gen.tokens_per_step": ("gauge", "EWMA active slots per decode step"),
    "nns.gen.jit_buckets": ("gauge", "live decode/prefill compile buckets (LRU-bounded)"),
    "nns.gen.decode_compiles": ("counter", "slotted decode-step retraces (shape churn)"),
    "nns.gen.resumes": ("counter", "streams joined from a RESUME checkpoint"),
    "nns.gen.goaway_evicted": ("counter", "live streams handed off as resumable GOAWAY chunks on drain"),
    "nns.gen.resume_rejects": ("counter", "RESUME requests refused (signature/digest/shape mismatch)"),
    "nns.gen.resizes": ("counter", "zero-loss slot-width rebuilds (autoscale resize actuation)"),

    # -- shared-prefix KV cache (core/slots.py PrefixCache) ----------------
    "nns.prefix.hits": ("counter", "eligible prompts that attached cached prefix pages"),
    "nns.prefix.misses": ("counter", "eligible prompts that found no cached prefix chunk"),
    "nns.prefix.publishes": ("counter", "prefix grain chunks published for reuse"),
    "nns.prefix.evictions": ("counter", "cached prefix entries reclaimed (LRU cap, trim, or remesh)"),
    "nns.prefix.entries": ("gauge", "live cached prefix entries"),
    "nns.prefix.refs": ("gauge", "pins held by live reader streams (refcounted entries)"),
    "nns.prefix.bytes": ("gauge", "bytes held by the shared-prefix page pool"),
    "nns.prefix.hit_tokens": ("counter", "prefill tokens skipped via prefix attach"),
    "nns.fleet.prefix_hits": ("counter", "prefix-cache hits fleet-wide (retired servers included)"),
    "nns.fleet.prefix_misses": ("counter", "prefix-cache misses fleet-wide (retired servers included)"),
    "nns.fleet.prefix_hit_ratio": ("gauge", "fleet prefix-cache hit ratio (hits / eligible lookups)"),
    "nns.fleet.prefix_entries": ("gauge", "cached prefix entries fleet-wide (live servers)"),

    # -- mesh-sharded serving (backends/jax_xla.py mesh= prop) -------------
    "nns.mesh.devices": ("gauge", "devices in the filter's serving mesh (0 = unsharded)"),
    "nns.mesh.dp": ("gauge", "data-parallel axis size of the serving mesh"),
    "nns.mesh.tp": ("gauge", "tensor-parallel axis size of the serving mesh"),
    "nns.mesh.scatters": ("counter", "host micro-batches scattered onto the mesh"),

    # -- device-resource resilience (OOM / device loss; core/resilience.py)
    "nns.device.oom_retries": ("counter", "invokes retried after a device OOM"),
    "nns.device.oom_shrinks": ("counter", "micro-batches split to a smaller bucket on OOM"),
    "nns.device.oom_evictions": ("counter", "cache/pool entries trimmed by OOM recovery"),
    "nns.device.lost": ("counter", "device-loss events seen by the element"),
    "nns.device.remeshes": ("counter", "backends/models rebuilt on surviving devices"),
    "nns.device.degraded": ("gauge", "1 while serving in a reduced (post-loss) configuration"),
    "nns.gen.oom_retries": ("counter", "slot-engine device steps retried after an OOM"),
    "nns.gen.oom_sheds": ("counter", "slots shed resumably to relieve HBM pressure"),
    "nns.gen.device_lost": ("counter", "lost-device events survived by the slot engine"),
    "nns.gen.device_lost_evicted": ("counter", "live streams handed off on device loss"),
    "nns.gen.remeshes": ("counter", "slot models rebuilt on surviving devices"),

    # -- memory-pressure watermarks (core/liveness.py monitor) -------------
    "nns.mem.bytes_in_use": ("gauge", "device HBM bytes in use (most-loaded chip)"),
    "nns.mem.bytes_limit": ("gauge", "device HBM capacity (most-loaded chip; 0 = unreported)"),
    "nns.mem.host_rss": ("gauge", "process resident set size, bytes"),
    "nns.mem.fraction": ("gauge", "watermark fraction driving the pressure state"),
    "nns.mem.pressure": ("gauge", "1 while above the high memory watermark (hysteresis)"),
    "nns.mem.polls": ("counter", "watermark evaluations (sweeper cadence)"),
    "nns.mem.trims": ("counter", "pool/cache trim sweeps fired at the high watermark"),
    "nns.mem.trimmed_entries": ("counter", "entries freed by memory-pressure trims"),
    "nns.mem.incidents": ("counter", "sustained-pressure flight-recorder incidents"),
    "nns.query.memory_shed": ("counter", "requests shed with BUSY at the memory watermark"),

    # -- per-stream SLO accounting (SloTracker; tenant= label) -------------
    "nns.slo.ttft_seconds": ("histogram", "time to first token, log2 buckets"),
    "nns.slo.ttft_p95_ms": ("gauge", "p95 time to first token, ms (log2 estimate)"),
    "nns.slo.ttft_burn": ("gauge", "TTFT error-budget burn rate (1.0 = consuming exactly the budget)"),
    "nns.slo.token_seconds": ("histogram", "per-token inter-arrival time, log2 buckets"),
    "nns.slo.token_p99_ms": ("gauge", "p99 per-token inter-arrival, ms (log2 estimate)"),
    "nns.slo.token_burn": ("gauge", "per-token-latency error-budget burn rate"),
    "nns.slo.availability": ("gauge", "observed goodput fraction (good / classified streams)"),
    "nns.slo.availability_burn": ("gauge", "availability error-budget burn rate"),
    "nns.slo.status": ("gauge", "worst armed objective: 0 met / 1 warn / 2 burned"),
    "nns.slo.good": ("counter", "streams that completed to their final token (goodput)"),
    "nns.slo.shed": ("counter", "streams refused by admission (BUSY exhausted)"),
    "nns.slo.evicted": ("counter", "streams cancelled/evicted before completion"),
    "nns.slo.expired": ("counter", "streams evicted on deadline/pace (typed expiry)"),
    "nns.slo.errors": ("counter", "streams lost to transport/server errors"),

    # -- fleet observatory (core/fleet.py; fleet= label) -------------------
    "nns.query.digests": ("counter", "telemetry digests published on the discovery plane"),
    "nns.fleet.servers": ("gauge", "live servers with a fresh digest"),
    "nns.fleet.draining": ("gauge", "live servers announcing draining"),
    "nns.fleet.degraded": ("gauge", "live servers announcing degraded"),
    "nns.fleet.swapping": ("gauge", "live servers mid hot-swap"),
    "nns.fleet.mem_pressured": ("gauge", "live servers above their memory watermark"),
    "nns.fleet.inflight": ("gauge", "requests in flight fleet-wide"),
    "nns.fleet.slots": ("gauge", "generation slots fleet-wide"),
    "nns.fleet.occupied": ("gauge", "occupied generation slots fleet-wide"),
    "nns.fleet.waiting": ("gauge", "prompts queued for a slot fleet-wide"),
    "nns.fleet.occupancy": ("gauge", "fleet slot occupancy (occupied / slots)"),
    "nns.fleet.tokens_per_s": ("gauge", "aggregate decode throughput, tokens/s (sum of live EWMAs)"),
    "nns.fleet.slot_headroom": ("gauge", "admittable free slots on unpressured servers"),
    "nns.fleet.mem_headroom_bytes": ("gauge", "bytes until the memory high watermark, fleet-wide"),
    "nns.fleet.tokens": ("counter", "tokens decoded fleet-wide (retired servers included)"),
    "nns.fleet.admitted": ("counter", "requests admitted fleet-wide (retired servers included)"),
    "nns.fleet.shed": ("counter", "requests shed fleet-wide (retired servers included)"),
    "nns.fleet.tenant_admitted": ("counter", "requests admitted for the tenant, fleet-wide"),
    "nns.fleet.tenant_shed": ("counter", "requests shed for the tenant, fleet-wide"),
    "nns.fleet.slo_burn": ("gauge", "worst per-tenant SLO burn rate across live servers"),
    "nns.fleet.digests": ("counter", "digests ingested by the observatory"),
    "nns.fleet.retired": ("counter", "server rows retired on announce tombstone"),
    "nns.fleet.stale_evicted": ("counter", "server rows retired on digest TTL expiry"),
    "nns.fleet.stale": ("gauge", "live-but-stale servers (digest older than the stale threshold; excluded from headroom)"),
    "nns.fleet.retired_evicted": ("counter", "retired-server snapshots evicted by the ledger cap (aggregates preserved)"),
    "nns.fleet.ttft_p95_ms": ("gauge", "worst per-server p95 time to first token across fresh digests, ms"),
    # control-plane health (explicit broker-loss signal — rows aging
    # stale silently is not a diagnosis)
    "nns.fleet.plane_connected": ("gauge", "1 while the observatory's broker connection is up"),
    "nns.fleet.plane_ingest_age_s": ("gauge", "seconds since the observatory last ingested any digest"),
    "nns.fleet.plane_reconnects": ("counter", "observatory broker reconnects (restart/failover dials that succeeded)"),

    # -- fleet autoscaling (core/autoscale.py FleetController) -------------
    "nns.autoscale.ticks": ("counter", "controller decision-loop evaluations"),
    "nns.autoscale.decisions": ("counter", "actions emitted by the planner"),
    "nns.autoscale.scale_ups": ("counter", "spawn actions dispatched to the actuator"),
    "nns.autoscale.scale_downs": ("counter", "zero-loss drain actions dispatched to the actuator"),
    "nns.autoscale.resizes": ("counter", "slot-width resize actions dispatched to the actuator"),
    "nns.autoscale.actions_failed": ("counter", "actuator tickets that completed unsuccessfully"),
    "nns.autoscale.actions_inflight": ("gauge", "actuator tickets dispatched but not yet complete"),
    "nns.autoscale.cooldown_skips": ("counter", "wanted actions suppressed by a per-kind cooldown"),
    "nns.autoscale.hysteresis_holds": ("counter", "pressure ticks held below the hysteresis streak"),
    "nns.autoscale.envelope_clamps": ("counter", "wanted actions clamped by the min/max fleet envelope"),
    "nns.autoscale.inflight_skips": ("counter", "targets skipped because an action is already in flight"),
    "nns.autoscale.predictive_decisions": ("counter", "decisions driven by the fitted performance model"),
    "nns.autoscale.reactive_decisions": ("counter", "decisions driven by the reactive (observed) path"),
    "nns.autoscale.model_samples": ("gauge", "observations banked by the performance model"),
    "nns.autoscale.model_ready": ("gauge", "1 when the predictive model has enough samples to act"),
    "nns.autoscale.target_servers": ("gauge", "fleet size the controller is steering toward"),
    # fail-static ladder + leader lease (control-plane resilience)
    "nns.autoscale.frozen": ("counter", "actions the fail-static ladder froze instead of dispatching (reason= label breaks down the cause)"),
    "nns.autoscale.plane_level": ("gauge", "assessed control-plane view: 0 ok / 1 degraded / 2 blind"),
    "nns.autoscale.standby_ticks": ("counter", "ticks spent standby (leader lease not held)"),
    "nns.autoscale.lease_held": ("gauge", "1 while this controller holds the leader lease"),
    "nns.autoscale.lease_epoch": ("gauge", "this controller's lease epoch (monotonic across takeovers)"),
    "nns.autoscale.lease_acquires": ("counter", "leader-lease acquisitions (vacant grant or expiry takeover)"),
    "nns.autoscale.lease_steals": ("counter", "expired foreign leases taken over"),
    "nns.autoscale.lease_losses": ("counter", "leaderships lost (superseding epoch, split-lease resolution, or self-fence)"),
    "nns.autoscale.lease_refusals": ("counter", "acquire attempts refused because a fresh foreign lease exists"),

    # -- control-plane resilience, target side (fencing + failover) --------
    "nns.query.reannounces": ("counter", "retained announces re-published after a broker reconnect"),
    "nns.query.plane_reconnects": ("counter", "announce-client broker reconnects (restart or failover)"),
    "nns.query.digest_publish_failures": ("counter", "digest publishes refused while the broker was unreachable"),
    "nns.query.stale_epoch_rejects": ("counter", "fenced drain commands refused for a stale lease epoch"),
    "nns.query.fence_epoch": ("gauge", "highest lease epoch this server has accepted"),
    "nns.gen.stale_epoch_rejects": ("counter", "fenced resize commands refused for a stale lease epoch"),
    "nns.gen.fence_epoch": ("gauge", "highest lease epoch this generator has accepted"),

    "nns.source.pending": ("gauge", "frames pushed but not yet pulled (appsrc)"),
    "nns.sink.rendered": ("counter", "logical frames rendered by the sink"),
    "nns.wire.corrupt_dropped": ("counter", "undecodable pub/sub frames dropped"),
    "nns.datarepo.truncated_samples": ("counter", "samples lost to a truncated repo"),
    # pools (process-wide; core/buffer.py)
    "nns.pool.frame_reused": ("counter", "frame carcasses reused"),
    "nns.pool.frame_recycled": ("counter", "frame carcasses recycled"),
    "nns.pool.device_allocated": ("counter", "staging buffers freshly allocated"),
    "nns.pool.device_reused": ("counter", "staging buffers reused"),
    "nns.pool.device_reuse_rate": ("gauge", "staging-buffer reuse fraction"),
    "nns.pool.rings_evicted": ("counter", "staging-buffer rings evicted by the key-space LRU"),
    "nns.pool.trims": ("counter", "staging-pool memory-pressure trims"),
    # -- continuous learning (elements/trainer.py + elements/validator.py) --
    "nns.train.steps": ("counter", "optimizer steps taken (monotone across resumes)"),
    "nns.train.samples": ("counter", "samples consumed by train steps"),
    "nns.train.epochs": ("counter", "training epochs completed"),
    "nns.train.loss": ("gauge", "most recent training loss"),
    "nns.train.checkpoints": ("counter", "durable (marker-committed) checkpoints written"),
    "nns.train.resumes": ("counter", "trainer starts that resumed from a durable checkpoint"),
    "nns.train.replay_skipped": ("counter", "already-trained samples skipped on resume (exactly-once accounting)"),
    "nns.train.gap_samples": ("counter", "partial-epoch samples dropped realigning after a mid-stream restart"),
    "nns.train.pauses": ("counter", "memory-watermark pauses of the train loop"),
    "nns.train.paused": ("gauge", "1 while train steps are paused (pressure or operator)"),
    "nns.train.restarts": ("counter", "trainer-backend revivals through the supervisor"),
    "nns.train.alive": ("gauge", "1 while the training thread is running"),
    "nns.train.validations": ("counter", "held-out validation passes over candidate checkpoints"),
    "nns.train.val_score": ("gauge", "most recent held-out validation score (gate metric)"),
    "nns.train.promotions": ("counter", "candidates promoted into the serving filter"),
    "nns.train.promotions_refused": ("counter", "candidates refused by the validation gate (regression)"),
    "nns.train.promote_failures": ("counter", "promotion attempts that failed (old model kept serving)"),
    # flight recorder
    "nns.flight.dumps": ("counter", "flight-recorder incident dumps written"),
}

#: numeric state -> code maps (documented in Documentation/observability.md)
STATE_CODES = {
    "idle": 0, "running": 1, "restarting": 2, "degraded": 3,
    "failed": 4, "finished": 5, "stalled": 6,
}
SERVER_STATE_CODES = {"stopped": 0, "serving": 1, "draining": 2}
SWAP_STATE_CODES = {"idle": 0, "staging": 1, "staged": 2, "observing": 3}

#: ``health_info()`` keys with an explicit stable metric name; numeric
#: keys absent here export as ``nns.health.<key>`` (gauge)
HEALTH_KEY_METRICS: Dict[str, str] = {
    "restarts": "nns.element.restarts",
    "restarts_window": "nns.element.restarts_window",
    "dead_letters": "nns.element.dead_letters",
    "dead_letter_depth": "nns.element.dead_letter_depth",
    "deadline_drops": "nns.element.deadline_drops",
    "stalls": "nns.element.stalls",
    "overruns": "nns.element.overruns",
    "model_version": "nns.filter.model_version",
    "swaps": "nns.filter.swaps",
    "swap_failures": "nns.filter.swap_failures",
    "rollbacks": "nns.filter.rollbacks",
    "inflight": "nns.query.inflight",
    "admitted": "nns.query.admitted",
    "load_shed": "nns.query.load_shed",
    "shedding": "nns.query.shedding",
    "admission_high": "nns.query.admission_high",
    "admission_low": "nns.query.admission_low",
    "ingress_depth": "nns.query.ingress_depth",
    "corrupt_requests": "nns.query.corrupt_requests",
    "goaway_sent": "nns.query.goaway_sent",
    "draining": "nns.lifecycle.draining",
    "delivered": "nns.query.delivered",
    "retried": "nns.query.retried",
    "busy_replies": "nns.query.busy_replies",
    "goaway_replies": "nns.query.goaway_replies",
    "deadline_expired": "nns.query.deadline_expired",
    "corruption_detected": "nns.query.corruption_detected",
    "degraded_frames": "nns.query.degraded_frames",
    "breaker_trips_evicted": "nns.query.breaker_trips_evicted",
    "affinity_remaps": "nns.query.affinity_remaps",
    "stream_resumes": "nns.query.stream_resumes",
    "stream_migrations": "nns.query.stream_migrations",
    "duplicate_tokens_dropped": "nns.query.duplicate_tokens_dropped",
    "resume_failures": "nns.query.resume_failures",
    "corrupt_dropped": "nns.wire.corrupt_dropped",
    "truncated_samples": "nns.datarepo.truncated_samples",
    "pending_frames": "nns.source.pending",
    "rendered_frames": "nns.sink.rendered",
    "gen_slots": "nns.gen.slots",
    "gen_occupied": "nns.gen.occupied",
    "gen_waiting": "nns.gen.waiting",
    "gen_joins": "nns.gen.joins",
    "gen_completed": "nns.gen.completed",
    "gen_evicted": "nns.gen.evicted",
    "gen_cancelled": "nns.gen.cancelled",
    "gen_tokens": "nns.gen.tokens",
    "gen_decode_steps": "nns.gen.decode_steps",
    "gen_prefill_chunks": "nns.gen.prefill_chunks",
    "gen_tokens_per_step": "nns.gen.tokens_per_step",
    "gen_jit_buckets": "nns.gen.jit_buckets",
    "gen_decode_compiles": "nns.gen.decode_compiles",
    "gen_resumes": "nns.gen.resumes",
    "gen_goaway_evicted": "nns.gen.goaway_evicted",
    "gen_resume_rejects": "nns.gen.resume_rejects",
    "gen_resizes": "nns.gen.resizes",
    # shared-prefix KV cache (engine.snapshot carries these only when armed)
    "prefix_hits": "nns.prefix.hits",
    "prefix_misses": "nns.prefix.misses",
    "prefix_publishes": "nns.prefix.publishes",
    "prefix_evictions": "nns.prefix.evictions",
    "prefix_entries": "nns.prefix.entries",
    "prefix_refs": "nns.prefix.refs",
    "prefix_bytes": "nns.prefix.bytes",
    "prefix_hit_tokens": "nns.prefix.hit_tokens",
    "mesh_devices": "nns.mesh.devices",
    "mesh_dp": "nns.mesh.dp",
    "mesh_tp": "nns.mesh.tp",
    "mesh_scatters": "nns.mesh.scatters",
    "profiler_active": "nns.profiler.active",
    # device-resource resilience (filter + slot engine)
    "oom_retries": "nns.device.oom_retries",
    "oom_shrinks": "nns.device.oom_shrinks",
    "oom_evictions": "nns.device.oom_evictions",
    "device_lost": "nns.device.lost",
    "remeshes": "nns.device.remeshes",
    "degraded": "nns.device.degraded",
    "gen_oom_retries": "nns.gen.oom_retries",
    "gen_oom_sheds": "nns.gen.oom_sheds",
    "gen_device_lost": "nns.gen.device_lost",
    "gen_device_lost_evicted": "nns.gen.device_lost_evicted",
    "gen_remeshes": "nns.gen.remeshes",
    # memory-pressure watermarks (serversrc health row)
    "mem_bytes_in_use": "nns.mem.bytes_in_use",
    "mem_bytes_limit": "nns.mem.bytes_limit",
    "mem_host_rss": "nns.mem.host_rss",
    "mem_fraction": "nns.mem.fraction",
    "mem_pressure": "nns.mem.pressure",
    "mem_polls": "nns.mem.polls",
    "mem_trims": "nns.mem.trims",
    "mem_trimmed_entries": "nns.mem.trimmed_entries",
    "mem_incidents": "nns.mem.incidents",
    "memory_shed": "nns.query.memory_shed",
    # fleet observatory (discovery-plane digests, serversrc health row)
    "digests_published": "nns.query.digests",
    # control-plane resilience (serversrc + generator health rows)
    "reannounces": "nns.query.reannounces",
    "plane_reconnects": "nns.query.plane_reconnects",
    "digest_publish_failures": "nns.query.digest_publish_failures",
    "stale_epoch_rejects": "nns.query.stale_epoch_rejects",
    "fence_epoch": "nns.query.fence_epoch",
    "gen_stale_epoch_rejects": "nns.gen.stale_epoch_rejects",
    "gen_fence_epoch": "nns.gen.fence_epoch",
    # continuous learning (tensor_trainer + model_validator health rows)
    "train_steps": "nns.train.steps",
    "train_samples": "nns.train.samples",
    "train_epochs": "nns.train.epochs",
    "train_loss": "nns.train.loss",
    "train_checkpoints": "nns.train.checkpoints",
    "train_resumes": "nns.train.resumes",
    "train_replay_skipped": "nns.train.replay_skipped",
    "train_gap_samples": "nns.train.gap_samples",
    "train_pauses": "nns.train.pauses",
    "train_paused": "nns.train.paused",
    "train_restarts": "nns.train.restarts",
    "train_alive": "nns.train.alive",
    "train_validations": "nns.train.validations",
    "train_val_score": "nns.train.val_score",
    "train_promotions": "nns.train.promotions",
    "train_promotions_refused": "nns.train.promotions_refused",
    "train_promote_failures": "nns.train.promote_failures",
}

#: non-numeric / structured health keys handled specially (or skipped) by
#: the collector — never auto-exported
HEALTH_KEYS_SPECIAL = (
    "state", "policy", "last_error", "model", "servers", "breakers",
    "remotes", "lifecycle", "swap_state", "swap_last_error",
    # mesh config string ("dp:2,tp:2") — the numeric axis sizes export
    # separately as nns.mesh.*
    "mesh_axes",
    # fleet routing / tenancy (handled by dedicated collector branches)
    "tenants", "remote_inflight", "endpoint_hints", "routing",
    # per-tenant SLO rows ({tenant: SloTracker row} — dedicated branch)
    "slo",
    # background-thread census ({thread name: ThreadBeat.snapshot()}):
    # liveness detail for operators, not a numeric series
    "threads",
)


def metric_kind(name: str) -> str:
    if name in METRICS:
        return METRICS[name][0]
    return "gauge"  # nns.health.<key> fallbacks


# ---------------------------------------------------------------------------
# Instruments
# ---------------------------------------------------------------------------
def _label_key(labels: Optional[Dict[str, str]]) -> Tuple:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic counter (thread-safe)."""

    __slots__ = ("name", "labels", "_value", "_lock")

    def __init__(self, name: str, labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.labels = dict(labels or {})
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def samples(self) -> List["Sample"]:
        return [Sample(self.name, self.labels, self._value, "counter")]


class Gauge:
    """Point-in-time value; ``set_fn`` makes it poll-at-scrape (zero
    hot-path cost — the callback runs only when someone reads)."""

    __slots__ = ("name", "labels", "_value", "_fn")

    def __init__(self, name: str, labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.labels = dict(labels or {})
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, v: float) -> None:
        self._value = float(v)

    def set_fn(self, fn: Callable[[], float]) -> None:
        self._fn = fn

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:  # scrape must never die on a gauge callback
                log.exception("gauge callback failed for %s", self.name)
                return 0.0
        return self._value

    def samples(self) -> List["Sample"]:
        return [Sample(self.name, self.labels, self.value, "gauge")]


#: default histogram buckets: request-latency shaped, seconds
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    5.0, 10.0,
)


class Histogram:
    """Fixed-bucket histogram (Prometheus classic histogram semantics)."""

    __slots__ = ("name", "labels", "buckets", "_counts", "_sum", "_count",
                 "_lock")

    def __init__(self, name: str, labels: Optional[Dict[str, str]] = None,
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        self.name = name
        self.labels = dict(labels or {})
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +Inf tail
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self._sum += v
            self._count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def samples(self) -> List["Sample"]:
        out: List[Sample] = []
        with self._lock:
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += self._counts[i]
                out.append(Sample(
                    f"{self.name}_bucket", {**self.labels, "le": repr(b)},
                    cum, "counter",
                ))
            cum += self._counts[-1]
            out.append(Sample(
                f"{self.name}_bucket", {**self.labels, "le": "+Inf"},
                cum, "counter",
            ))
            out.append(Sample(
                f"{self.name}_sum", self.labels, self._sum, "counter"))
            out.append(Sample(
                f"{self.name}_count", self.labels, self._count, "counter"))
        return out


#: log2 bucket layout shared by every Log2Histogram: boundary i is
#: 2**(LOG2_E_MIN + i) seconds — 2^-20 s (~1 µs) up to 2^4 s (16 s),
#: plus one overflow bucket.  Fixed at import so fused/unfused (and any
#: two processes) bucket identically.
LOG2_E_MIN = -20
LOG2_NBUCKETS = 25  # boundaries 2^-20 .. 2^4
_LOG2_SCALE = float(2 ** -LOG2_E_MIN)
LOG2_BOUNDS = tuple(2.0 ** (LOG2_E_MIN + i) for i in range(LOG2_NBUCKETS))


class Log2Histogram:
    """Fixed-bucket log2-scale latency histogram, hot-path-safe.

    The record path is one float multiply, one ``int.bit_length`` and one
    list increment — no lock, no allocation, no branch-per-bucket scan
    (the :class:`Histogram` record path takes a lock and walks its bucket
    list; this one is safe to arm on every frame).  The contract is
    SINGLE-WRITER per instrument on the record path — which the scheduler
    guarantees: each element's handler (and each mailbox's consumer, and
    each dispatch window's ``pop_ready``) runs on exactly one streaming
    thread.  Scrape-time readers may race a write and see a snapshot off
    by the in-flight observation; quantiles are estimates by design.

    Quantiles are log-linear interpolations within a bucket, so p50/p95/
    p99 carry ~2x resolution — the right grain for "where did the time
    go", not for microbenchmarks (use the tracer's proc ring for those).
    """

    __slots__ = ("_counts", "_sum")

    def __init__(self):
        self._counts = [0] * (LOG2_NBUCKETS + 1)  # +1: overflow tail
        self._sum = 0.0

    def record(self, seconds: float) -> None:
        # bucket i collects v in [2^(i-1), 2^i) * 2^LOG2_E_MIN seconds
        idx = int(seconds * _LOG2_SCALE).bit_length()
        if idx > LOG2_NBUCKETS:
            idx = LOG2_NBUCKETS
        self._counts[idx] += 1
        self._sum += seconds

    def record_n(self, seconds: float, n: int) -> None:
        """``n`` observations of the same value in ONE bucket increment —
        how per-token inter-arrival is recorded from a k-token decode
        scan / chunk (k tokens at dt/k each) without k bucketing
        passes."""
        idx = int(seconds * _LOG2_SCALE).bit_length()
        if idx > LOG2_NBUCKETS:
            idx = LOG2_NBUCKETS
        self._counts[idx] += n
        self._sum += seconds * n

    def count_over(self, seconds: float) -> int:
        """Observations in buckets strictly ABOVE the bucket holding
        ``seconds`` — the (bucket-grain, deterministic) violation count
        SLO burn rates are computed from.  Observations sharing the
        threshold's bucket count as compliant: at log2 grain that is the
        conservative reading, and it is exactly reproducible, which the
        burn-rate truth table pins."""
        idx = int(seconds * _LOG2_SCALE).bit_length()
        if idx >= LOG2_NBUCKETS:
            return 0
        return sum(self._counts[idx + 1:])

    @property
    def count(self) -> int:
        return sum(self._counts)

    @property
    def sum(self) -> float:
        return self._sum

    def state(self) -> Tuple[int, ...]:
        """Immutable bucket-count snapshot (parity tests pin this)."""
        return tuple(self._counts)

    def quantile(self, q: float) -> Optional[float]:
        """Estimated q-quantile in seconds (None when empty)."""
        counts = list(self._counts)
        total = sum(counts)
        if total == 0:
            return None
        target = q * total
        cum = 0.0
        for i, c in enumerate(counts):
            if c and cum + c >= target:
                lo = 0.0 if i == 0 else 2.0 ** (LOG2_E_MIN + i - 1)
                hi = 2.0 ** (LOG2_E_MIN + min(i, LOG2_NBUCKETS))
                return lo + (hi - lo) * (target - cum) / c
            cum += c
        return 2.0 ** (LOG2_E_MIN + LOG2_NBUCKETS)

    def percentiles_us(self) -> Dict[str, float]:
        """{p50, p95, p99} in microseconds (empty dict when empty)."""
        out: Dict[str, float] = {}
        for tag, q in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99)):
            v = self.quantile(q)
            if v is None:
                return {}
            out[tag] = v * 1e6
        return out

    def samples(self, name: str,
                labels: Optional[Dict[str, str]] = None) -> List["Sample"]:
        """Prometheus classic-histogram samples (cumulative le buckets)."""
        labels = dict(labels or {})
        counts = list(self._counts)
        out: List[Sample] = []
        cum = 0
        for i, b in enumerate(LOG2_BOUNDS):
            cum += counts[i]
            out.append(Sample(
                f"{name}_bucket", {**labels, "le": repr(b)}, cum, "counter"))
        cum += counts[-1]
        out.append(Sample(
            f"{name}_bucket", {**labels, "le": "+Inf"}, cum, "counter"))
        out.append(Sample(f"{name}_sum", labels, self._sum, "counter"))
        out.append(Sample(f"{name}_count", dict(labels), cum, "counter"))
        return out


#: quantile gauges derived from each log2 histogram at scrape time
#: (PURE LITERAL: the schema lint reads metric names statically)
HIST_QUANTILE_GAUGES: Dict[str, Tuple[Tuple[str, float], ...]] = {
    "nns.element.handle_seconds": (
        ("nns.element.handle_p50_us", 0.5),
        ("nns.element.handle_p95_us", 0.95),
        ("nns.element.handle_p99_us", 0.99),
    ),
    "nns.element.queue_wait_seconds": (
        ("nns.element.queue_wait_p50_us", 0.5),
        ("nns.element.queue_wait_p99_us", 0.99),
    ),
    "nns.feed.window_dwell_seconds": (
        ("nns.feed.window_dwell_p50_us", 0.5),
        ("nns.feed.window_dwell_p99_us", 0.99),
    ),
}


def hist_samples(name: str, hist: Log2Histogram,
                 labels: Optional[Dict[str, str]] = None) -> List["Sample"]:
    """A log2 histogram as exported samples: the classic bucket series
    plus the derived p50/p95/p99 gauges (µs) catalogued for it.  Empty
    histograms export nothing — an element that never crossed a mailbox
    must not show a fake zero-latency series."""
    if hist.count == 0:
        return []
    out = hist.samples(name, labels)
    for gname, q in HIST_QUANTILE_GAUGES.get(name, ()):
        v = hist.quantile(q)
        if v is not None:
            out.append(Sample(gname, dict(labels or {}), v * 1e6, "gauge"))
    return out


@dataclass
class Sample:
    """One exported measurement."""

    name: str
    labels: Dict[str, str] = field(default_factory=dict)
    value: float = 0.0
    kind: str = "gauge"


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------
class MetricsRegistry:
    """Process-wide instrument table + scrape-time collectors.

    Instruments are keyed by (name, labelset) and must use catalogued
    names (:data:`METRICS`) — the stable-naming contract the
    ``check_health_schema`` lint enforces.  Collectors are callables
    returning an iterable of :class:`Sample`; pipelines register one on
    ``start()`` and unregister on ``stop()``, so all per-frame cost lives
    at scrape time, not on the hot path."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[Tuple[str, Tuple], Any] = {}
        self._collectors: List[Callable[[], Iterable[Sample]]] = []

    def _get(self, cls, name: str, labels: Optional[Dict[str, str]],
             **kw) -> Any:
        if name not in METRICS and not name.startswith("nns.health."):
            raise ValueError(
                f"metric name {name!r} is not in the telemetry.METRICS "
                "catalog (stable-naming contract; add it there and to "
                "Documentation/observability.md)"
            )
        key = (name, _label_key(labels))
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                inst = cls(name, labels, **kw)
                self._instruments[key] = inst
            elif not isinstance(inst, cls):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}")
            return inst

    def counter(self, name: str,
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str,
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, labels: Optional[Dict[str, str]] = None,
                  buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, labels, buckets=buckets)

    def remove_labeled(self, **labels) -> int:
        """Drop every instrument whose labels include all of ``labels``
        (a stopping pipeline evicts its instruments so restarts and tests
        do not accumulate stale series).  Returns the count removed."""
        want = set(_label_key(labels))
        with self._lock:
            doomed = [
                k for k in self._instruments if want <= set(k[1])
            ]
            for k in doomed:
                del self._instruments[k]
        return len(doomed)

    def collect_labeled(self, **labels) -> List[Sample]:
        """Samples of every INSTRUMENT whose labels include ``labels``
        (pipeline snapshots merge their own instruments this way)."""
        want = set(_label_key(labels))
        with self._lock:
            instruments = [
                inst for (name, lk), inst in self._instruments.items()
                if want <= set(lk)
            ]
        out: List[Sample] = []
        for inst in instruments:
            out.extend(inst.samples())
        return out

    def register_collector(self, fn: Callable[[], Iterable[Sample]]) -> None:
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def unregister_collector(self, fn: Callable[[], Iterable[Sample]]) -> None:
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    def collect(self) -> List[Sample]:
        with self._lock:
            instruments = list(self._instruments.values())
            collectors = list(self._collectors)
        out: List[Sample] = []
        for inst in instruments:
            out.extend(inst.samples())
        for fn in collectors:
            try:
                out.extend(fn())
            except Exception:  # a scrape must survive any collector bug
                log.exception("telemetry collector failed: %r", fn)
        return out

    # -- rendering ----------------------------------------------------------
    @staticmethod
    def _prom_name(name: str) -> str:
        return re.sub(r"[^a-zA-Z0-9_:]", "_", name)

    @staticmethod
    def _prom_labels(labels: Dict[str, str]) -> str:
        if not labels:
            return ""
        parts = []
        for k, v in sorted(labels.items()):
            v = str(v).replace("\\", r"\\").replace('"', r"\"").replace(
                "\n", r"\n")
            parts.append(f'{MetricsRegistry._prom_name(str(k))}="{v}"')
        return "{" + ",".join(parts) + "}"

    def render_prometheus(self) -> str:
        """The registry as Prometheus text exposition format 0.0.4."""
        by_name: Dict[str, List[Sample]] = {}
        for s in self.collect():
            by_name.setdefault(s.name, []).append(s)
        lines: List[str] = []
        typed: set = set()
        for name in sorted(by_name):
            base = name
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix) and name[: -len(suffix)] in METRICS:
                    base = name[: -len(suffix)]
            pname = self._prom_name(name)
            pbase = self._prom_name(base)
            if pbase not in typed:
                typed.add(pbase)
                kind, help_ = METRICS.get(
                    base, ("gauge", "ad-hoc health gauge"))
                lines.append(f"# HELP {pbase} {help_}")
                lines.append(f"# TYPE {pbase} {kind}")
            for s in by_name[name]:
                v = float(s.value)
                value = repr(int(v)) if v == int(v) else repr(v)
                lines.append(f"{pname}{self._prom_labels(s.labels)} {value}")
        return "\n".join(lines) + "\n"


#: the process-wide default registry every pipeline registers into
REGISTRY = MetricsRegistry()


# ---------------------------------------------------------------------------
# Pipeline-label claims
# ---------------------------------------------------------------------------
# Pipeline names default to "pipeline" (both Pipeline() and
# parse_pipeline()), so the ``pipeline=`` label CANNOT be the bare name:
# two concurrent defaults would alias each other's series, and one
# pipeline's stop() (remove_labeled) would evict the other's live
# instruments.  Labels are claimed per live pipeline — the first claim
# of a name gets it verbatim, concurrent claims get "name#2", "name#3"…
_label_lock = threading.Lock()
_active_labels: set = set()


def claim_pipeline_label(name: str) -> str:
    """A pipeline= label value unique among LIVE pipelines."""
    with _label_lock:
        label, i = name, 1
        while label in _active_labels:
            i += 1
            label = f"{name}#{i}"
        _active_labels.add(label)
        return label


def release_pipeline_label(label: str) -> None:
    with _label_lock:
        _active_labels.discard(label)


# ---------------------------------------------------------------------------
# Prometheus exposition server
# ---------------------------------------------------------------------------
_live_servers_lock = threading.Lock()
_live_servers: List["MetricsServer"] = []


def live_server_count() -> int:
    """Open exposition servers (conftest leak-check hook)."""
    with _live_servers_lock:
        return len(_live_servers)


class MetricsServer:
    """Tiny HTTP exposition endpoint serving ``/metrics`` as Prometheus
    text.  One listener socket + one serve thread (named
    ``<owner>-metrics`` so the test-suite leak census sees it); closed
    listeners release their fd synchronously in :meth:`close`."""

    def __init__(self, registry: MetricsRegistry = None, port: int = 0,
                 host: str = "127.0.0.1", name: str = "nns"):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        reg = registry if registry is not None else REGISTRY

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                if self.path.split("?")[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                try:
                    body = reg.render_prometheus().encode()
                except Exception as e:  # noqa: BLE001 — scrape boundary
                    self.send_error(500, str(e))
                    return
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):  # quiet scrapes
                log.debug("metrics http: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self.host = host
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            name=f"{name}-metrics", daemon=True,
        )
        self._thread.start()
        with _live_servers_lock:
            _live_servers.append(self)
        log.info("metrics exposition on http://%s:%d/metrics", host, self.port)

    def close(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()  # listener fd released HERE, synchronously
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        with _live_servers_lock:
            if self in _live_servers:
                _live_servers.remove(self)


# ---------------------------------------------------------------------------
# Snapshot view (pollable; bench rows attach this)
# ---------------------------------------------------------------------------
class TelemetrySnapshot:
    """Immutable sample list with lookup helpers."""

    def __init__(self, samples: List[Sample]):
        self.samples = list(samples)

    def get(self, name: str, default: float = None, **labels):
        want = set(labels.items())
        for s in self.samples:
            if s.name == name and want <= set(s.labels.items()):
                return s.value
        return default

    def sum(self, name: str, **labels) -> float:
        want = set(labels.items())
        return sum(
            s.value for s in self.samples
            if s.name == name and want <= set(s.labels.items())
        )

    def names(self) -> set:
        return {s.name for s in self.samples}

    def counters(self) -> Dict[Tuple[str, Tuple], float]:
        """{(name, labelset): value} for counter-kind samples only — the
        deterministic subset the fused/unfused parity test pins."""
        return {
            (s.name, _label_key(s.labels)): s.value
            for s in self.samples if s.kind == "counter"
        }

    def flat(self) -> Dict[str, float]:
        """{name: value} — counters summed across labelsets, gauges
        maxed; the compact labeled dump bench rows carry.  Histogram
        ``_bucket`` series are elided (cumulative per-le counts summed
        across labels are meaningless); their ``_sum``/``_count`` and the
        derived p50/p95/p99 gauges stay."""
        out: Dict[str, float] = {}
        for s in self.samples:
            if s.name.endswith("_bucket"):
                continue
            if s.kind == "counter":
                out[s.name] = out.get(s.name, 0.0) + float(s.value)
            else:
                out[s.name] = max(out.get(s.name, float("-inf")),
                                  float(s.value))
        return {
            k: (round(v, 6) if isinstance(v, float) else v)
            for k, v in out.items()
        }


# ---------------------------------------------------------------------------
# Per-stream SLO accounting
# ---------------------------------------------------------------------------
#: numeric status codes exported as ``nns.slo.status`` (documented map)
SLO_STATUS_CODES = {"met": 0, "warn": 1, "burned": 2}
#: burn-rate band edges: burn <= 1.0 is inside budget ("met"); above
#: SLO_BURN_BURNED the budget is being consumed at 2x+ ("burned")
SLO_BURN_BURNED = 2.0


def slo_status(burn: Optional[float]) -> str:
    """The met/warn/burned truth table for one burn rate (None = no
    armed objective = trivially met)."""
    if burn is None or burn <= 1.0:
        return "met"
    if burn < SLO_BURN_BURNED:
        return "warn"
    return "burned"


class _SloRow:
    """One tenant's SLO instruments.  Histogram record paths follow the
    Log2Histogram single-writer contract (each element's tracker is
    written from exactly one thread: the generator's pump or the
    client's dispatch thread)."""

    __slots__ = ("ttft", "token", "good", "shed", "evicted", "expired",
                 "errors")

    def __init__(self):
        self.ttft = Log2Histogram()
        self.token = Log2Histogram()
        self.good = 0
        self.shed = 0
        self.evicted = 0
        self.expired = 0
        self.errors = 0


class SloTracker:
    """Declarative per-tenant SLO objectives + the instruments their
    error-budget burn rates are computed from.

    Hot-path cost: ONE Log2Histogram record per first token (TTFT), one
    ``record_n`` per chunk/scan (per-token inter-arrival), one integer
    increment per stream outcome.  Burn rates, percentiles, and the
    met/warn/burned status are computed at SNAPSHOT (scrape) time only.

    Objectives (0 / None = not armed):

    * ``ttft_p95_s`` — 95% of streams must see their first token within
      this many seconds; burn = observed-over fraction / 0.05.
    * ``token_p99_s`` — 99% of token inter-arrivals under this bound;
      burn = observed-over fraction / 0.01.
    * ``availability`` — goodput fraction objective (e.g. 0.999); bad =
      shed + evicted + expired + errors; burn = bad fraction / allowed
      bad fraction.

    Violation counts use :meth:`Log2Histogram.count_over` — bucket-grain
    and deterministic, the documented precision of the log2 machinery."""

    def __init__(self, ttft_p95_s: float = 0.0, token_p99_s: float = 0.0,
                 availability: float = 0.0):
        self.ttft_p95_s = max(0.0, float(ttft_p95_s or 0.0))
        self.token_p99_s = max(0.0, float(token_p99_s or 0.0))
        self.availability = float(availability or 0.0)
        if not 0.0 <= self.availability < 1.0:
            raise ValueError(
                f"availability objective {availability!r} must be in "
                "[0, 1) (1.0 leaves a zero error budget — nothing can "
                "meet it)")
        self._rows: Dict[str, _SloRow] = {}
        self._lock = threading.Lock()

    @property
    def armed(self) -> bool:
        return bool(self.ttft_p95_s or self.token_p99_s
                    or self.availability)

    def _row(self, tenant: str) -> _SloRow:
        row = self._rows.get(tenant)
        if row is None:
            with self._lock:
                row = self._rows.setdefault(tenant, _SloRow())
        return row

    # -- record paths (cheap; single writer per element) --------------------
    def note_ttft(self, tenant: str, seconds: float) -> None:
        self._row(tenant).ttft.record(seconds)

    def note_tokens(self, tenant: str, elapsed_s: float, n: int) -> None:
        """``n`` tokens arrived ``elapsed_s`` after the previous ones:
        n inter-arrival observations of elapsed/n each (one bucket
        increment — see :meth:`Log2Histogram.record_n`)."""
        if n > 0:
            self._row(tenant).token.record_n(elapsed_s / n, n)

    def note_stream(self, tenant: str, outcome: str) -> None:
        """Terminal classification of one stream: ``good`` | ``shed`` |
        ``evicted`` | ``expired`` | ``error``."""
        row = self._row(tenant)
        if outcome == "good":
            row.good += 1
        elif outcome == "shed":
            row.shed += 1
        elif outcome == "evicted":
            row.evicted += 1
        elif outcome == "expired":
            row.expired += 1
        else:
            row.errors += 1

    # -- scrape-time views --------------------------------------------------
    @staticmethod
    def _latency_burn(hist: Log2Histogram, objective_s: float,
                      allowed_frac: float) -> Optional[float]:
        if objective_s <= 0.0 or hist.count == 0:
            return None
        frac_over = hist.count_over(objective_s) / hist.count
        return frac_over / allowed_frac

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """{tenant: row} for ``health_info()`` — numeric gauges/counters
        only (the telemetry collector's ``slo`` branch maps them onto
        ``nns.slo.*`` samples with a tenant label); burn rates and
        percentiles computed HERE, at read time."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            rows = dict(self._rows)
        for tenant, row in rows.items():
            classified = (row.good + row.shed + row.evicted + row.expired
                          + row.errors)
            entry: Dict[str, Any] = {
                "good": row.good,
                "shed": row.shed,
                "evicted": row.evicted,
                "expired": row.expired,
                "errors": row.errors,
            }
            burns = []
            ttft_burn = self._latency_burn(row.ttft, self.ttft_p95_s, 0.05)
            if row.ttft.count:
                p95 = row.ttft.quantile(0.95)
                if p95 is not None:
                    entry["ttft_p95_ms"] = round(p95 * 1e3, 3)
            if ttft_burn is not None:
                entry["ttft_burn"] = round(ttft_burn, 3)
                burns.append(ttft_burn)
            token_burn = self._latency_burn(
                row.token, self.token_p99_s, 0.01)
            if row.token.count:
                p99 = row.token.quantile(0.99)
                if p99 is not None:
                    entry["token_p99_ms"] = round(p99 * 1e3, 3)
            if token_burn is not None:
                entry["token_burn"] = round(token_burn, 3)
                burns.append(token_burn)
            if classified:
                avail = row.good / classified
                entry["availability"] = round(avail, 6)
                if self.availability > 0.0:
                    avail_burn = (1.0 - avail) / (1.0 - self.availability)
                    entry["availability_burn"] = round(avail_burn, 3)
                    burns.append(avail_burn)
            worst = max(burns) if burns else None
            entry["status"] = SLO_STATUS_CODES[slo_status(worst)]
            out[tenant] = entry
        return out

    def hist_rows(self) -> List[Tuple[str, Log2Histogram, Dict[str, str]]]:
        """(metric name, histogram, extra labels) triples for the
        element ``histograms_info`` hook — bucket series export with a
        ``tenant`` label, scrape time only."""
        with self._lock:
            rows = dict(self._rows)
        out = []
        for tenant, row in rows.items():
            labels = {"tenant": tenant or "_"}
            out.append(("nns.slo.ttft_seconds", row.ttft, labels))
            out.append(("nns.slo.token_seconds", row.token, labels))
        return out


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------
class FlightRecorder:
    """Bounded ring of recent per-frame span events + incident dumps.

    Fed by :class:`~.tracer.PipelineTracer` (the recorder rides the
    tracer's existing one-branch-per-frame hook): ``begin`` marks a frame
    entering an element (open span — this is what identifies a frame
    STUCK inside a hung element), ``end`` appends the completed span to
    the ring.  ``dump`` writes the assembled per-trace timelines to log +
    a JSON file, rate-limited so an incident storm cannot turn the
    recorder into its own outage.

    With ``profile_incidents`` (default on) each dump also runs the
    incident-time thread profiler (:func:`~.profiler.profile_threads`):
    the named framework threads are wall-clock-sampled for a bounded
    window and their collapsed top-stacks land in the dump's
    ``thread_profile`` field — a hung element's streaming thread shows
    exactly where it is stuck, without a chip or TensorBoard.  The
    capture blocks the dumping thread for ``profile_duration_s``
    (default 0.2 s), bounded overall by the dump rate limit."""

    def __init__(self, capacity: int = 4096, dump_dir: Optional[str] = None,
                 min_dump_interval_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic,
                 profile_incidents: bool = True,
                 profile_duration_s: float = 0.2,
                 profile_hz: float = 50.0):
        self._ring: deque = deque(maxlen=max(16, capacity))
        self._open: Dict[str, Tuple[Any, float]] = {}
        self._dump_dir = dump_dir
        self._min_interval = float(min_dump_interval_s)
        self._clock = clock
        self._last_dump_ts = float("-inf")
        self._dump_lock = threading.Lock()
        self._profile = bool(profile_incidents)
        self._profile_duration_s = float(profile_duration_s)
        self._profile_hz = float(profile_hz)
        self.dumps = 0
        self.suppressed = 0

    # -- hot path (enabled only; worker threads) ----------------------------
    def begin(self, element: str, frame) -> None:
        meta = getattr(frame, "meta", None)
        tid = meta.get(TRACE_ID_META) if meta is not None else None
        self._open[element] = (tid, time.perf_counter())

    def end(self, element: str, frame, t_in: float, t_out: float,
            nframes: int) -> None:
        meta = getattr(frame, "meta", None)
        tid = meta.get(TRACE_ID_META) if meta is not None else None
        self._open.pop(element, None)
        # deque append is GIL-atomic; full ring evicts oldest
        self._ring.append((tid, element, t_in, t_out, nframes))

    # -- assembly -----------------------------------------------------------
    @staticmethod
    def _snap(dq: deque) -> list:
        for _ in range(4):  # concurrent appends can break list(deque)
            try:
                return list(dq)
            except RuntimeError:
                continue
        return []

    def timelines(self) -> Dict[Any, List[Dict[str, Any]]]:
        """Per-trace span lists, oldest span first; open spans (entered,
        never left — the stalled frame) are flagged ``open: true``."""
        out: Dict[Any, List[Dict[str, Any]]] = {}
        for tid, element, t_in, t_out, nframes in self._snap(self._ring):
            out.setdefault(tid, []).append({
                "element": element, "t_in": t_in, "t_out": t_out,
                "dur_ms": round((t_out - t_in) * 1e3, 3),
                "frames": nframes,
            })
        for element, (tid, t_in) in list(self._open.items()):
            out.setdefault(tid, []).append({
                "element": element, "t_in": t_in, "open": True,
                "stuck_for_ms": round(
                    (time.perf_counter() - t_in) * 1e3, 3),
            })
        return out

    def dump(self, reason: str, source: str, detail: Any = None,
             logger=None) -> Optional[str]:
        """Write the current timelines to a JSON file (+ a log summary).
        Rate-limited; returns the file path or None when suppressed or
        nothing was recorded."""
        with self._dump_lock:
            now = self._clock()
            if now - self._last_dump_ts < self._min_interval:
                self.suppressed += 1
                return None
            self._last_dump_ts = now
        # thread profile FIRST: a stalled thread is still parked on its
        # hang site right now — sample it before assembling timelines
        profile = None
        if self._profile:
            try:
                from .profiler import profile_threads

                profile = profile_threads(
                    duration_s=self._profile_duration_s,
                    hz=self._profile_hz)
                REGISTRY.counter("nns.profiler.captures").inc()
            except Exception:  # profiling must never break the dump
                (logger or log).exception("incident thread profile failed")
        timelines = self.timelines()
        payload = {
            "reason": reason,
            "source": source,
            "detail": repr(detail) if detail is not None else None,
            "wall_time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "thread_profile": profile,
            "traces": [
                {"trace_id": tid, "spans": spans}
                for tid, spans in timelines.items()
            ],
        }
        import tempfile

        dump_dir = (
            self._dump_dir
            or os.environ.get("NNS_FLIGHT_DIR")
            or tempfile.gettempdir()
        )
        path = os.path.join(
            dump_dir,
            f"nns_flight_{source}_{reason}_{int(time.time() * 1000)}.json",
        )
        lg = logger or log
        try:
            os.makedirs(dump_dir, exist_ok=True)
            with open(path, "w") as f:
                json.dump(payload, f, indent=1)
        except OSError as e:
            lg.warning("flight-recorder dump failed: %s", e)
            return None
        self.dumps += 1
        try:
            REGISTRY.counter("nns.flight.dumps").inc()
        except Exception:  # allow-silent: accounting only
            pass
        open_spans = [
            s for spans in timelines.values() for s in spans
            if s.get("open")
        ]
        lg.warning(
            "flight recorder: %s at %s -> %s (%d trace(s), %d open "
            "span(s)%s)", reason, source, path, len(timelines),
            len(open_spans),
            "".join(
                f"; STUCK {s['element']} {s['stuck_for_ms']:.0f}ms"
                for s in open_spans[:3]
            ),
        )
        return path


# ---------------------------------------------------------------------------
# Pipeline collector (scrape-time; called via REGISTRY collectors)
# ---------------------------------------------------------------------------
def _num(v) -> Optional[float]:
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    if isinstance(v, (int, float)):
        return float(v)
    return None


def collect_pipeline(pipe) -> List[Sample]:
    """Every signal source of one pipeline as labeled samples: element
    ``health_info()`` counters, :class:`PipelineTracer` per-element
    stats, the filter's CompletionWindow / HostStagingLane gauges, query
    breaker / admission / lifecycle states, and the process-wide
    FramePool / DeviceBufferPool counters.  Runs only at scrape/snapshot
    time — the frame hot path is untouched."""
    base = {"pipeline": pipe.telemetry_label}
    out: List[Sample] = []
    out.append(Sample("nns.pipeline.delivered", dict(base),
                      pipe.delivered_frames(), "counter"))
    out.append(Sample("nns.pipeline.errors", dict(base),
                      len(pipe.errors), "gauge"))
    # -- health() -----------------------------------------------------------
    for el_name, entry in pipe.health().items():
        labels = {**base, "element": el_name}
        for key, val in entry.items():
            if key == "state":
                out.append(Sample(
                    "nns.lifecycle.state", dict(labels),
                    STATE_CODES.get(val, -1), "gauge"))
                continue
            if key == "lifecycle":
                out.append(Sample(
                    "nns.lifecycle.server_state", dict(labels),
                    SERVER_STATE_CODES.get(val, -1), "gauge"))
                continue
            if key == "swap_state":
                out.append(Sample(
                    "nns.lifecycle.swap_state", dict(labels),
                    SWAP_STATE_CODES.get(val, -1), "gauge"))
                continue
            if key == "breakers" and isinstance(val, dict):
                for remote, snap in val.items():
                    rl = {**labels, "remote": remote}
                    out.append(Sample(
                        "nns.query.breaker_open", dict(rl),
                        1.0 if snap.get("state") == "open" else 0.0,
                        "gauge"))
                    out.append(Sample(
                        "nns.query.breaker_trips", dict(rl),
                        snap.get("trips", 0), "counter"))
                    out.append(Sample(
                        "nns.query.breaker_failures", dict(rl),
                        snap.get("recent_failures", 0), "gauge"))
                continue
            if key == "tenants" and isinstance(val, dict):
                for tenant, row in val.items():
                    tl = {**labels, "tenant": tenant or "_"}
                    out.append(Sample(
                        "nns.query.tenant_inflight", dict(tl),
                        row.get("inflight", 0), "gauge"))
                    out.append(Sample(
                        "nns.query.tenant_admitted", dict(tl),
                        row.get("admitted", 0), "counter"))
                    out.append(Sample(
                        "nns.query.tenant_shed", dict(tl),
                        row.get("shed", 0), "counter"))
                    out.append(Sample(
                        "nns.query.tenant_quota", dict(tl),
                        row.get("quota", 0), "gauge"))
                continue
            if key == "remote_inflight" and isinstance(val, dict):
                for remote, v in val.items():
                    out.append(Sample(
                        "nns.query.remote_inflight",
                        {**labels, "remote": remote}, v, "gauge"))
                continue
            if key == "slo" and isinstance(val, dict):
                # per-tenant SLO rows (SloTracker.snapshot): every
                # numeric field maps onto its catalogued nns.slo.* name
                for tenant, srow in val.items():
                    tl = {**labels, "tenant": tenant or "_"}
                    for skey, sval in srow.items():
                        n = _num(sval)
                        if n is None:
                            continue
                        mname = f"nns.slo.{skey}"
                        if mname in METRICS:
                            out.append(Sample(
                                mname, dict(tl), n, metric_kind(mname)))
                continue
            if key == "remotes" and isinstance(val, dict):
                for remote, agg in val.items():
                    rl = {**labels, "remote": remote}
                    for akey, aval in agg.items():
                        n = _num(aval)
                        if n is None:
                            continue
                        mname = f"nns.query.remote_{akey}"
                        if mname in METRICS:
                            out.append(Sample(
                                mname, dict(rl), n, metric_kind(mname)))
                continue
            if key in HEALTH_KEYS_SPECIAL:
                continue
            n = _num(val)
            if n is None:
                continue
            mname = HEALTH_KEY_METRICS.get(key, f"nns.health.{key}")
            out.append(Sample(mname, dict(labels), n, metric_kind(mname)))
    # -- tracer per-element stats ------------------------------------------
    tracer = pipe.tracer
    if tracer is not None:
        for el_name, r in tracer.report().items():
            labels = {**base, "element": el_name}
            pairs = (
                ("nns.element.frames", r["frames"]),
                ("nns.element.calls", r["calls"]),
                ("nns.element.proctime_us", r["proctime_us_avg"]),
                ("nns.element.proctime_p99_us", r["proctime_us_p99"]),
                ("nns.element.fps", r["framerate_fps"]),
                ("nns.element.interlatency_ms", r["interlatency_ms_avg"]),
                ("nns.element.queue_depth", r["queuelevel_avg"]),
                ("nns.element.queue_capacity", r["queue_capacity"]),
                ("nns.element.bitrate_mbps", r["bitrate_mbps"]),
            )
            for mname, v in pairs:
                if v is None:
                    continue
                out.append(Sample(mname, dict(labels), float(v),
                                  metric_kind(mname)))
        # always-on log2 latency histograms (handle time + mailbox
        # queue-wait), with their derived p50/p95/p99 gauges
        for el_name, mname, h in tracer.latency_histograms():
            out.extend(hist_samples(mname, h, {**base, "element": el_name}))
    # -- element-specific gauges (filter window/lane, client inflight) ------
    for el_name, el in pipe.elements.items():
        labels = {**base, "element": el_name}
        hinfo = getattr(el, "histograms_info", None)
        if hinfo is not None:
            try:
                for hrow in hinfo() or ():
                    # (name, hist) or (name, hist, extra_labels) — the
                    # 3-form carries per-tenant labels (SLO histograms)
                    mname, h = hrow[0], hrow[1]
                    lb = dict(labels)
                    if len(hrow) > 2 and hrow[2]:
                        lb.update(hrow[2])
                    out.extend(hist_samples(mname, h, lb))
            except Exception:  # scrape must survive element bugs
                log.exception("histograms_info failed for %s", el_name)
        info = getattr(el, "metrics_info", None)
        if info is None:
            continue
        try:
            rows = info() or ()
        except Exception:  # scrape must survive element bugs
            log.exception("metrics_info failed for %s", el_name)
            continue
        for row in rows:
            if len(row) == 2:
                mname, v = row
                extra = None
            else:
                mname, v, extra = row
            n = _num(v)
            if n is None:
                continue
            lb = dict(labels)
            if extra:
                lb.update(extra)
            out.append(Sample(mname, lb, n, metric_kind(mname)))
    # -- process-wide pools (labeled by pipeline for scrape context) --------
    from .buffer import DEVICE_POOL, FRAME_POOL

    out.append(Sample("nns.pool.frame_reused", dict(base),
                      FRAME_POOL.reused, "counter"))
    out.append(Sample("nns.pool.frame_recycled", dict(base),
                      FRAME_POOL.recycled, "counter"))
    out.append(Sample("nns.pool.device_allocated", dict(base),
                      DEVICE_POOL.allocated, "counter"))
    out.append(Sample("nns.pool.device_reused", dict(base),
                      DEVICE_POOL.reused, "counter"))
    out.append(Sample("nns.pool.device_reuse_rate", dict(base),
                      DEVICE_POOL.reuse_rate, "gauge"))
    out.append(Sample("nns.pool.rings_evicted", dict(base),
                      DEVICE_POOL.rings_evicted, "counter"))
    out.append(Sample("nns.pool.trims", dict(base),
                      DEVICE_POOL.trims, "counter"))
    return out
