"""JAX/Optax trainer delegate — the in-framework analog of NNTrainer.

Model config (the ``model-config`` property, JSON file or inline JSON)::

    {"arch": "mnist_cnn", "arch_props": {"dtype": "float32"},
     "optimizer": "adam", "learning_rate": 1e-3, "batch_size": 32,
     "loss": "softmax_ce"}

Data protocol (≙ trainer ABI push_data, SURVEY §3.4): each incoming frame
carries ``num-inputs`` input tensors followed by ``num-labels`` label
tensors; every ``num-training-samples`` + ``num-validation-samples`` frames
form one epoch (train split first, then validation) — the exact contract of
the reference element (``gsttensor_trainer.c`` header: total expected =
(train+valid)×epochs).

The training loop runs on a dedicated thread; samples stream in through a
bounded queue (backpressure to the pipeline).  Each optimizer step is one
jitted donate-argnums XLA call over a micro-batch.

Crash safety (net-new vs the reference; the preemptible-TPU contract):

* **Step-grain durable checkpoints** — ``checkpoint-steps=N`` saves
  params + optimizer state every N optimizer steps (plus every epoch
  boundary) under ``checkpoint-path``, each committed by an atomic
  completion marker (core/checkpoint.py) carrying the **data cursor**:
  global step, epoch, position-in-epoch, stream position, and the last
  datarepo ``(epoch, sample_index)`` incorporated.  A torn save is never
  resumed.
* **Exact-step resume** — a restarted pipeline (``resume=true``) restores
  the newest durable checkpoint and fast-forwards the deterministic
  datarepo replay by the cursor's stream position: zero samples re-trained,
  zero lost, final params bit-identical to an uninterrupted run at
  checkpoint grain (the replay skip only engages for frames stamped with
  the datarepo ``epoch`` meta; direct-API feeds keep the legacy
  continue-from-epoch behavior).
* **Resumable pause** — :meth:`pause`/:meth:`unpause` gate the train loop
  between steps; a paused trainer stops consuming, the bounded queue
  backpressures the pipeline, and no sample is lost (the element couples
  this to the memory watermark so training never starves serving).
* **Fault sites** — ``trainer.step``, ``trainer.checkpoint`` (pre-save)
  and ``trainer.checkpoint.commit`` (the torn-save gap between the Orbax
  write and the marker) make every failure path chip-free testable.
"""

from __future__ import annotations

import json
import os
import queue
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.buffer import TensorFrame
from ..core.log import get_logger
from .base import (
    EVENT_EPOCH_COMPLETION,
    EVENT_TRAINING_COMPLETION,
    TrainerBackend,
    TrainerStatus,
    register_trainer,
)

log = get_logger("jax-trainer")


def _truthy(v) -> bool:
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "yes", "on")
    return bool(v)


def make_loss_fn(fn, loss_kind: str):
    """The one loss builder shared by the trainer's train/eval steps and
    the model_validator's held-out scorer (the gate must judge candidates
    by the same objective training optimizes).  Returns
    ``loss_fn(params, xs, ys) -> (loss, accuracy)``, jit-traceable."""
    import jax
    import jax.numpy as jnp

    def loss_fn(p, xs, ys):
        logits = fn(p, xs)[0]
        if loss_kind == "softmax_ce":
            labels = ys[0]
            # one-hot only when the trailing dim is the class dim;
            # (B,1) integer labels must NOT be argmax'd
            if labels.ndim == logits.ndim and labels.shape[-1] == logits.shape[-1]:
                labels = jnp.argmax(labels, axis=-1)
            labels = labels.reshape(-1).astype(jnp.int32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            ll = jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
            acc = jnp.mean(
                (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
            )
            return -jnp.mean(ll), acc
        if loss_kind == "mse":
            target = ys[0].astype(logits.dtype)
            return jnp.mean((logits - target) ** 2), jnp.zeros(())
        raise ValueError(f"unknown loss {loss_kind!r}")

    return loss_fn


class JaxTrainer(TrainerBackend):
    NAME = "jax"

    def __init__(self):
        super().__init__()
        self._cfg: Dict[str, Any] = {}
        self._props: Dict[str, Any] = {}
        self._q: "queue.Queue[Optional[TensorFrame]]" = queue.Queue(256)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._paused = threading.Event()
        self.params = None
        self._fn = None
        self.error: Optional[BaseException] = None
        # mesh (``mesh=`` grammar, PR-13) — set by _build when armed
        self._mesh = None
        self._mesh_axes: Dict[str, int] = {}
        self._batch_put = None  # device_put batches onto the dp axis
        # exact step/sample accounting (the element exports these as
        # nns.train.*; the chaos harness and the kill/resume truth table
        # pin them)
        self.steps = 0                # optimizer steps completed
        self.samples_trained = 0      # samples incorporated by train steps
        self.checkpoints = 0          # durable (marker-committed) saves
        self.resumes = 0              # restores from a durable checkpoint
        self.resumed_at = -1          # global step the last resume restored
        self.replay_skipped = 0       # already-trained frames skipped on resume
        self.gap_samples = 0          # frames dropped realigning a mid-stream restart
        self.trained_log: List[Tuple[int, int]] = []  # (epoch, sample_index) ledger

    # -- ABI ----------------------------------------------------------------
    def create(self, props: Dict[str, Any]) -> None:
        self._props = dict(props)
        cfg_text = props.get("model-config") or "{}"
        if os.path.isfile(cfg_text):
            with open(cfg_text) as f:
                self._cfg = json.load(f)
        else:
            self._cfg = json.loads(cfg_text)
        if "arch" not in self._cfg:
            raise ValueError("trainer model-config must name an 'arch'")

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._train_loop, name="jax-trainer", daemon=True
        )
        self._thread.start()

    def push_data(self, frame: TensorFrame) -> None:
        while not self._stop.is_set():
            if self._thread is not None and not self._thread.is_alive():
                return  # trainer died; don't spin (its error is surfaced)
            try:
                self._q.put(frame, timeout=0.2)
                return
            except queue.Full:
                continue

    def _put_sentinel(self) -> None:
        # never block: if the queue is full the consumer is gone — drain one
        try:
            self._q.put_nowait(None)
        except queue.Full:
            try:
                self._q.get_nowait()
                self._q.put_nowait(None)
            except (queue.Empty, queue.Full):
                pass

    def end_of_data(self) -> None:
        # block-put like push_data: the consumer is still alive here, and a
        # lossy put would drop a real sample from the final epoch
        while not self._stop.is_set():
            if self._thread is None or not self._thread.is_alive():
                self._put_sentinel()
                return
            try:
                self._q.put(None, timeout=0.2)
                return
            except queue.Full:
                continue

    def stop(self) -> None:
        self._stop.set()
        self._put_sentinel()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def thread_alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- resumable pause (starvation-free co-hosting) ------------------------
    def pause(self) -> None:
        """Stop taking train steps at the next step boundary.  The loop
        stops consuming, the bounded queue backpressures the pipeline:
        resumable, zero samples lost."""
        self._paused.set()

    def unpause(self) -> None:
        self._paused.clear()

    @property
    def paused(self) -> bool:
        return self._paused.is_set()

    # -- internals ----------------------------------------------------------
    def _build(self):
        import jax
        import optax

        from .. import models as zoo

        arch = self._cfg["arch"]
        arch_props = {k: str(v) for k, v in self._cfg.get("arch_props", {}).items()}
        fn, params, _, _ = zoo.build(arch, arch_props)
        load_path = self._props.get("model-load-path")
        if load_path:
            params = _load_params(load_path, params)
        # zoo params come back committed to host CPU (models/_init_util.py);
        # re-commit to the accelerator so training compiles there, and init
        # the optimizer as one compiled call (eager tree_map would dispatch
        # a tiny op per leaf through the device tunnel)
        mesh_spec = str(self._props.get("mesh") or "")
        if mesh_spec.strip() not in ("", "0", "off", "none"):
            params = self._arm_mesh(mesh_spec, params)
        else:
            params = jax.device_put(params, jax.devices()[0])
        lr = float(self._cfg.get("learning_rate", 1e-3))
        opt_name = self._cfg.get("optimizer", "adam")
        tx = {
            "adam": optax.adam,
            "adamw": optax.adamw,
            "sgd": optax.sgd,
        }[opt_name](lr)
        opt_state = jax.jit(tx.init)(params)

        loss_fn = make_loss_fn(fn, self._cfg.get("loss", "softmax_ce"))

        @jax.jit
        def eval_step(p, xs, ys):
            return loss_fn(p, xs, ys)

        def _step(p, opt, xs, ys):
            (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(p, xs, ys)
            updates, opt = tx.update(grads, opt, p)
            p = optax.apply_updates(p, updates)
            return p, opt, loss, acc

        train_step = jax.jit(_step, donate_argnums=(0, 1))
        return fn, params, opt_state, train_step, eval_step

    def _arm_mesh(self, spec: str, params):
        """Shard jitted train steps via the serving ``mesh=`` grammar
        (PR-13): params/opt_state replicated over the mesh, batches
        scattered on the ``dp`` axis.  Gradients psum implicitly through
        jit's partitioner — the training analog of the filter's sharded
        invoke."""
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from ..parallel.mesh import claim_devices, make_mesh, parse_mesh_spec

        axes = parse_mesh_spec(spec)
        devices = claim_devices(axes)
        mesh = make_mesh(axes, devices)
        self._mesh, self._mesh_axes = mesh, axes
        repl = NamedSharding(mesh, P())
        dp = int(mesh.shape.get("dp", 1))
        if dp > 1:
            batch_sh = NamedSharding(mesh, P("dp"))

            def put(a):
                # the final partial batch may not split across dp —
                # replicate it (one odd-shaped compile, exact math)
                sh = batch_sh if a.shape[0] % dp == 0 else repl
                return jax.device_put(a, sh)

            self._batch_put = put
        else:
            self._batch_put = lambda a: jax.device_put(a, repl)
        log.info("trainer mesh armed: %s over %d device(s)", spec, mesh.size)
        return jax.device_put(params, repl)

    def _batches(self, samples, batch_size: int):
        for i in range(0, len(samples), batch_size):
            chunk = samples[i : i + batch_size]
            xs = [np.stack([s[0][t] for s in chunk]) for t in range(len(chunk[0][0]))]
            ys = [np.stack([s[1][t] for s in chunk]) for t in range(len(chunk[0][1]))]
            yield xs, ys

    def _train_loop(self) -> None:
        try:
            self._fn, self.params, opt_state, train_step, eval_step = self._build()
            opt_state, cursor = self._maybe_resume(opt_state)
        except Exception as e:
            log.exception("trainer build failed")
            self.error = e  # surfaced by the element's watchdog sweep
            self.notify(EVENT_TRAINING_COMPLETION)
            return
        try:
            self._train_body(opt_state, train_step, eval_step, cursor)
        except Exception as e:
            log.exception("training failed")
            self.error = e
        self.notify(EVENT_TRAINING_COMPLETION)

    def _maybe_resume(self, opt_state):
        """Durable-checkpoint resume (preemptible-TPU recovery): restore
        params + optimizer state + the data cursor from the newest
        marker-committed checkpoint under ``checkpoint-path`` when
        ``resume=1``.  Torn saves are invisible (core/checkpoint.py)."""
        from ..core import checkpoint as ckpt

        path = self._props.get("checkpoint-path")
        if not (path and _truthy(self._props.get("resume", False))):
            return opt_state, None
        step = ckpt.latest_step(path)
        if step is None:
            log.info("resume requested but no checkpoint under %s", path)
            return opt_state, None
        state = ckpt.restore_state(
            path, step, {"params": self.params, "opt_state": opt_state}
        )
        self.params = state["params"]
        cursor = ckpt.load_meta(path, step).get("cursor")
        if cursor is None:
            # pre-cursor checkpoint id semantics: id == completed epochs
            cursor = {"unit": "epoch", "epoch": int(step), "epoch_pos": 0,
                      "stream_pos": 0, "step": 0}
        self.resumes += 1
        self.resumed_at = int(cursor.get("step", 0))
        self.steps = self.resumed_at
        log.info("resumed from %s step %d (cursor %s)", path, step, cursor)
        return state["opt_state"], cursor

    def _ckpt(self, opt_state, cursor: Dict[str, Any]) -> None:
        """One durable checkpoint: Orbax write, then the atomic
        completion marker carrying the data cursor.  The two fault sites
        bracket the torn-save gap."""
        from ..core import checkpoint as ckpt
        from ..core.resilience import FAULTS

        path = self._props.get("checkpoint-path")
        if not path:
            return
        cid = int(cursor["step"] if cursor["unit"] == "step"
                  else cursor["epoch"])
        if cid == getattr(self, "_last_ckpt_id", None):
            return  # epoch boundary coinciding with a step checkpoint
        FAULTS.check("trainer.checkpoint")
        ckpt.write_state(path, cid, {"params": self.params, "opt_state": opt_state})
        FAULTS.check("trainer.checkpoint.commit")
        ckpt.commit_state(path, cid, {"cursor": cursor})
        keep = int(self._props.get("checkpoint-keep", 3))
        ckpt.prune(path, keep)
        self.checkpoints += 1
        self._last_ckpt_id = cid
        log.info("checkpointed %s %d to %s", cursor["unit"], cid, path)

    def _train_body(self, opt_state, train_step, eval_step,
                    cursor: Optional[Dict[str, Any]] = None) -> None:
        from ..core.resilience import FAULTS

        n_in = int(self._props.get("num-inputs", 1))
        n_lab = int(self._props.get("num-labels", 1))
        n_train = int(self._props.get("num-training-samples", 0))
        n_valid = int(self._props.get("num-validation-samples", 0))
        epochs = int(self._props.get("epochs", 1))
        batch_size = int(self._cfg.get("batch_size", 32))
        ckpt_steps = int(self._props.get("checkpoint-steps", 0) or 0)
        ckpt_interval = int(self._props.get("checkpoint-interval", 1))
        per_epoch = n_train + n_valid
        midstream = _truthy(self._props.get("_midstream-restart", False))

        cursor = cursor or {}
        done_epochs = int(cursor.get("epoch", 0))
        gstep = int(cursor.get("step", 0))
        epoch_pos = int(cursor.get("epoch_pos", 0))
        stream_pos = int(cursor.get("stream_pos", 0))
        ep_losses = [float(x) for x in cursor.get("ep_losses", [])]
        ep_accs = [float(x) for x in cursor.get("ep_accs", [])]
        # resume fast-forward: the deterministic datarepo replay re-emits
        # every frame from sample 0; skip exactly the cursor's stream
        # position (only meta-stamped frames — a direct-API feed is the
        # caller resuming where IT left off, so nothing is skipped)
        skip_left = 0 if midstream else stream_pos
        # mid-stream backend restart: the live stream does NOT replay, and
        # frames between the checkpoint and the crash are gone — drop the
        # rest of the partial epoch (counted) and realign exactly at the
        # next epoch boundary the datarepo meta announces
        realign = midstream and per_epoch > 0
        realign_seen: Optional[int] = None

        train_buf: List[Tuple[List[np.ndarray], List[np.ndarray], Any]] = []
        valid_buf: List[Tuple[List[np.ndarray], List[np.ndarray], Any]] = []
        tail_buf: List[Tuple[List[np.ndarray], List[np.ndarray]]] = []

        def cursor_now(unit: str) -> Dict[str, Any]:
            c: Dict[str, Any] = {
                "unit": unit, "step": gstep, "epoch": done_epochs,
                "epoch_pos": epoch_pos, "stream_pos": stream_pos,
                "ep_losses": ep_losses, "ep_accs": ep_accs,
            }
            if self.trained_log:
                c["meta_epoch"], c["sample_index"] = self.trained_log[-1]
            return c

        def do_step(batch) -> None:
            nonlocal opt_state, gstep, stream_pos
            FAULTS.check("trainer.step")
            xs = [np.stack([s[0][t] for s in batch])
                  for t in range(len(batch[0][0]))]
            ys = [np.stack([s[1][t] for s in batch])
                  for t in range(len(batch[0][1]))]
            if self._batch_put is not None:
                xs = [self._batch_put(a) for a in xs]
                ys = [self._batch_put(a) for a in ys]
            self.params, opt_state, loss, acc = train_step(
                self.params, opt_state, xs, ys
            )
            gstep += 1
            stream_pos += len(batch)
            self.steps = gstep
            self.samples_trained += len(batch)
            for s in batch:
                if s[2] is not None:
                    self.trained_log.append(s[2])
            ep_losses.append(float(loss))
            ep_accs.append(float(acc))
            if ckpt_steps > 0 and gstep % ckpt_steps == 0:
                self._ckpt(opt_state, cursor_now("step"))

        def finish_epoch() -> None:
            nonlocal done_epochs, epoch_pos, stream_pos
            nonlocal ep_losses, ep_accs, valid_buf
            vlosses, vaccs = [], []
            for bx, by in self._batches(
                    [(s[0], s[1]) for s in valid_buf], batch_size
            ) if valid_buf else ():
                loss, acc = eval_step(self.params, bx, by)
                vlosses.append(float(loss))
                vaccs.append(float(acc))
            for s in valid_buf:
                if s[2] is not None:
                    self.trained_log.append(s[2])
            stream_pos += len(valid_buf)
            done_epochs += 1
            epoch_pos = 0
            valid_buf = []
            self.status = TrainerStatus(
                epoch_count=done_epochs,
                training_loss=float(np.mean(ep_losses)) if ep_losses else 0.0,
                training_accuracy=float(np.mean(ep_accs)) if ep_accs else 0.0,
                validation_loss=float(np.mean(vlosses)) if vlosses else 0.0,
                validation_accuracy=float(np.mean(vaccs)) if vaccs else 0.0,
            )
            ep_losses, ep_accs = [], []
            self.notify(EVENT_EPOCH_COMPLETION)
            if ckpt_steps > 0:
                self._ckpt(opt_state, cursor_now("step"))
            elif ckpt_interval > 0 and done_epochs % ckpt_interval == 0:
                self._ckpt(opt_state, cursor_now("epoch"))

        while not self._stop.is_set() and (epochs <= 0 or done_epochs < epochs):
            # resumable pause: between steps only — never mid-step, never
            # consuming (the bounded queue backpressures the pipeline)
            if self._paused.is_set():
                self._stop.wait(0.05)
                continue
            try:
                frame = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            if frame is None:
                break
            meta_ep = frame.meta.get("epoch") if frame.meta else None
            if skip_left > 0 and meta_ep is not None:
                skip_left -= 1
                self.replay_skipped += 1
                continue
            if realign and meta_ep is not None:
                if realign_seen is None:
                    realign_seen = int(meta_ep)
                if int(meta_ep) == realign_seen:
                    self.gap_samples += 1
                    continue
                realign = False  # fresh epoch boundary: exact from here
                epoch_pos = 0
                train_buf, valid_buf = [], []
                ep_losses, ep_accs = [], []
            elif realign and meta_ep is None:
                realign = False  # no meta: continue from the cursor as-is
            xs = [np.asarray(t) for t in frame.tensors[:n_in]]
            ys = [np.asarray(t) for t in frame.tensors[n_in : n_in + n_lab]]
            tag = (
                (int(meta_ep), int(frame.meta.get("sample_index", -1)))
                if meta_ep is not None else None
            )
            if not per_epoch:
                tail_buf.append((xs, ys))
                continue
            if epoch_pos < n_train:
                train_buf.append((xs, ys, tag))
                flush = (len(train_buf) >= batch_size
                         or epoch_pos == n_train - 1)
            else:
                valid_buf.append((xs, ys, tag))
                flush = False
            epoch_pos += 1
            if flush:
                batch, train_buf = train_buf, []
                do_step(batch)
            if epoch_pos >= per_epoch:
                finish_epoch()

        if (train_buf or valid_buf) and not self._stop.is_set():
            log.warning(
                "dropping %d leftover samples (incomplete epoch of %d)",
                len(train_buf) + len(valid_buf), per_epoch,
            )
        if tail_buf and not self._stop.is_set():
            # num-training-samples unset: the whole stream is the dataset;
            # honor epochs= by re-iterating it instead of silently saving
            # the untrained init (done_epochs already counts resumed ones)
            while done_epochs < max(1, epochs) and not self._stop.is_set():
                for bx, by in self._batches(tail_buf, batch_size):
                    FAULTS.check("trainer.step")
                    if self._batch_put is not None:
                        bx = [self._batch_put(a) for a in bx]
                        by = [self._batch_put(a) for a in by]
                    self.params, opt_state, loss, acc = train_step(
                        self.params, opt_state, bx, by
                    )
                    gstep += 1
                    self.steps = gstep
                    self.samples_trained += len(bx[0])
                    ep_losses.append(float(loss))
                    ep_accs.append(float(acc))
                done_epochs += 1
                self.status = TrainerStatus(
                    epoch_count=done_epochs,
                    training_loss=float(np.mean(ep_losses)) if ep_losses else 0.0,
                    training_accuracy=float(np.mean(ep_accs)) if ep_accs else 0.0,
                )
                ep_losses, ep_accs = [], []
                self.notify(EVENT_EPOCH_COMPLETION)
                epoch_pos = 0
                if ckpt_steps > 0:
                    self._ckpt(opt_state, cursor_now("step"))
                elif ckpt_interval > 0 and done_epochs % ckpt_interval == 0:
                    self._ckpt(opt_state, cursor_now("epoch"))
        save_path = self._props.get("model-save-path")
        if save_path and self.params is not None:
            _save_params(save_path, self.params)
            log.info("model saved to %s", save_path)


def _save_params(path: str, params) -> None:
    if path.endswith(".msgpack"):
        from flax import serialization

        from ..core.checkpoint import atomic_write_bytes

        # temp-sibling + fsync + os.replace (the datareposink pattern):
        # a crash mid-save leaves the previous complete model, never a
        # torn file a co-hosted serving filter could hot-load
        atomic_write_bytes(path, serialization.to_bytes(params))
    else:
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.abspath(path), params, force=True)
        ckptr.wait_until_finished()


def _load_params(path: str, template):
    if path.endswith(".msgpack"):
        from flax import serialization

        with open(path, "rb") as f:
            return serialization.from_bytes(template, f.read())
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(os.path.abspath(path), template)


def mnist_epoch_benchmark(
    dtype: str = "bfloat16",
    n_train: int = 2048,
    n_valid: int = 256,
    epochs: int = 3,
    tmp_dir: str = "/tmp/nns_mnist_bench",
    timeout_s: float = 900.0,
) -> Tuple[float, float]:
    """BASELINE.md tracked row: tensor_trainer MNIST CNN epoch time.

    Runs the reference's canonical in-pipeline training config
    (datareposrc -> tensor_trainer, SURVEY §3.4) on a synthetic
    MNIST-shaped dataset and returns (steady-state seconds/epoch, final
    training accuracy).  Epoch 1 includes the XLA compile, so timing uses
    the epochs after it (stats-frame arrival deltas at the sink).
    """
    import json as _json
    import shutil
    import time

    from ..pipeline import parse_pipeline

    shutil.rmtree(tmp_dir, ignore_errors=True)
    os.makedirs(tmp_dir, exist_ok=True)
    data_path = os.path.join(tmp_dir, "data.bin")
    json_path = os.path.join(tmp_dir, "data.json")

    # synthetic learnable task: class = brightest of 10 row-bands
    rng = np.random.default_rng(0)
    wpipe = parse_pipeline(
        f"appsrc name=src ! datareposink location={data_path} json={json_path}"
    )
    wpipe.start()
    n = n_train + n_valid
    for i in range(n):
        label = i % 10
        img = rng.normal(0.2, 0.05, (28, 28, 1)).astype(np.float32)
        img[label * 2 : label * 2 + 3, :, :] += 0.8
        wpipe["src"].push([img, np.int64([label])])
    wpipe["src"].end_of_stream()
    wpipe.wait(timeout=60)
    wpipe.stop()

    cfg = {
        "arch": "mnist_cnn",
        "arch_props": {"dtype": dtype, "classes": "10"},
        "optimizer": "adam",
        "learning_rate": 3e-3,
        "batch_size": 256,
    }
    cfg_path = os.path.join(tmp_dir, "cfg.json")
    with open(cfg_path, "w") as f:
        _json.dump(cfg, f)

    pipe = parse_pipeline(
        f"datareposrc location={data_path} json={json_path} epochs={epochs} ! "
        f"tensor_trainer name=t framework=jax model-config={cfg_path} "
        f"num-inputs=1 num-labels=1 num-training-samples={n_train} "
        f"num-validation-samples={n_valid} epochs={epochs} ! "
        "tensor_sink name=out"
    )
    arrivals = []
    pipe.start()
    pipe["out"].connect_new_data(lambda f: arrivals.append(time.perf_counter()))
    pipe.wait(timeout=timeout_s)
    stats = [f.tensors[0] for f in pipe["out"].frames]
    pipe.stop()

    if len(arrivals) < 2:
        raise RuntimeError(
            f"expected >=2 epoch stats frames, got {len(arrivals)}"
        )
    deltas = [b - a for a, b in zip(arrivals[1:], arrivals[2:])] or [
        arrivals[1] - arrivals[0]
    ]
    return float(np.mean(deltas)), float(stats[-1][2])


register_trainer(JaxTrainer)
