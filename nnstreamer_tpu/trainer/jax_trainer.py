"""JAX/Optax trainer delegate — the in-framework analog of NNTrainer.

Model config (the ``model-config`` property, JSON file or inline JSON)::

    {"arch": "mnist_cnn", "arch_props": {"dtype": "float32"},
     "optimizer": "adam", "learning_rate": 1e-3, "batch_size": 32,
     "loss": "softmax_ce"}

Data protocol (≙ trainer ABI push_data, SURVEY §3.4): each incoming frame
carries ``num-inputs`` input tensors followed by ``num-labels`` label
tensors; every ``num-training-samples`` + ``num-validation-samples`` frames
form one epoch (train split first, then validation) — the exact contract of
the reference element (``gsttensor_trainer.c`` header: total expected =
(train+valid)×epochs).

The training loop runs on a dedicated thread; samples stream in through a
bounded queue (backpressure to the pipeline).  Each optimizer step is one
jitted donate-argnums XLA call over a micro-batch.
"""

from __future__ import annotations

import json
import os
import queue
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.buffer import TensorFrame
from ..core.log import get_logger
from .base import (
    EVENT_EPOCH_COMPLETION,
    EVENT_TRAINING_COMPLETION,
    TrainerBackend,
    TrainerStatus,
    register_trainer,
)

log = get_logger("jax-trainer")


class JaxTrainer(TrainerBackend):
    NAME = "jax"

    def __init__(self):
        super().__init__()
        self._cfg: Dict[str, Any] = {}
        self._props: Dict[str, Any] = {}
        self._q: "queue.Queue[Optional[TensorFrame]]" = queue.Queue(256)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.params = None
        self._fn = None
        self.error: Optional[BaseException] = None

    # -- ABI ----------------------------------------------------------------
    def create(self, props: Dict[str, Any]) -> None:
        self._props = dict(props)
        cfg_text = props.get("model-config") or "{}"
        if os.path.isfile(cfg_text):
            with open(cfg_text) as f:
                self._cfg = json.load(f)
        else:
            self._cfg = json.loads(cfg_text)
        if "arch" not in self._cfg:
            raise ValueError("trainer model-config must name an 'arch'")

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._train_loop, name="jax-trainer", daemon=True
        )
        self._thread.start()

    def push_data(self, frame: TensorFrame) -> None:
        while not self._stop.is_set():
            if self._thread is not None and not self._thread.is_alive():
                return  # trainer died; don't spin (its error is surfaced)
            try:
                self._q.put(frame, timeout=0.2)
                return
            except queue.Full:
                continue

    def _put_sentinel(self) -> None:
        # never block: if the queue is full the consumer is gone — drain one
        try:
            self._q.put_nowait(None)
        except queue.Full:
            try:
                self._q.get_nowait()
                self._q.put_nowait(None)
            except (queue.Empty, queue.Full):
                pass

    def end_of_data(self) -> None:
        # block-put like push_data: the consumer is still alive here, and a
        # lossy put would drop a real sample from the final epoch
        while not self._stop.is_set():
            if self._thread is None or not self._thread.is_alive():
                self._put_sentinel()
                return
            try:
                self._q.put(None, timeout=0.2)
                return
            except queue.Full:
                continue

    def stop(self) -> None:
        self._stop.set()
        self._put_sentinel()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    # -- internals ----------------------------------------------------------
    def _build(self):
        import jax
        import jax.numpy as jnp
        import optax

        from .. import models as zoo

        arch = self._cfg["arch"]
        arch_props = {k: str(v) for k, v in self._cfg.get("arch_props", {}).items()}
        fn, params, _, _ = zoo.build(arch, arch_props)
        load_path = self._props.get("model-load-path")
        if load_path:
            params = _load_params(load_path, params)
        # zoo params come back committed to host CPU (models/_init_util.py);
        # re-commit to the accelerator so training compiles there, and init
        # the optimizer as one compiled call (eager tree_map would dispatch
        # a tiny op per leaf through the device tunnel)
        params = jax.device_put(params, jax.devices()[0])
        lr = float(self._cfg.get("learning_rate", 1e-3))
        opt_name = self._cfg.get("optimizer", "adam")
        tx = {
            "adam": optax.adam,
            "adamw": optax.adamw,
            "sgd": optax.sgd,
        }[opt_name](lr)
        opt_state = jax.jit(tx.init)(params)

        loss_kind = self._cfg.get("loss", "softmax_ce")

        def loss_fn(p, xs, ys):
            logits = fn(p, xs)[0]
            if loss_kind == "softmax_ce":
                labels = ys[0]
                # one-hot only when the trailing dim is the class dim;
                # (B,1) integer labels must NOT be argmax'd
                if labels.ndim == logits.ndim and labels.shape[-1] == logits.shape[-1]:
                    labels = jnp.argmax(labels, axis=-1)
                labels = labels.reshape(-1).astype(jnp.int32)
                logp = jax.nn.log_softmax(logits, axis=-1)
                ll = jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
                acc = jnp.mean(
                    (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
                )
                return -jnp.mean(ll), acc
            if loss_kind == "mse":
                target = ys[0].astype(logits.dtype)
                return jnp.mean((logits - target) ** 2), jnp.zeros(())
            raise ValueError(f"unknown loss {loss_kind!r}")

        @jax.jit
        def eval_step(p, xs, ys):
            return loss_fn(p, xs, ys)

        def _step(p, opt, xs, ys):
            (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(p, xs, ys)
            updates, opt = tx.update(grads, opt, p)
            p = optax.apply_updates(p, updates)
            return p, opt, loss, acc

        train_step = jax.jit(_step, donate_argnums=(0, 1))
        return fn, params, opt_state, train_step, eval_step

    def _batches(self, samples: List[Tuple[List[np.ndarray], List[np.ndarray]]],
                 batch_size: int):
        for i in range(0, len(samples), batch_size):
            chunk = samples[i : i + batch_size]
            xs = [np.stack([s[0][t] for s in chunk]) for t in range(len(chunk[0][0]))]
            ys = [np.stack([s[1][t] for s in chunk]) for t in range(len(chunk[0][1]))]
            yield xs, ys

    def _train_loop(self) -> None:
        try:
            self._fn, self.params, opt_state, train_step, eval_step = self._build()
            opt_state, start_epoch = self._maybe_resume(opt_state)
        except Exception as e:
            log.exception("trainer build failed")
            self.error = e  # surfaced as a pipeline error by the element
            self.notify(EVENT_TRAINING_COMPLETION)
            return
        try:
            self._train_body(opt_state, train_step, eval_step, start_epoch)
        except Exception as e:
            log.exception("training failed")
            self.error = e
        self.notify(EVENT_TRAINING_COMPLETION)

    def _maybe_resume(self, opt_state):
        """Periodic-checkpoint resume (preemptible-TPU recovery): restore
        params + optimizer state + epoch from the newest checkpoint under
        ``checkpoint-path`` when ``resume=1``."""
        from ..core import checkpoint as ckpt

        path = self._props.get("checkpoint-path")
        resume = self._props.get("resume", False)
        if isinstance(resume, str):  # direct-API callers; element props are bool
            resume = resume.strip().lower() in ("1", "true", "yes", "on")
        if not (path and resume):
            return opt_state, 0
        step = ckpt.latest_step(path)
        if step is None:
            log.info("resume requested but no checkpoint under %s", path)
            return opt_state, 0
        state = ckpt.restore_state(
            path, step, {"params": self.params, "opt_state": opt_state}
        )
        self.params = state["params"]
        log.info("resumed from %s step %d", path, step)
        return state["opt_state"], step

    def _checkpoint(self, opt_state, epoch: int) -> None:
        from ..core import checkpoint as ckpt

        path = self._props.get("checkpoint-path")
        if not path:
            return
        interval = int(self._props.get("checkpoint-interval", 1))
        if interval <= 0 or epoch % interval:
            return
        ckpt.save_state(
            path, epoch, {"params": self.params, "opt_state": opt_state}
        )
        keep = int(self._props.get("checkpoint-keep", 3))
        ckpt.prune(path, keep)
        log.info("checkpointed epoch %d to %s", epoch, path)

    def _train_body(self, opt_state, train_step, eval_step,
                    start_epoch: int = 0) -> None:
        n_in = int(self._props.get("num-inputs", 1))
        n_lab = int(self._props.get("num-labels", 1))
        n_train = int(self._props.get("num-training-samples", 0))
        n_valid = int(self._props.get("num-validation-samples", 0))
        epochs = int(self._props.get("epochs", 1))
        batch_size = int(self._cfg.get("batch_size", 32))
        per_epoch = n_train + n_valid

        epoch_samples: List[Tuple[List[np.ndarray], List[np.ndarray]]] = []
        done_epochs = start_epoch

        def run_epoch(train, valid):
            nonlocal opt_state, done_epochs
            losses, accs = [], []
            for bx, by in self._batches(train, batch_size):
                self.params, opt_state, loss, acc = train_step(
                    self.params, opt_state, bx, by
                )
                losses.append(float(loss))
                accs.append(float(acc))
            vlosses, vaccs = [], []
            for bx, by in self._batches(valid, batch_size) if valid else ():
                loss, acc = eval_step(self.params, bx, by)
                vlosses.append(float(loss))
                vaccs.append(float(acc))
            done_epochs += 1
            self.status = TrainerStatus(
                epoch_count=done_epochs,
                training_loss=float(np.mean(losses)) if losses else 0.0,
                training_accuracy=float(np.mean(accs)) if accs else 0.0,
                validation_loss=float(np.mean(vlosses)) if vlosses else 0.0,
                validation_accuracy=float(np.mean(vaccs)) if vaccs else 0.0,
            )
            self.notify(EVENT_EPOCH_COMPLETION)
            self._checkpoint(opt_state, done_epochs)

        while not self._stop.is_set() and (epochs <= 0 or done_epochs < epochs):
            try:
                frame = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            if frame is None:
                break
            xs = [np.asarray(t) for t in frame.tensors[:n_in]]
            ys = [np.asarray(t) for t in frame.tensors[n_in : n_in + n_lab]]
            epoch_samples.append((xs, ys))
            if per_epoch and len(epoch_samples) >= per_epoch:
                run_epoch(epoch_samples[:n_train], epoch_samples[n_train:per_epoch])
                epoch_samples = []
        if epoch_samples and not self._stop.is_set():
            if per_epoch:
                log.warning(
                    "dropping %d leftover samples (incomplete epoch of %d)",
                    len(epoch_samples), per_epoch,
                )
            else:
                # num-training-samples unset: the whole stream is the dataset;
                # honor epochs= by re-iterating it instead of silently saving
                # the untrained init (done_epochs already counts resumed ones)
                while done_epochs < max(1, epochs) and not self._stop.is_set():
                    run_epoch(epoch_samples, [])
        save_path = self._props.get("model-save-path")
        if save_path and self.params is not None:
            _save_params(save_path, self.params)
            log.info("model saved to %s", save_path)


def _save_params(path: str, params) -> None:
    if path.endswith(".msgpack"):
        from flax import serialization

        with open(path, "wb") as f:
            f.write(serialization.to_bytes(params))
    else:
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.abspath(path), params, force=True)
        ckptr.wait_until_finished()


def _load_params(path: str, template):
    if path.endswith(".msgpack"):
        from flax import serialization

        with open(path, "rb") as f:
            return serialization.from_bytes(template, f.read())
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(os.path.abspath(path), template)


def mnist_epoch_benchmark(
    dtype: str = "bfloat16",
    n_train: int = 2048,
    n_valid: int = 256,
    epochs: int = 3,
    tmp_dir: str = "/tmp/nns_mnist_bench",
    timeout_s: float = 900.0,
) -> Tuple[float, float]:
    """BASELINE.md tracked row: tensor_trainer MNIST CNN epoch time.

    Runs the reference's canonical in-pipeline training config
    (datareposrc -> tensor_trainer, SURVEY §3.4) on a synthetic
    MNIST-shaped dataset and returns (steady-state seconds/epoch, final
    training accuracy).  Epoch 1 includes the XLA compile, so timing uses
    the epochs after it (stats-frame arrival deltas at the sink).
    """
    import json as _json
    import shutil
    import time

    from ..pipeline import parse_pipeline

    shutil.rmtree(tmp_dir, ignore_errors=True)
    os.makedirs(tmp_dir, exist_ok=True)
    data_path = os.path.join(tmp_dir, "data.bin")
    json_path = os.path.join(tmp_dir, "data.json")

    # synthetic learnable task: class = brightest of 10 row-bands
    rng = np.random.default_rng(0)
    wpipe = parse_pipeline(
        f"appsrc name=src ! datareposink location={data_path} json={json_path}"
    )
    wpipe.start()
    n = n_train + n_valid
    for i in range(n):
        label = i % 10
        img = rng.normal(0.2, 0.05, (28, 28, 1)).astype(np.float32)
        img[label * 2 : label * 2 + 3, :, :] += 0.8
        wpipe["src"].push([img, np.int64([label])])
    wpipe["src"].end_of_stream()
    wpipe.wait(timeout=60)
    wpipe.stop()

    cfg = {
        "arch": "mnist_cnn",
        "arch_props": {"dtype": dtype, "classes": "10"},
        "optimizer": "adam",
        "learning_rate": 3e-3,
        "batch_size": 256,
    }
    cfg_path = os.path.join(tmp_dir, "cfg.json")
    with open(cfg_path, "w") as f:
        _json.dump(cfg, f)

    pipe = parse_pipeline(
        f"datareposrc location={data_path} json={json_path} epochs={epochs} ! "
        f"tensor_trainer name=t framework=jax model-config={cfg_path} "
        f"num-inputs=1 num-labels=1 num-training-samples={n_train} "
        f"num-validation-samples={n_valid} epochs={epochs} ! "
        "tensor_sink name=out"
    )
    arrivals = []
    pipe.start()
    pipe["out"].connect_new_data(lambda f: arrivals.append(time.perf_counter()))
    pipe.wait(timeout=timeout_s)
    stats = [f.tensors[0] for f in pipe["out"].frames]
    pipe.stop()

    if len(arrivals) < 2:
        raise RuntimeError(
            f"expected >=2 epoch stats frames, got {len(arrivals)}"
        )
    deltas = [b - a for a, b in zip(arrivals[1:], arrivals[2:])] or [
        arrivals[1] - arrivals[0]
    ]
    return float(np.mean(deltas)), float(stats[-1][2])


register_trainer(JaxTrainer)
