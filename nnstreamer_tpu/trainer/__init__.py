"""Training subsystem: trainer backend ABI + the JAX/Optax delegate.

Reference analog: ``GstTensorTrainerFramework`` ABI
(``nnstreamer_plugin_api_trainer.h:95-196``) whose reference implementation
is NNTrainer (out-of-repo); here the delegate is JAX/Optax.
"""

from .base import TrainerBackend, TrainerStatus, find_trainer, register_trainer  # noqa: F401
from . import jax_trainer  # noqa: F401 — registers "jax"
