"""Trainer backend ABI.

Reference: ``GstTensorTrainerFramework`` {create, destroy, start, stop,
push_data, getStatus, getFrameworkInfo} + event notifier
(EPOCH_COMPLETION, TRAINING_COMPLETION) —
``nnstreamer_plugin_api_trainer.h:95-196``; status fields epoch_count and
training/validation loss/accuracy (:31-48).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..core import registry
from ..core.buffer import TensorFrame

EVENT_EPOCH_COMPLETION = "epoch-completion"
EVENT_TRAINING_COMPLETION = "training-completion"


@dataclass
class TrainerStatus:
    """≙ GstTensorTrainerStats."""

    epoch_count: int = 0
    training_loss: float = 0.0
    training_accuracy: float = 0.0
    validation_loss: float = 0.0
    validation_accuracy: float = 0.0

    def as_dict(self) -> Dict[str, float]:
        return {
            "epoch": self.epoch_count,
            "training_loss": self.training_loss,
            "training_accuracy": self.training_accuracy,
            "validation_loss": self.validation_loss,
            "validation_accuracy": self.validation_accuracy,
        }


class TrainerBackend:
    """Lifecycle: create(props) -> start() -> push_data(frame)* ->
    events fire -> stop().  Training runs on the backend's own thread
    (≙ "subplugin spawns training thread", SURVEY §3.4)."""

    NAME = "base"

    def __init__(self):
        self.status = TrainerStatus()
        self._listeners: List[Callable[[str, TrainerStatus], None]] = []

    def add_listener(self, cb: Callable[[str, TrainerStatus], None]) -> None:
        self._listeners.append(cb)

    def notify(self, event: str) -> None:
        """≙ nnstreamer_trainer_notify_event."""
        for cb in list(self._listeners):
            cb(event, self.status)

    # -- ABI ----------------------------------------------------------------
    def create(self, props: Dict[str, Any]) -> None:
        raise NotImplementedError

    def start(self) -> None:
        raise NotImplementedError

    def push_data(self, frame: TensorFrame) -> None:
        raise NotImplementedError

    def stop(self) -> None:
        pass

    def destroy(self) -> None:
        pass

    def get_status(self) -> TrainerStatus:
        return self.status


def register_trainer(cls) -> None:
    registry.register(registry.KIND_TRAINER, cls.NAME, cls)


def find_trainer(name: str):
    return registry.get(registry.KIND_TRAINER, name)
