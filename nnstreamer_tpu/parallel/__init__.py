"""Parallelism layer: meshes, shardings, ring attention, collectives.

SURVEY §2.3/§5.7/§5.8: the reference's distribution is among-device stream
transport (nnstreamer-edge) with no intra-model sharding; the TPU build adds
mesh-based dp/fsdp/tp/sp parallelism as a first-class subsystem.
"""

from .mesh import DP, EP, FSDP, PP, SP, TP, default_mesh, make_mesh, mesh_axis_size, single_device_mesh  # noqa: F401
from .ring_attention import reference_attention, ring_attention  # noqa: F401
from .ulysses import sequence_attention, ulysses_attention  # noqa: F401
from .sharding import batch_sharding, replicated, shard_params, spec_for_path, transformer_rules  # noqa: F401
from . import multihost  # noqa: F401
