"""Device-mesh construction helpers.

The reference scales *among devices* with nnstreamer-edge transports
(SURVEY §2.3); intra-model sharding does not exist there (§2.3 "NOT
present").  The TPU build's answer is a first-class `jax.sharding.Mesh`
layer: every parallel subsystem (data/tensor/sequence parallel filters,
ring attention, the trainer) takes a mesh + axis names.

Axis vocabulary (the scaling-book convention):
  * ``dp`` — data parallel (batch split; gradient psum)
  * ``fsdp`` — fully-sharded data parallel (params sharded over dp too)
  * ``tp`` — tensor parallel (heads / hidden split; activation collectives)
  * ``sp`` — sequence/context parallel (ring attention over this axis)
  * ``pp`` — pipeline stages  * ``ep`` — expert parallel
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

DP, FSDP, TP, SP, PP, EP = "dp", "fsdp", "tp", "sp", "pp", "ep"

#: the axis vocabulary serving configs may name (typo guard for the
#: ``mesh=`` element-prop grammar; make_mesh itself accepts any names)
KNOWN_AXES = (DP, FSDP, TP, SP, PP, EP)


def parse_mesh_spec(text: str) -> Dict[str, int]:
    """Parse the serving-config mesh grammar: ``"tp:4"`` /
    ``"dp:2,tp:2"`` / ``"dp:-1"`` (-1 = remaining devices, at most one
    axis) into ``{axis: size}``.  Empty/``"0"``/``"off"`` -> ``{}``
    (unsharded).  The one grammar shared by the tensor_filter /
    tensor_generator ``mesh=`` props, the jax-xla backend, and bench's
    ``BENCH_MESH`` axis — config surfaces cannot drift."""
    text = (text or "").strip()
    if text in ("", "0", "off", "none"):
        return {}
    axes: Dict[str, int] = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, size = part.partition(":")
        name = name.strip().lower()
        if not sep:
            raise ValueError(
                f"mesh spec {text!r}: expected axis:size, got {part!r}")
        if name not in KNOWN_AXES:
            raise ValueError(
                f"mesh spec {text!r}: unknown axis {name!r} "
                f"(want one of {', '.join(KNOWN_AXES)})")
        if name in axes:
            raise ValueError(f"mesh spec {text!r}: duplicate axis {name!r}")
        try:
            n = int(size)
        except ValueError:
            raise ValueError(
                f"mesh spec {text!r}: axis {name} size {size!r} is not "
                "an integer") from None
        if n == 0 or n < -1:
            raise ValueError(
                f"mesh spec {text!r}: axis {name} size must be >= 1 "
                "(or -1 = remaining devices)")
        axes[name] = n
    if sum(1 for v in axes.values() if v == -1) > 1:
        raise ValueError(f"mesh spec {text!r}: at most one axis may be -1")
    return axes


def claim_devices(axes: Dict[str, int], devices: Optional[Sequence] = None,
                  exclude: Sequence[int] = ()):
    """THE device-claiming rule for a parsed serving mesh spec (shared
    by the jax-xla backend and the slotted generator): a ``-1`` wildcard
    claims every device, explicit sizes claim a sub-mesh of the first
    N.  ``exclude`` removes device ORDINALS from the claimable pool
    first — the degraded re-shard path claims the survivors of a lost
    mesh member this way, so a rebuilt backend can never land back on
    the dead chip."""
    import math

    import jax

    devices = list(devices if devices is not None else jax.devices())
    if exclude:
        dead = {int(i) for i in exclude}
        devices = [d for d in devices if int(d.id) not in dead]
    if any(v == -1 for v in axes.values()):
        return devices
    return devices[: math.prod(axes.values())]


def shrink_axes(axes: Dict[str, int], n_avail: int) -> Dict[str, int]:
    """THE degraded-mesh shrink ladder: the largest mesh config that
    fits ``n_avail`` surviving devices, derived from the serving mesh
    ``axes``.  Data parallelism gives way first (``dp:2,tp:2`` on 3
    survivors -> ``dp:1,tp:2`` — dp only changes batch scatter, never
    the math); when even the non-dp product no longer fits, ``tp``
    halves down pow2-style (params re-shard by the same rules); an
    empty dict means "serve unsharded on one survivor".  Shared by the
    jax-xla filter backend and the slotted generator so both re-shard
    identically."""
    if n_avail <= 1:
        return {}
    out = {k: int(v) for k, v in axes.items() if k != DP}
    other = math.prod(out.values()) if out else 1
    if other <= n_avail:
        if DP in axes:
            out[DP] = n_avail // other
        return out
    # non-dp axes alone no longer fit: halve tp until they do
    tp = out.get(TP, 1)
    rest = other // max(1, tp)
    while tp > 1 and rest * tp > n_avail:
        tp //= 2
    if rest * max(1, tp) > n_avail:
        return {}
    if TP in out:
        if tp > 1:
            out[TP] = tp
        else:
            out.pop(TP)
    return out


def remesh_after_loss(current_ids: Sequence[int], axes: Dict[str, int],
                      lost_ids: Sequence[int] = (), probe=None):
    """THE survivors/shrink computation after a device loss, shared by
    the jax-xla backend and the slotted generator so both re-shard
    identically.  Identify the dead members — the runtime's reported
    ordinals when it names them, else ``probe(current_ids)`` (a
    per-device liveness probe; real XLA status strings usually do NOT
    carry the ordinal), else conservatively the LAST mesh member — and
    shrink ``axes`` to the survivors via :func:`shrink_axes`.

    Returns ``(dead_ids, new_axes, spec)`` with ``spec`` the
    :func:`mesh_spec_str` string of ``new_axes`` (``""`` = rebuild
    unsharded).  The probe distinguishes CANNOT-PROBE (``None`` —
    enumeration itself failed; fall back to the conservative
    last-member guess) from ALL-ALIVE (``()`` — every member answered,
    the loss did not reproduce): in the latter case ``dead_ids`` comes
    back EMPTY with ``axes`` unchanged, and callers must escalate to
    supervision (a plain retry may cure a transient) instead of
    condemning a healthy chip.  Whenever ``dead_ids`` is non-empty,
    every rebuild path EXCLUDES them from its device claim, so a
    replacement backend can never land back on the chip that just
    died."""
    current = [int(i) for i in current_ids]
    dead = {int(i) for i in (lost_ids or ())}
    if not dead:
        probed = probe(current) if probe is not None else None
        if probed is None:
            # no probe / probe unavailable: conservative last-member guess
            dead = {current[-1]}
        else:
            dead = {int(i) for i in probed}
    if not dead:
        # every member answered the probe: nothing provably dead,
        # nothing to shrink — the caller escalates to supervision
        return (), dict(axes), mesh_spec_str(axes)
    survivors = [i for i in current if i not in dead]
    new_axes = shrink_axes(axes, len(survivors))
    spec = mesh_spec_str(new_axes) if new_axes else ""
    return tuple(sorted(dead)), new_axes, spec


def mesh_spec_str(axes: Dict[str, int]) -> str:
    """Canonical string form of a parsed mesh spec (health/evidence
    labels): ``{}`` -> ``"0"``, else ``"dp:2,tp:2"`` in KNOWN_AXES
    order."""
    if not axes:
        return "0"
    known = [a for a in KNOWN_AXES if a in axes]
    rest = [a for a in axes if a not in KNOWN_AXES]
    return ",".join(f"{a}:{axes[a]}" for a in known + rest)


def mesh_health_info(mesh: Mesh, axes: Dict[str, int]) -> Dict[str, object]:
    """THE serving-mesh health/metrics dict (``mesh_devices``/``mesh_dp``/
    ``mesh_tp``/``mesh_axes``), shared by every element that serves on a
    mesh (jax-xla filter backend, slotted generator) so the exported
    ``nns.mesh.*`` surface cannot drift between them."""
    return {
        "mesh_devices": int(mesh.size),
        "mesh_dp": int(mesh.shape.get(DP, 1)),
        "mesh_tp": int(mesh.shape.get(TP, 1)),
        "mesh_axes": mesh_spec_str(axes),
    }


def make_mesh(
    axes: Dict[str, int], devices: Optional[Sequence] = None
) -> Mesh:
    """Build a Mesh with named axes, e.g. ``make_mesh({"dp": 2, "tp": 4})``.

    Axis sizes must multiply to the device count. ``-1`` for at most one
    axis means "whatever is left".
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    sizes = dict(axes)
    wild = [k for k, v in sizes.items() if v == -1]
    if len(wild) > 1:
        raise ValueError("at most one axis may be -1")
    fixed = math.prod(v for v in sizes.values() if v != -1)
    if wild:
        if n % fixed:
            raise ValueError(f"{n} devices not divisible by {fixed}")
        sizes[wild[0]] = n // fixed
    if math.prod(sizes.values()) != n:
        raise ValueError(
            f"mesh axes {sizes} multiply to {math.prod(sizes.values())}, "
            f"but {n} devices are available"
        )
    arr = np.asarray(devices).reshape(tuple(sizes.values()))
    return Mesh(arr, tuple(sizes.keys()))


def single_device_mesh(axis: str = DP) -> Mesh:
    return make_mesh({axis: 1}, devices=jax.devices()[:1])


def default_mesh(n: Optional[int] = None) -> Mesh:
    """A sensible mesh for n devices: prefer dp×tp close to square
    (dp outermost → gradient psum rides the slower links, tp innermost →
    activation collectives ride the fastest ICI neighbors)."""
    devices = jax.devices() if n is None else jax.devices()[:n]
    n = len(devices)
    tp = 1
    for cand in (8, 4, 2, 1):
        if n % cand == 0 and cand <= n:
            tp = cand
            break
    return make_mesh({DP: n // tp, TP: tp}, devices=devices)


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis] if axis in mesh.shape else 1
