"""Device-mesh construction helpers.

The reference scales *among devices* with nnstreamer-edge transports
(SURVEY §2.3); intra-model sharding does not exist there (§2.3 "NOT
present").  The TPU build's answer is a first-class `jax.sharding.Mesh`
layer: every parallel subsystem (data/tensor/sequence parallel filters,
ring attention, the trainer) takes a mesh + axis names.

Axis vocabulary (the scaling-book convention):
  * ``dp`` — data parallel (batch split; gradient psum)
  * ``fsdp`` — fully-sharded data parallel (params sharded over dp too)
  * ``tp`` — tensor parallel (heads / hidden split; activation collectives)
  * ``sp`` — sequence/context parallel (ring attention over this axis)
  * ``pp`` — pipeline stages  * ``ep`` — expert parallel
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

DP, FSDP, TP, SP, PP, EP = "dp", "fsdp", "tp", "sp", "pp", "ep"


def make_mesh(
    axes: Dict[str, int], devices: Optional[Sequence] = None
) -> Mesh:
    """Build a Mesh with named axes, e.g. ``make_mesh({"dp": 2, "tp": 4})``.

    Axis sizes must multiply to the device count. ``-1`` for at most one
    axis means "whatever is left".
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    sizes = dict(axes)
    wild = [k for k, v in sizes.items() if v == -1]
    if len(wild) > 1:
        raise ValueError("at most one axis may be -1")
    fixed = math.prod(v for v in sizes.values() if v != -1)
    if wild:
        if n % fixed:
            raise ValueError(f"{n} devices not divisible by {fixed}")
        sizes[wild[0]] = n // fixed
    if math.prod(sizes.values()) != n:
        raise ValueError(
            f"mesh axes {sizes} multiply to {math.prod(sizes.values())}, "
            f"but {n} devices are available"
        )
    arr = np.asarray(devices).reshape(tuple(sizes.values()))
    return Mesh(arr, tuple(sizes.keys()))


def single_device_mesh(axis: str = DP) -> Mesh:
    return make_mesh({axis: 1}, devices=jax.devices()[:1])


def default_mesh(n: Optional[int] = None) -> Mesh:
    """A sensible mesh for n devices: prefer dp×tp close to square
    (dp outermost → gradient psum rides the slower links, tp innermost →
    activation collectives ride the fastest ICI neighbors)."""
    devices = jax.devices() if n is None else jax.devices()[:n]
    n = len(devices)
    tp = 1
    for cand in (8, 4, 2, 1):
        if n % cand == 0 and cand <= n:
            tp = cand
            break
    return make_mesh({DP: n // tp, TP: tp}, devices=devices)


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis] if axis in mesh.shape else 1
