"""Ulysses-style sequence parallelism: all-to-all head redistribution.

The second long-context strategy next to :mod:`ring_attention` (SURVEY
§5.7 is net-new design; pattern reference: DeepSpeed-Ulysses, Jacobs et
al. 2023, PAPERS.md).  Where ring attention keeps the sequence sharded and
rotates K/V blocks around the ring, Ulysses re-shards with two
all-to-alls:

    in:  (B, T/sp, H,    D)   sequence-sharded
    a2a: (B, T,    H/sp, D)   head-sharded  -> plain local attention
    a2a: (B, T/sp, H,    D)   back to sequence-sharded

Exact attention, two collectives per layer (vs sp-1 ppermute hops for
ring), but heads must divide by the ``sp`` axis.  On TPU the all-to-all
rides ICI; pick Ulysses when H % sp == 0 and T_local x T attention fits
HBM, ring otherwise — :func:`sequence_attention` makes that choice.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .ring_attention import (
    reference_attention,
    ring_attention,
    shard_map_compat,
)


def _local_attention(q, k, v, causal: bool):
    """Plain exact attention on local (full-sequence, head-sharded) blocks."""
    D = q.shape[-1]
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / (D**0.5)
    if causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", (p / jnp.sum(p, axis=-1, keepdims=True)).astype(v.dtype),
        v, preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def _ulysses_local(q, k, v, *, seq_axis: str, causal: bool):
    # (B, T_local, H, D) -> all-to-all -> (B, T, H_local, D)
    def scatter_heads(x):
        return lax.all_to_all(
            x, seq_axis, split_axis=2, concat_axis=1, tiled=True
        )

    def gather_heads(x):
        return lax.all_to_all(
            x, seq_axis, split_axis=1, concat_axis=2, tiled=True
        )

    q, k, v = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    out = _local_attention(q, k, v, causal)
    return gather_heads(out)


def ulysses_attention(
    q, k, v, mesh: Mesh, *, seq_axis: str = "sp", batch_axes=("dp",),
    causal: bool = True,
):
    """Exact attention, sequence sharded on ``seq_axis``, via two
    all-to-alls.  q/k/v: (B, T, H, D) global; H must divide by
    mesh.shape[seq_axis]."""
    sp = mesh.shape[seq_axis]
    if q.shape[2] % sp:
        raise ValueError(
            f"ulysses needs heads ({q.shape[2]}) divisible by "
            f"{seq_axis}={sp}; use ring_attention instead"
        )
    batch_axes = tuple(a for a in batch_axes if a in mesh.shape)
    batch_spec = (
        None
        if not batch_axes
        else (batch_axes[0] if len(batch_axes) == 1 else batch_axes)
    )
    spec = P(batch_spec, seq_axis, None, None)
    fn = shard_map_compat(
        functools.partial(_ulysses_local, seq_axis=seq_axis, causal=causal),
        mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


def sequence_attention(q, k, v, mesh: Mesh, *, seq_axis: str = "sp",
                       batch_axes=("dp",), causal: bool = True,
                       strategy: str = "auto", use_flash: bool = False,
                       interpret: bool = False):
    """Pick a sequence-parallel attention strategy.

    ``auto``: Ulysses when the head count divides the ``sp`` axis (two
    ICI all-to-alls), else ring (sp-1 neighbor ppermutes).  Both exact.
    ``ring-flash`` (or ``use_flash=True`` with ring) runs each ring hop
    as one Pallas flash-attention kernel call.
    """
    sp = mesh.shape.get(seq_axis, 1)
    if strategy == "ring-flash":
        strategy, use_flash = "ring", True
    if strategy == "auto":
        # an explicit flash request pins the ring path: auto-resolving to
        # ulysses would silently drop it and re-materialize the full
        # (T x T_local) score matrix the caller opted out of
        strategy = (
            "ring" if use_flash
            else "ulysses" if sp > 1 and q.shape[2] % sp == 0
            else "ring"
        )
    elif strategy == "ulysses" and use_flash:
        raise ValueError(
            "use_flash applies to the ring path; pass strategy='ring' or "
            "'ring-flash' (ulysses has no per-hop kernel)"
        )
    if strategy == "ulysses":
        return ulysses_attention(
            q, k, v, mesh, seq_axis=seq_axis, batch_axes=batch_axes,
            causal=causal,
        )
    if strategy == "ring":
        return ring_attention(
            q, k, v, mesh, seq_axis=seq_axis, batch_axes=batch_axes,
            causal=causal, use_flash=use_flash, interpret=interpret,
        )
    raise ValueError(
        f"unknown strategy {strategy!r} (auto|ulysses|ring|ring-flash)"
    )


__all__ = [
    "ulysses_attention",
    "sequence_attention",
    "reference_attention",
]
