"""Manual-SPMD transformer training step over a 5-axis mesh (dp/tp/sp/pp/ep).

The reference has *no* intra-model sharding of any kind (SURVEY §2.3 "NOT
present": no TP/SP/EP/CP, no collectives); scale-out there is among-device
fan-out over nnstreamer-edge.  This module is the TPU build's net-new
answer: one training step written per-shard under ``shard_map`` so every
parallelism dimension is explicit and rides ICI collectives:

  * ``dp`` — batch sharded; gradient ``psum`` (inserted by autodiff of the
    loss ``psum``).
  * ``tp`` — Megatron-style: qkv/up kernels column-sharded, out/down
    kernels row-sharded, one ``psum`` after each row-sharded matmul.
  * ``sp`` — sequence sharded; exact attention via the ring-attention body
    (``ring_attention._ring_attn_local``): K/V blocks ``ppermute`` around
    the ring.
  * ``pp`` — layer stack split into ``pp`` stages (stage-stacked param
    leading axis sharded on pp); GPipe microbatch schedule: activations
    hop stage→stage via ``ppermute`` each tick, M+S-1 ticks total.
  * ``ep`` — Switch-style top-1 MoE FFN: tokens dispatched to experts with
    ``all_to_all`` over ep, expert matmuls (tp-sharded), combined back.

Everything is a single jitted program; XLA overlaps the ppermute/all_to_all
DMAs with the MXU matmuls.  Pattern references: GPipe (arXiv 1811.06965),
Megatron-LM (1909.08053), Switch Transformer (2101.03961), Ring Attention
(2310.01889) — all public; see PAPERS.md.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map

from .ring_attention import _ring_attn_local, shard_map_compat, vary_over


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    vocab: int = 128
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 2        # must divide by mesh pp
    d_ff: int = 128
    n_experts: int = 4       # 0 => dense FFN; must divide by mesh ep
    max_seq: int = 64
    n_microbatches: int = 2  # GPipe schedule depth (must divide local batch)
    capacity_factor: float = 2.0
    dtype: Any = jnp.float32


# ---------------------------------------------------------------------------
# Params: plain pytree, stage-stacked on the leading axis.
# ---------------------------------------------------------------------------
def init_params(cfg: PipelineConfig, seed: int = 0) -> Dict[str, Any]:
    ks = jax.random.split(jax.random.PRNGKey(seed), 10)
    L, D, F, V, E = cfg.n_layers, cfg.d_model, cfg.d_ff, cfg.vocab, cfg.n_experts
    dt = cfg.dtype
    s = lambda *sh: 1.0 / np.sqrt(sh[-2] if len(sh) >= 2 else sh[-1])
    p = {
        "embed": jax.random.normal(ks[0], (V, D), dt) * 0.02,
        "pos": jax.random.normal(ks[1], (cfg.max_seq, D), dt) * 0.02,
        "ln1": jnp.ones((L, D), dt),
        # (L, D, 3, D) so each of q/k/v column-shards independently on tp
        "qkv": jax.random.normal(ks[2], (L, D, 3, D), dt) * s(D, D),
        "out": jax.random.normal(ks[3], (L, D, D), dt) * s(D, D),
        "ln2": jnp.ones((L, D), dt),
        "ln_f": jnp.ones((D,), dt),
        "lm_head": jax.random.normal(ks[4], (D, V), dt) * s(D, V),
    }
    if E > 0:
        p["router"] = jax.random.normal(ks[5], (L, D, E), dt) * s(D, E)
        p["moe_up"] = jax.random.normal(ks[6], (L, E, D, F), dt) * s(D, F)
        p["moe_down"] = jax.random.normal(ks[7], (L, E, F, D), dt) * s(F, D)
    else:
        p["mlp_up"] = jax.random.normal(ks[6], (L, D, F), dt) * s(D, F)
        p["mlp_down"] = jax.random.normal(ks[7], (L, F, D), dt) * s(F, D)
    return p


def param_specs(cfg: PipelineConfig) -> Dict[str, P]:
    """PartitionSpec per leaf: stage axis on pp, Megatron dims on tp,
    experts on ep."""
    sp = {
        "embed": P(),
        "pos": P(),
        "ln1": P("pp", None),
        "qkv": P("pp", None, None, "tp"),
        "out": P("pp", "tp", None),
        "ln2": P("pp", None),
        "ln_f": P(),
        "lm_head": P("tp", None),
    }
    if cfg.n_experts > 0:
        sp["router"] = P("pp", None, None)
        sp["moe_up"] = P("pp", "ep", None, "tp")
        sp["moe_down"] = P("pp", "ep", "tp", None)
    else:
        sp["mlp_up"] = P("pp", None, "tp")
        sp["mlp_down"] = P("pp", "tp", None)
    return sp


AXES = ("dp", "pp", "sp", "tp", "ep")


def _ln(x, scale):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + 1e-6) * scale


def _moe_ffn(h, router, w_up, w_down, cfg: PipelineConfig, mesh: Mesh):
    """Per-shard Switch top-1 MoE.  h: (N, D) local tokens; experts sharded
    over ep (w_up: (E_loc, D, F_loc)); dispatch/combine via all_to_all."""
    ep = mesh.shape["ep"]
    N, D = h.shape
    E = cfg.n_experts
    C = max(1, int(cfg.capacity_factor * N / E))  # per-source-shard capacity

    glogits = h @ router                       # (N, E)
    gprobs = jax.nn.softmax(glogits.astype(jnp.float32), -1)
    eidx = jnp.argmax(gprobs, -1)              # (N,)
    gate = jnp.max(gprobs, -1)                 # (N,)
    onehot = jax.nn.one_hot(eidx, E, dtype=jnp.float32)          # (N, E)
    pos = jnp.cumsum(onehot, 0) * onehot                          # 1-based
    keep = (pos > 0) & (pos <= C)
    disp = onehot[..., None] * jax.nn.one_hot(
        (pos - 1).astype(jnp.int32), C, dtype=jnp.float32
    )                                                             # (N, E, C)
    disp = disp * keep.astype(jnp.float32)[..., None]
    xin = jnp.einsum("nec,nd->ecd", disp, h.astype(jnp.float32)).astype(h.dtype)

    if ep > 1:
        # (E, C, D) -> each ep rank keeps its E/ep experts, gains the
        # other ranks' capacity slots: (E/ep, ep*C, D)
        xin = lax.all_to_all(xin, "ep", split_axis=0, concat_axis=1, tiled=True)
    act = jnp.einsum("ecd,edf->ecf", xin, w_up,
                     preferred_element_type=jnp.float32)
    act = jax.nn.gelu(act).astype(h.dtype)
    yout = jnp.einsum("ecf,efd->ecd", act, w_down,
                      preferred_element_type=jnp.float32)
    yout = lax.psum(yout, "tp")  # F is tp-sharded: partial sums
    if ep > 1:
        yout = lax.all_to_all(yout, "ep", split_axis=1, concat_axis=0, tiled=True)
    out = jnp.einsum("nec,ecd->nd", disp * gate[:, None, None].astype(jnp.float32),
                     yout)
    return out.astype(h.dtype)


def _make_stage_fn(cfg: PipelineConfig, mesh: Mesh):
    """Per-shard body for ONE transformer layer (tp/sp/ep-parallel)."""
    tp = mesh.shape["tp"]
    H_loc = cfg.n_heads // tp
    hd = cfg.d_model // cfg.n_heads
    D = cfg.d_model

    def layer(x, lp):
        # x: (mb, T_loc, D) full residual stream on every tp rank
        B, T, _ = x.shape
        h = _ln(x, lp["ln1"])
        # kernel (D, 3, D/tp): q/k/v each col-sharded on tp (head-aligned)
        qkv = jnp.einsum("btd,dke->btke", h, lp["qkv"])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # (B, T, D/tp)
        q = q.reshape(B, T, H_loc, hd)
        k = k.reshape(B, T, H_loc, hd)
        v = v.reshape(B, T, H_loc, hd)
        attn = _ring_attn_local(
            q, k, v, axis_name="sp", all_axes=AXES, causal=True
        ).reshape(B, T, D // tp)
        proj = attn @ lp["out"]                # row-sharded: partial sums
        x = x + lax.psum(proj, "tp")
        h = _ln(x, lp["ln2"])
        if cfg.n_experts > 0:
            y = _moe_ffn(h.reshape(B * T, D), lp["router"], lp["moe_up"],
                         lp["moe_down"], cfg, mesh).reshape(B, T, D)
        else:
            a = jax.nn.gelu(h @ lp["mlp_up"])  # col-sharded
            y = lax.psum(a @ lp["mlp_down"], "tp")
        return x + y

    def stage(stage_params, x):
        # stage_params leaves have leading axis L_loc (this stage's layers)
        L_loc = stage_params["ln1"].shape[0]
        for i in range(L_loc):
            x = layer(x, jax.tree.map(lambda a: a[i], stage_params))
        return x

    return stage


def make_pipeline_train_step(
    mesh: Mesh,
    cfg: Optional[PipelineConfig] = None,
    learning_rate: float = 1e-3,
    seed: int = 0,
):
    """Build the 5-axis-parallel LM training step.

    Returns ``(train_step, params, opt_state, data_sharding)``;
    ``train_step(params, opt_state, tokens) -> (params, opt_state, loss)``.
    ``tokens``: (B, T) int32, B % (dp * n_microbatches) == 0, T % sp == 0.
    """
    import optax

    cfg = cfg or PipelineConfig()
    for ax in AXES:
        if ax not in mesh.shape:
            raise ValueError(f"mesh must have axis {ax!r} (size 1 is fine)")
    pp, sp_n, tp, ep = (mesh.shape[a] for a in ("pp", "sp", "tp", "ep"))
    if cfg.n_layers % pp:
        raise ValueError("n_layers must divide by pp")
    if cfg.n_heads % tp or cfg.d_ff % tp or cfg.d_model % tp:
        raise ValueError("heads/d_ff/d_model must divide by tp")
    if cfg.n_experts and cfg.n_experts % ep:
        raise ValueError("n_experts must divide by ep")

    params = init_params(cfg, seed)
    specs = param_specs(cfg)
    params = {
        k: jax.device_put(v, NamedSharding(mesh, specs[k]))
        for k, v in params.items()
    }
    tx = optax.adamw(learning_rate)
    # optimizer moments propagate the param shardings; leaves with NO
    # param dependence (adam's step count) come out single-device, so pin
    # every non-mesh leaf replicated over the mesh — a mixed placement
    # breaks later jitted steps and checkpoint-restore templates
    opt_state = jax.jit(tx.init)(params)
    _rep = jax.sharding.NamedSharding(mesh, P())
    opt_state = jax.tree.map(
        lambda a: a if isinstance(
            getattr(a, "sharding", None), jax.sharding.NamedSharding
        ) else jax.device_put(a, _rep),
        opt_state,
    )
    data_sh = NamedSharding(mesh, P("dp", "sp"))
    stage_fn = _make_stage_fn(cfg, mesh)
    M = cfg.n_microbatches
    S = pp

    def _fwd_loss(p, tokens):
        """Per-shard: tokens (B_loc, T_loc) int32."""
        B_loc, T_loc = tokens.shape
        mb = B_loc // M
        D, V = cfg.d_model, cfg.vocab
        pp_idx = lax.axis_index("pp")
        sp_idx = lax.axis_index("sp")
        tp_idx = lax.axis_index("tp")

        # ---- embed (stage-0 work, computed by all pp ranks; masked later)
        posids = sp_idx * T_loc + jnp.arange(T_loc)
        x0 = p["embed"][tokens] + p["pos"][posids][None]       # (B_loc,T_loc,D)
        x0 = x0.reshape(M, mb, T_loc, D)

        # ---- next-token targets: shift across the sp ring
        first = lax.ppermute(
            tokens[:, :1], "sp", [(j, (j - 1) % sp_n) for j in range(sp_n)]
        )
        targets = jnp.concatenate([tokens[:, 1:], first], axis=1)
        tmask = jnp.ones((B_loc, T_loc), jnp.float32)
        if sp_n > 1:
            tmask = jnp.where(sp_idx == sp_n - 1,
                              tmask.at[:, -1].set(0.0), tmask)
        else:
            tmask = tmask.at[:, -1].set(0.0)
        targets = targets.reshape(M, mb, T_loc)
        tmask = tmask.reshape(M, mb, T_loc)

        fwd_perm = [(j, (j + 1) % S) for j in range(S)]

        def tick(carry, t):
            state, loss_sum, cnt = carry
            # activations hop one stage forward; stage 0 ingests microbatch t
            shifted = lax.ppermute(state, "pp", fwd_perm) if S > 1 else state
            inj = lax.dynamic_index_in_dim(
                x0, jnp.clip(t, 0, M - 1), 0, keepdims=False
            )
            cur = jnp.where(pp_idx == 0, inj, shifted) if S > 1 else inj
            new = stage_fn(p_stage, cur)
            # last stage, ticks S-1..M+S-2 hold microbatch t-(S-1)'s output
            midx = jnp.clip(t - (S - 1), 0, M - 1)
            hvalid = (t >= S - 1) & (pp_idx == S - 1)
            h = _ln(new, p["ln_f"])
            h_loc = lax.dynamic_slice_in_dim(h, tp_idx * (D // tp), D // tp, 2)
            logits = lax.psum(
                jnp.einsum("btd,dv->btv", h_loc,
                           p["lm_head"].astype(jnp.float32)), "tp")
            tgt = lax.dynamic_index_in_dim(targets, midx, 0, keepdims=False)
            msk = lax.dynamic_index_in_dim(tmask, midx, 0, keepdims=False)
            logp = jax.nn.log_softmax(logits, -1)
            ll = jnp.take_along_axis(logp, tgt[..., None], -1)[..., 0]
            valid = hvalid.astype(jnp.float32)
            loss_sum = loss_sum + valid * (-(ll * msk).sum())
            cnt = cnt + valid * msk.sum()
            return (new, loss_sum, cnt), None

        p_stage = {
            k: v for k, v in p.items()
            if k not in ("embed", "pos", "ln_f", "lm_head")
        }
        state0 = vary_over(jnp.zeros((mb, T_loc, D), cfg.dtype), AXES)
        l0 = vary_over(jnp.zeros((), jnp.float32), AXES)
        (_, loss_sum, cnt), _ = lax.scan(
            tick, (state0, l0, l0), jnp.arange(M + S - 1)
        )
        # loss lives on the last pp stage only; tokens are sharded dp×sp.
        # psum over tp too (numerator/denominator both scale by tp — exact).
        total = lax.psum(loss_sum, ("pp", "dp", "sp", "tp", "ep"))
        n = lax.psum(cnt, ("pp", "dp", "sp", "tp", "ep"))
        return total / n

    # ---- per-shard loss AND grad in ONE shard-mapped body ----------------
    # value_and_grad lives INSIDE the body (per-shard grads, psum'd over
    # each param's replication axes) instead of wrapping the shard_map:
    # differentiating through a shard_map with replicated out_specs is
    # exactly the transform old (pre-vma) jax cannot transpose
    # (_SpecError), while per-shard AD through the body's collectives is
    # the classic pmap-era recipe every jax generation supports.  The
    # math is identical: the final psum's transpose seeds cotangent 1 on
    # every device, so local partials summed over a param's replication
    # axes ARE the global grad.
    mesh_axes = tuple(mesh.axis_names)

    def _repl_axes(spec: P):
        named = set()
        for entry in spec:
            if entry is None:
                continue
            if isinstance(entry, (tuple, list)):
                named.update(entry)
            else:
                named.add(entry)
        return tuple(a for a in mesh_axes if a not in named)

    grad_psum_axes = {k: _repl_axes(specs[k]) for k in params}

    def _fwd_loss_and_grad(p, tokens):
        loss, grads = jax.value_and_grad(_fwd_loss)(p, tokens)
        grads = {
            k: (lax.psum(g, grad_psum_axes[k]) if grad_psum_axes[k] else g)
            for k, g in grads.items()
        }
        return loss, grads

    in_specs = ({k: specs[k] for k in params}, P("dp", "sp"))
    sharded_loss_and_grad = shard_map_compat(
        _fwd_loss_and_grad, mesh, in_specs=in_specs,
        out_specs=(P(), {k: specs[k] for k in params}),
        check=False,
    )

    def _step(p, opt, tokens):
        loss, grads = sharded_loss_and_grad(p, tokens)
        updates, opt = tx.update(grads, opt, p)
        p = optax.apply_updates(p, updates)
        return p, opt, loss

    train_step = jax.jit(_step, donate_argnums=(0, 1))
    return train_step, params, opt_state, data_sh


# ---------------------------------------------------------------------------
# Single-device oracle (same params, dense math) for tests.
# ---------------------------------------------------------------------------
def reference_loss(params, tokens, cfg: PipelineConfig) -> jnp.ndarray:
    """Unsharded forward+loss over the same param pytree (test oracle;
    exact match requires capacity_factor high enough that no token drops)."""
    B, T = tokens.shape
    D, H, V = cfg.d_model, cfg.n_heads, cfg.vocab
    x = params["embed"][tokens] + params["pos"][jnp.arange(T)][None]
    for i in range(cfg.n_layers):
        lp = jax.tree.map(lambda a: a[i], {
            k: v for k, v in params.items()
            if k not in ("embed", "pos", "ln_f", "lm_head")
        })
        h = _ln(x, lp["ln1"])
        qkv = jnp.einsum("btd,dke->btke", h, lp["qkv"])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        q = q.reshape(B, T, H, D // H)
        k = k.reshape(B, T, H, D // H)
        v = v.reshape(B, T, H, D // H)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                       preferred_element_type=jnp.float32) / np.sqrt(D // H)
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
        a = jax.nn.softmax(s, -1).astype(v.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", a, v).reshape(B, T, D)
        x = x + attn @ lp["out"]
        h = _ln(x, lp["ln2"])
        if cfg.n_experts > 0:
            N = B * T
            hf = h.reshape(N, D)
            gp = jax.nn.softmax((hf @ lp["router"]).astype(jnp.float32), -1)
            eidx = jnp.argmax(gp, -1)
            gate = jnp.max(gp, -1)
            xin = jnp.einsum("ne,nd->ned", jax.nn.one_hot(eidx, cfg.n_experts),
                             hf.astype(jnp.float32)).astype(h.dtype)
            act = jax.nn.gelu(jnp.einsum("ned,edf->nef", xin, lp["moe_up"],
                                         preferred_element_type=jnp.float32)
                              ).astype(h.dtype)
            yo = jnp.einsum("nef,efd->ned", act, lp["moe_down"],
                            preferred_element_type=jnp.float32)
            y = jnp.einsum("ned,ne->nd", yo,
                           jax.nn.one_hot(eidx, cfg.n_experts) *
                           gate[:, None]).reshape(B, T, D).astype(h.dtype)
        else:
            y = jax.nn.gelu(h @ lp["mlp_up"]) @ lp["mlp_down"]
        x = x + y
    hf = _ln(x, params["ln_f"])
    logits = jnp.einsum("btd,dv->btv", hf, params["lm_head"].astype(jnp.float32))
    targets = jnp.roll(tokens, -1, 1)
    logp = jax.nn.log_softmax(logits, -1)
    ll = jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]
    msk = jnp.ones_like(ll).at[:, -1].set(0.0)
    return -(ll * msk).sum() / msk.sum()


# ---------------------------------------------------------------------------
# Elastic checkpointing: preemptible-TPU recovery for the 5-axis train step
# (SURVEY §5.3/§5.4 — the reference checkpoints only the trainer element;
# sharded multi-chip training state is net-new).  Orbax persists each
# jax.Array with its sharding; restoring against a sharded template puts
# every shard back on its mesh position, so a resumed run is bit-identical
# to an uninterrupted one (tests/test_pipeline_parallel.py asserts this).
# ---------------------------------------------------------------------------
def save_train_state(path: str, step: int, params, opt_state) -> str:
    """Persist (params, opt_state) as checkpoint `step` under `path`."""
    from ..core.checkpoint import save_state

    return save_state(path, step, {"params": params, "opt_state": opt_state})


def restore_train_state(path: str, step: int, params_template, opt_template):
    """-> (params, opt_state) restored onto the templates' shardings."""
    from ..core.checkpoint import restore_state

    state = restore_state(
        path, step, {"params": params_template, "opt_state": opt_template}
    )

    def _resharded(tmpl_tree, got_tree):
        # orbax can restore scalar/replicated leaves onto a single device;
        # re-commit every leaf to its template's mesh sharding so the next
        # jitted step sees a consistent placement
        def one(got, tmpl):
            if hasattr(tmpl, "sharding") and hasattr(got, "shape"):
                return jax.device_put(got, tmpl.sharding)
            return got

        return jax.tree.map(one, got_tree, tmpl_tree)

    return (
        _resharded(params_template, state["params"]),
        _resharded(opt_template, state["opt_state"]),
    )
