"""Ring attention: exact attention over a sequence sharded across devices.

The reference has no long-context story (SURVEY §5.7: "absent ... net-new
design").  This is that net-new design: the sequence axis is sharded over a
mesh axis (``sp``); each device holds a local Q/K/V block, and K/V blocks
rotate around the ring via ``lax.ppermute`` while a streaming (online)
softmax accumulates exact results — attention memory stays O(T_local) and
the permute overlaps with the block matmuls (XLA schedules the ppermute
DMA concurrently; each hop is neighbor-to-neighbor on ICI).

Pattern references: Liu et al., "Ring Attention with Blockwise Transformers
for Near-Infinite Context" (PAPERS.md); flash-attention online softmax.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map


def vary_over(x, axes):
    """Mark a constant as device-varying over manual mesh axes (shard_map
    vma typing; pcast on jax >= 0.8, pvary before).  On jax generations
    WITHOUT vma typing (0.4.x: neither pcast nor pvary exists) the mark
    is meaningless — closed-over constants are handled by the old
    ``check_rep`` replication tracking — so the identity is correct."""
    try:
        return lax.pcast(x, axes, to="varying")
    except (AttributeError, TypeError):  # pragma: no cover — older jax
        pass
    try:
        return lax.pvary(x, axes)
    except AttributeError:  # pre-vma jax: no mark exists or is needed
        return x


def shard_map_compat(body, mesh, in_specs, out_specs, check: bool = True):
    """``shard_map`` across jax generations: the strictness knob is
    ``check_vma`` on vma-typed jax (>= 0.8 era), ``check_rep`` on the
    older replication-tracked jax, and absent before either.  Callers
    pass ``check=False`` for bodies the checker cannot type (the pallas
    interpreter emits internal constants without vma, and old jax has no
    pallas replication rule at all) — the SAME intent lands on whichever
    kwarg this jax speaks.  One wrapper shared by every manual-SPMD
    subsystem (ring/ulysses attention, the pipeline-parallel trainer) so
    the version shim cannot drift between them."""
    for kwargs in ({"check_vma": check}, {"check_rep": check}, {}):
        try:
            return shard_map(
                body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                **kwargs,
            )
        except TypeError:  # this jax doesn't know the kwarg — next shim
            continue
    raise RuntimeError("shard_map rejected every known strictness kwarg")


def _block_attn(q, k, v, q_pos, k_pos, causal: bool, scale: float):
    """One (q-block × kv-block) attention contribution.

    q: (B, Tq, H, D), k/v: (B, Tk, H, D); returns (scores-max m, partial
    numerator o, partial denominator l) for online-softmax merging.
    """
    # f32 accumulation on the MXU regardless of input dtype (bf16-safe)
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale  # (B,H,Tq,Tk) f32
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]  # (Tq,Tk)
        s = jnp.where(mask[None, None], s, -jnp.inf)
    m = jnp.max(s, axis=-1)  # (B,H,Tq)
    # fully-masked rows: exp(-inf - -inf) guards via where
    p = jnp.exp(s - jnp.where(jnp.isinf(m), 0.0, m)[..., None])
    p = jnp.where(jnp.isinf(s), 0.0, p)
    l = jnp.sum(p, axis=-1)  # (B,H,Tq) f32
    o = jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
    )  # (B,Tq,H,D) f32
    return m, o, l


def _merge(m1, o1, l1, m2, o2, l2):
    """Merge two online-softmax partials (flash-attention recurrence)."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(jnp.where(jnp.isinf(m1), -jnp.inf, m1) - m)
    a2 = jnp.exp(jnp.where(jnp.isinf(m2), -jnp.inf, m2) - m)
    a1 = jnp.where(jnp.isinf(m1) & (m1 < 0), 0.0, a1)
    a2 = jnp.where(jnp.isinf(m2) & (m2 < 0), 0.0, a2)
    o = o1 * a1.transpose(0, 2, 1)[..., None] + o2 * a2.transpose(0, 2, 1)[..., None]
    l = l1 * a1 + l2 * a2
    return m, o, l


def _ring_flash_local(q, k, v, *, axis_name: str, causal: bool,
                      interpret: bool, ring_size: int):
    """Per-shard body with the Pallas flash kernel as the block primitive.

    Each ring hop holds one remote K/V block; the block's attention runs
    as ONE flash-attention kernel call (``ops/flash_attention.py``
    ``with_lse``), and partials merge across hops by the exact
    (out, lse) recurrence.  Hop cases under causal masking:

    * hop 0 — the device's own block: intra-block causal (kernel
      ``causal=True``; local positions are aligned, no offset needed);
    * source block strictly BEFORE mine: fully visible
      (``causal=False``);
    * source block AFTER mine: fully masked — the kernel still runs
      (same cost shape as the jnp path, which masks everything to -inf)
      but its contribution is zeroed via lse = -inf before the merge.

    The hop loop is a Python unroll over the STATIC ``ring_size`` (the
    mesh axis length), so each hop keeps a static kernel configuration;
    visibility of later hops depends on the traced device index and is
    applied as a select on lse.
    """
    from ..ops.flash_attention import _NEG_INF, flash_attention_lse

    my_idx = lax.axis_index(axis_name)
    B, T, H, D = q.shape
    # this body runs under check_vma=False (the pallas interpreter emits
    # constants without vma, tripping strict varying-axes typing), so the
    # accumulators need no vary_over marking
    lse_acc = jnp.full((B, H, T), _NEG_INF, jnp.float32)
    o_acc = jnp.zeros(q.shape, jnp.float32)
    perm = [(j, (j + 1) % ring_size) for j in range(ring_size)]
    k_cur, v_cur = k, v
    for i in range(ring_size):
        src = (my_idx - i) % ring_size  # traced; block owner of k_cur
        o_b, lse_b = flash_attention_lse(
            q, k_cur, v_cur, causal=(causal and i == 0),
            interpret=interpret,
        )
        if causal and i > 0:
            visible = src < my_idx  # traced whole-block visibility
            lse_b = jnp.where(visible, lse_b, _NEG_INF)
        # exact two-partial merge (the kernel's online-softmax recurrence
        # lifted to whole blocks)
        lse_new = jnp.logaddexp(lse_acc, lse_b)
        a_acc = jnp.exp(lse_acc - lse_new)
        a_b = jnp.exp(lse_b - lse_new)
        o_acc = (
            o_acc * a_acc.transpose(0, 2, 1)[..., None]
            + o_b.astype(jnp.float32) * a_b.transpose(0, 2, 1)[..., None]
        )
        lse_acc = lse_new
        if i + 1 < ring_size:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
    return o_acc.astype(q.dtype)


def _ring_attn_local(q, k, v, *, axis_name: str, all_axes, causal: bool):
    """Per-shard body (runs under shard_map): local Q stays put, K/V blocks
    ring-rotate `axis_size` times."""
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, T, H, D = q.shape
    scale = 1.0 / (D**0.5)
    q_pos = my_idx * T + jnp.arange(T)

    # constants entering the scan carry must be marked device-varying over
    # the manual mesh axes (shard_map vma typing)
    m0 = vary_over(jnp.full((B, H, T), -jnp.inf, jnp.float32), all_axes)
    o0 = vary_over(jnp.zeros(q.shape, jnp.float32), all_axes)
    l0 = vary_over(jnp.zeros((B, H, T), jnp.float32), all_axes)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def step(carry, i):
        m, o, l, k_cur, v_cur = carry
        src = (my_idx - i) % axis_size  # whose kv block we currently hold
        k_pos = src * T + jnp.arange(T)
        m2, o2, l2 = _block_attn(q, k_cur, v_cur, q_pos, k_pos, causal, scale)
        m, o, l = _merge(m, o, l, m2, o2, l2)
        # rotate kv to the next device (neighbor hop on the ring)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (m, o, l, k_nxt, v_nxt), None

    (m, o, l, _, _), _ = lax.scan(
        step, (m0, o0, l0, k, v), jnp.arange(axis_size)
    )
    # normalize; fully-masked rows (can't happen causally: diag always valid)
    out = o / jnp.maximum(l, 1e-20).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q,
    k,
    v,
    mesh: Mesh,
    *,
    seq_axis: str = "sp",
    batch_axes=("dp",),
    causal: bool = True,
    use_flash: bool = False,
    interpret: bool = False,
):
    """Exact multi-head attention with the sequence dim sharded on
    ``seq_axis`` and batch on ``batch_axes``.

    q/k/v: (B, T, H, D) global shapes; T must divide by mesh[seq_axis].
    Returns (B, T, H, D) with the same sharding.

    ``use_flash=True`` runs each ring hop's block product as ONE Pallas
    flash-attention kernel call (ring-flash composition: VMEM-streamed
    scores inside the hop, exact (out, lse) merge across hops) — the
    long-context configuration on real TPU.  ``interpret`` forces the
    kernel interpreter (CPU tests).
    """
    batch_axes = tuple(a for a in batch_axes if a in mesh.shape)
    batch_spec = (
        None
        if not batch_axes
        else (batch_axes[0] if len(batch_axes) == 1 else batch_axes)
    )
    spec = P(batch_spec, seq_axis, None, None)
    all_axes = tuple(batch_axes) + (seq_axis,)
    if use_flash:
        body = functools.partial(
            _ring_flash_local, axis_name=seq_axis,
            causal=causal, interpret=interpret,
            ring_size=mesh.shape[seq_axis],
        )
    else:
        body = functools.partial(
            _ring_attn_local, axis_name=seq_axis, all_axes=all_axes,
            causal=causal,
        )
    # the pallas interpreter/lowering emits internal constants without
    # vma (and pre-vma jax has no pallas replication rule at all);
    # jax's documented workaround is to disable the check for this body
    # (the jnp ring keeps strict typing)
    fn = shard_map_compat(
        body, mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check=not use_flash,
    )
    return fn(q, k, v)


def reference_attention(q, k, v, causal: bool = True):
    """Unsharded exact attention (test oracle)."""
    B, T, H, D = q.shape
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / (D**0.5)
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)
