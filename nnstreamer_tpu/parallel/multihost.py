"""Multi-host (multi-process) runtime bootstrap: the DCN-scale half of the
distributed communication backend.

The reference scales among devices with nnstreamer-edge transports (TCP /
MQTT / AITT — SURVEY §2.3) and leaves intra-model collectives to
NCCL-style out-of-repo stacks.  The TPU-native equivalent is the JAX
multi-process runtime: **one process per host**, every process sees the
global device list, XLA inserts collectives that ride ICI within a slice
and DCN across slices (SURVEY §5.8 "inter-slice/inter-host = DCN via JAX
multi-process runtime").

This module owns three things:

1. ``initialize()`` — env-driven ``jax.distributed`` bring-up that works
   both on real TPU pods (where the coordinator is auto-discovered) and in
   CPU-simulated multi-host tests (N processes × M virtual devices on
   localhost, gloo collectives).
2. ``hybrid_mesh()`` — a Mesh whose DCN-crossing axes are outermost (one
   mesh row per process) and whose ICI axes stay within a host, following
   the scaling-book rule: put the slowest links on the axes with the
   least-frequent/most-overlappable collectives (dp gradient psum), keep
   tp/sp activation collectives on ICI.
3. Cross-process utilities — barrier, broadcast-from-primary,
   per-process data → global sharded array — small wrappers with a stable
   framework-level API so elements/trainers never import jax internals.

Elasticity: the JAX runtime is gang-scheduled (a lost process fails the
job); elastic behavior is restart-from-checkpoint — see
``trainer/jax_trainer.py`` periodic Orbax checkpoints + the
``resume`` property, and ``Documentation/examples.md`` (elastic resume).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np

from ..core.log import get_logger

log = get_logger("parallel.multihost")

_ENV_COORD = "NNS_TPU_COORDINATOR"
_ENV_NPROC = "NNS_TPU_NUM_PROCS"
_ENV_PROC = "NNS_TPU_PROC_ID"
_ENV_LOCAL = "NNS_TPU_LOCAL_DEVICES"

_initialized = False


def is_initialized() -> bool:
    return _initialized


def initialize(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_count: Optional[int] = None,
    platform: Optional[str] = None,
) -> None:
    """Bring up the multi-process runtime (idempotent).

    On a real TPU pod all arguments are auto-discovered by JAX (metadata
    server) — call with no arguments.  For CPU-simulated multi-host (tests,
    laptops) pass/export the coordinator address and process ids:

        NNS_TPU_COORDINATOR=127.0.0.1:29400 NNS_TPU_NUM_PROCS=2 \
        NNS_TPU_PROC_ID=0 NNS_TPU_LOCAL_DEVICES=4 python worker.py

    ``local_device_count``/``platform`` must be applied BEFORE the backend
    initializes, so call this before any other jax API touches devices.
    """
    global _initialized
    if _initialized:
        return
    import jax

    coordinator = coordinator or os.environ.get(_ENV_COORD)
    if num_processes is None and os.environ.get(_ENV_NPROC):
        num_processes = int(os.environ[_ENV_NPROC])
    if process_id is None and os.environ.get(_ENV_PROC):
        process_id = int(os.environ[_ENV_PROC])
    if local_device_count is None and os.environ.get(_ENV_LOCAL):
        local_device_count = int(os.environ[_ENV_LOCAL])

    if platform:
        os.environ["JAX_PLATFORMS"] = platform
        jax.config.update("jax_platforms", platform)
    if platform == "cpu" and (coordinator or num_processes):
        # CPU-simulated multi-host: cross-process collectives on the CPU
        # backend need an explicit implementation (default "none" fails
        # any multiprocess computation with INVALID_ARGUMENT); gloo is
        # the one jaxlib ships
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError) as e:
            log.warning("cpu collectives unavailable (%s): cross-process "
                        "computations may fail", e)
    if local_device_count:
        if hasattr(jax.config, "jax_num_cpu_devices"):
            jax.config.update("jax_num_cpu_devices", local_device_count)
        else:
            # older jax (0.4.x) has no post-import device-count config;
            # XLA reads XLA_FLAGS at backend init, which this contract
            # already requires to be in the future ("call before any
            # other jax API touches devices") — fresh worker processes
            # always satisfy it.  An INHERITED device-count flag (the
            # test conftest exports one) is rewritten, not silently
            # kept: the caller's count wins, loudly.
            import re

            flags = os.environ.get("XLA_FLAGS", "")
            want = (f"--xla_force_host_platform_device_count="
                    f"{local_device_count}")
            pat = r"--xla_force_host_platform_device_count=\d+"
            if re.search(pat, flags):
                if want not in flags:
                    log.warning(
                        "overriding inherited XLA device-count flag with "
                        "local_device_count=%d", local_device_count)
                flags = re.sub(pat, want, flags)
            else:
                flags = f"{flags} {want}".strip()
            os.environ["XLA_FLAGS"] = flags

    if coordinator is None and num_processes is None:
        # real pod: everything comes from the cluster environment
        jax.distributed.initialize()
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    _initialized = True
    log.info(
        "multihost up: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )


def shutdown() -> None:
    global _initialized
    if not _initialized:
        return
    import jax

    jax.distributed.shutdown()
    _initialized = False


def process_index() -> int:
    import jax

    return jax.process_index()


def process_count() -> int:
    import jax

    return jax.process_count()


def is_primary() -> bool:
    """True on the process that should own singleton side effects
    (checkpoint writes, bus logging, serving endpoints)."""
    return process_index() == 0


# ---------------------------------------------------------------------------
# Hybrid DCN×ICI meshes
# ---------------------------------------------------------------------------

def hybrid_mesh(
    ici_axes: Dict[str, int],
    dcn_axes: Optional[Dict[str, int]] = None,
):
    """Mesh spanning every process: ``dcn_axes`` cross hosts (outermost,
    default ``{"dp": process_count()}``), ``ici_axes`` stay within a host.

    ``hybrid_mesh({"tp": 4}, {"dp": 2})`` on 2 hosts × 4 chips gives a
    (dp=2, tp=4) mesh where tp collectives never touch DCN.  Axis sizes
    must multiply to the per-host / host counts respectively; ``-1``
    wildcards are resolved like ``make_mesh``.
    """
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    nproc = jax.process_count()
    nlocal = jax.local_device_count()
    if dcn_axes is None:
        dcn_axes = {"dp": nproc}

    ici = _resolve(dict(ici_axes), nlocal, "ici")
    dcn = _resolve(dict(dcn_axes), nproc, "dcn")

    if nproc == 1:
        # single-process: collapse to an ordinary mesh over local devices
        from .mesh import make_mesh

        merged = {**dcn, **ici}
        return make_mesh(merged, devices=jax.devices()[: nproc * nlocal])

    # per-axis shape vectors: every mesh axis appears in both vectors, as 1
    # on the side it does not span
    names = tuple(dcn.keys()) + tuple(ici.keys())
    ici_shape = [1] * len(dcn) + [ici[k] for k in ici]
    dcn_shape = [dcn[k] for k in dcn] + [1] * len(ici)
    devs = mesh_utils.create_hybrid_device_mesh(
        ici_shape, dcn_shape, devices=jax.devices(),
        process_is_granule=True,
    )
    return Mesh(devs, names)


def _resolve(sizes: Dict[str, int], total: int, kind: str) -> Dict[str, int]:
    import math

    wild = [k for k, v in sizes.items() if v == -1]
    if len(wild) > 1:
        raise ValueError(f"at most one {kind} axis may be -1")
    fixed = math.prod(v for v in sizes.values() if v != -1)
    if wild:
        if total % fixed:
            raise ValueError(f"{total} {kind} devices not divisible by {fixed}")
        sizes[wild[0]] = total // fixed
    elif math.prod(sizes.values()) != total:
        raise ValueError(
            f"{kind} axes {sizes} must multiply to {total}"
        )
    return sizes


# ---------------------------------------------------------------------------
# Cross-process data movement
# ---------------------------------------------------------------------------

def barrier(name: str = "nns_tpu_barrier") -> None:
    """Block until every process reaches this point (control plane)."""
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def broadcast_from_primary(tree):
    """Replicate host-local data from process 0 to all processes
    (config blobs, model-selection decisions, shuffled index orders)."""
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(tree)


def all_processes_agree(value) -> bool:
    """True iff every process passed an identical value (guardrail before
    collective compilation: mismatched shapes deadlock a gang-scheduled
    job with no diagnostics)."""
    from jax.experimental import multihost_utils

    try:
        multihost_utils.assert_equal(value, fail_message="mismatch")
        return True
    except AssertionError:
        return False


def global_array(mesh, pspec, local_data: np.ndarray):
    """Assemble per-process host data into ONE global jax.Array sharded by
    ``pspec`` over ``mesh`` — the data-loader handoff for multi-host
    training (each host reads its own datarepo shard; XLA sees a single
    logical batch).
    """
    import jax
    from jax.sharding import NamedSharding

    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, pspec), np.asarray(local_data)
    )


def gather_to_host(arr) -> np.ndarray:
    """Fetch a (possibly multi-host sharded) jax.Array to every host as
    numpy — the sink-side boundary (metrics, decoders that must run on
    host).  Uses an all-gather under the hood; cheap for the small
    decoded outputs it is meant for."""
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))
