"""Parameter/activation sharding rules.

The scaling-book recipe: pick a mesh, annotate shardings with
``NamedSharding(mesh, PartitionSpec(...))``, let XLA's SPMD partitioner
insert the collectives.  This module holds the annotation helpers: regex
path -> PartitionSpec rules applied over a params pytree.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# Transformer parameter rules for (fsdp|dp)×tp meshes.  Convention: shard
# the contracting/output-feature dim that grows with the model on tp, and
# (optionally) the other dim on fsdp.
def transformer_rules(tp_axis: str = "tp", fsdp_axis: Optional[str] = None):
    f = fsdp_axis
    return [
        # anchored so pos_embed/embedding (positions) stays replicated
        (r"(^|/)embed/embedding$", P(tp_axis, None)),  # vocab sharded
        (r"(attn|attention).*(query|key|value|qkv).*kernel$", P(f, tp_axis)),
        (r"(attn|attention).*(out|proj).*kernel$", P(tp_axis, f)),
        (r"mlp.*(up|fc1|in).*kernel$", P(f, tp_axis)),
        (r"mlp.*(down|fc2|out).*kernel$", P(tp_axis, f)),
        (r"lm_head.*kernel$", P(f, tp_axis)),
        (r".*bias$", P(None)),
        (r".*(scale|ln|layernorm).*", P(None)),
    ]


def spec_for_path(path: str, rules) -> P:
    for pat, spec in rules:
        if re.search(pat, path):
            return spec
    return P()  # replicated by default


def _keypath_str(kp) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in kp)


def shard_params(params, mesh: Mesh, rules=None):
    """device_put every param leaf with its rule-derived NamedSharding."""
    rules = rules if rules is not None else transformer_rules()

    def put(kp, leaf):
        path = _keypath_str(kp)
        spec = spec_for_path(path, rules)
        # drop axes the mesh doesn't have and axes that don't divide evenly
        cleaned = []
        for i, ax in enumerate(spec):
            ok = (
                ax is not None
                and ax in mesh.shape
                and i < leaf.ndim
                and leaf.shape[i] % mesh.shape[ax] == 0
            )
            cleaned.append(ax if ok else None)
        while cleaned and cleaned[-1] is None:
            cleaned.pop()
        return jax.device_put(leaf, NamedSharding(mesh, P(*cleaned)))

    return jax.tree_util.tree_map_with_path(put, params)


def batch_sharding(mesh: Mesh, *axes: Optional[str]) -> NamedSharding:
    """NamedSharding for data: e.g. batch_sharding(mesh, 'dp', 'sp') shards
    dim0 on dp and dim1 on sp (tokens: (batch, seq))."""
    cleaned = [a if (a is not None and a in mesh.shape) else None for a in axes]
    return NamedSharding(mesh, P(*cleaned))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
