"""Fused uint8 -> float normalize (scale + bias + cast) as a Pallas kernel.

The canonical image-ingest hot path (``tensor_transform mode=arithmetic``
chains + typecast in the reference, ORC-accelerated there): one VMEM-tiled
pass computing ``x * scale + bias`` in the target dtype.  On TPU this runs
as a real Pallas kernel (VPU elementwise, lane-aligned tiles); elsewhere it
runs the identical jnp expression (XLA fuses it anyway) — same numerics.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

_LANES = 128
_ROWS = 256  # block rows: multiple of every dtype's sublane minimum


def _kernel(x_ref, o_ref, *, scale: float, bias: float, out_dtype):
    x = x_ref[:].astype(jnp.float32)
    o_ref[:] = (x * scale + bias).astype(out_dtype)


@functools.partial(jax.jit, static_argnames=("scale", "bias", "out_dtype"))
def _pallas_normalize(flat, *, scale: float, bias: float, out_dtype):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    rows = flat.shape[0] // _LANES
    x2 = flat.reshape(rows, _LANES)
    grid = (max(1, rows // _ROWS),)
    blk = min(_ROWS, rows)
    out = pl.pallas_call(
        functools.partial(_kernel, scale=scale, bias=bias, out_dtype=out_dtype),
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), out_dtype),
        grid=grid,
        in_specs=[pl.BlockSpec((blk, _LANES), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((blk, _LANES), lambda i: (i, 0)),
    )(x2)
    return out.reshape(flat.shape)


def normalize_u8(
    x,
    scale: float = 2.0 / 255.0,
    bias: float = -1.0,
    dtype: Any = jnp.bfloat16,
    use_pallas: bool = True,
):
    """``x * scale + bias`` cast to `dtype` (default: uint8 [0,255] ->
    [-1, 1] bf16, the MobileNet ingest transform).  Accepts any shape."""
    x = jnp.asarray(x)
    on_tpu = jax.devices()[0].platform == "tpu"
    if not (use_pallas and on_tpu):
        return (x.astype(jnp.float32) * scale + bias).astype(dtype)
    n = x.size
    tile = _ROWS * _LANES
    padded = (n + tile - 1) // tile * tile
    flat = x.reshape(-1)
    if padded != n:
        flat = jnp.pad(flat, (0, padded - n))
    out = _pallas_normalize(
        flat, scale=float(scale), bias=float(bias), out_dtype=jnp.dtype(dtype)
    )
    return out[:n].reshape(x.shape)
