"""Int8 quantized inference primitives (post-training, calibration-free).

The reference's flagship pipeline runs a *quantized* model
(``tests/test_models/models/mobilenet_v2_1.0_224_quant.tflite`` — uint8
TFLite quantization executed by the tflite subplugin's integer kernels).
The TPU-native analog is int8 matmul/conv on the MXU: TPU systolic arrays
execute int8×int8→int32 at twice the bf16 rate and quantized weights halve
HBM traffic — the same lever TFLite quantization pulls on edge NPUs.

Scheme (AQT-style, all in-graph so XLA fuses everything):

* **weights** — symmetric per-output-channel int8, quantized from the
  float params inside the jitted program (negligible next to the conv
  itself; params stay a plain float tree, so checkpoints/reload/zoo
  plumbing are unchanged).
* **activations** — symmetric per-tensor *dynamic* quantization: abs-max
  computed on the fly.  No calibration pass, no observer state; accuracy
  follows TFLite dynamic-range quantization.

Usage: models opt in via ``custom=quantize:int8`` (zoo prop); see
``models/mobilenet_v2.py`` ConvBN.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import lax

_EPS = 1e-8


def quantize_symmetric(
    x: jnp.ndarray, axes: Optional[Tuple[int, ...]] = None
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(int8 values, float32 scale) with ``x ≈ values * scale``.

    ``axes=None`` → one per-tensor scale; otherwise the scale is computed
    by reducing over ``axes`` (e.g. ``(0,1,2)`` for HWIO conv kernels =
    per-output-channel).
    """
    x = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x)) if axes is None else jnp.max(
        jnp.abs(x), axis=axes, keepdims=True
    )
    scale = jnp.maximum(amax, _EPS) / 127.0
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


def int8_conv(
    x: jnp.ndarray,
    w: jnp.ndarray,
    strides: Tuple[int, int] = (1, 1),
    padding: str = "SAME",
    feature_group_count: int = 1,
    out_dtype=jnp.bfloat16,
) -> jnp.ndarray:
    """NHWC×HWIO conv computed int8×int8→int32 on the MXU, rescaled to
    ``out_dtype``.  ``w`` is the float kernel straight from params.

    Activation scales are per-SAMPLE (reduce over H/W/C only): a frame's
    quantization must not depend on which other frames the scheduler
    happened to micro-batch it with — same input, same output, regardless
    of arrival timing."""
    xq, s_x = quantize_symmetric(x, axes=tuple(range(1, x.ndim)))
    wq, s_w = quantize_symmetric(w, axes=(0, 1, 2))
    y = lax.conv_general_dilated(
        xq,
        wq,
        strides,
        padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=feature_group_count,
        preferred_element_type=jnp.int32,
    )
    rescale = (s_x * s_w.reshape(1, 1, 1, -1)).astype(jnp.float32)
    return (y.astype(jnp.float32) * rescale).astype(out_dtype)


def int8_dense(
    x: jnp.ndarray, w: jnp.ndarray, out_dtype=jnp.float32
) -> jnp.ndarray:
    """x @ w with int8 MXU accumulation; ``w`` is (in, out) float.
    Activation scale is per-row (last dim only) — batching-invariant."""
    xq, s_x = quantize_symmetric(x, axes=(x.ndim - 1,))
    wq, s_w = quantize_symmetric(w, axes=(0,))
    y = lax.dot_general(
        xq,
        wq,
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return (y.astype(jnp.float32) * (s_x * s_w.reshape(1, -1))).astype(
        out_dtype
    )
