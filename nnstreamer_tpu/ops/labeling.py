"""Fused top-1 (argmax + max score) over class logits.

≙ the image-labeling decoder's C argmax loop
(``tensordec-imagelabel.c``), done once per micro-batch on device: a
Pallas row-reduction on TPU, identical jnp expression elsewhere.
Returning (idx, score) together saves a second pass over HBM.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp

_LANES = 128


def _kernel(x_ref, idx_ref, val_ref):
    x = x_ref[:].astype(jnp.float32)  # (RB, C)
    idx_ref[:, 0] = jnp.argmax(x, axis=1).astype(jnp.int32)
    val_ref[:, 0] = jnp.max(x, axis=1)


@jax.jit
def _pallas_top1(x):
    from jax.experimental import pallas as pl

    B, C = x.shape
    idx, val = pl.pallas_call(
        _kernel,
        out_shape=(
            jax.ShapeDtypeStruct((B, 1), jnp.int32),
            jax.ShapeDtypeStruct((B, 1), jnp.float32),
        ),
        in_specs=[pl.BlockSpec((B, C), lambda: (0, 0))],
        out_specs=(
            pl.BlockSpec((B, 1), lambda: (0, 0)),
            pl.BlockSpec((B, 1), lambda: (0, 0)),
        ),
    )(x)
    return idx[:, 0], val[:, 0]


def top1(logits, use_pallas: bool = True, platform: str = None):
    """logits (B, C) or (C,) -> (argmax int32, max float32) per row.

    ``platform`` is the platform of the device this computation actually
    runs on; callers compiling for a non-default device (e.g. a filter
    with accelerator=cpu on a TPU host) must pass it — the default-backend
    guess is wrong exactly there, and a Pallas TPU kernel traced into a
    CPU program fails to lower.
    """
    x = jnp.asarray(logits)
    single = x.ndim == 1
    if single:
        x = x[None]
    if platform is None:
        platform = jax.default_backend()
    if use_pallas and platform == "tpu":
        # pad classes to a lane multiple with -inf (argmax unaffected)
        C = x.shape[1]
        Cp = (C + _LANES - 1) // _LANES * _LANES
        if Cp != C:
            x = jnp.pad(x, ((0, 0), (0, Cp - C)),
                        constant_values=-jnp.inf)
        idx, val = _pallas_top1(x.astype(jnp.float32))
    else:
        idx = jnp.argmax(x, axis=1).astype(jnp.int32)
        val = jnp.max(x.astype(jnp.float32), axis=1)
    if single:
        return idx[0], val[0]
    return idx, val
