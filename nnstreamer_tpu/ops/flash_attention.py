"""Flash attention as a Pallas TPU kernel (single-device block).

The MXU-native attention inner loop for the transformer family: Q blocks
stream over K/V blocks with an online softmax, so the (Tq x Tk) score
matrix never materializes in HBM — scores live in VMEM one block at a
time, accumulation in f32.  Pattern references: Dao et al. FlashAttention;
the public jax pallas attention examples (PAPERS.md / SNIPPETS.md).

This is the intra-device complement of the sequence-parallel layers:
``parallel/ring_attention.py`` shards T across chips and rotates K/V;
each device's local block product is exactly what this kernel computes.

``flash_attention(q, k, v)`` takes (B, T, H, D) like the rest of the
stack.  Off-TPU it falls back to the fused-XLA reference implementation;
``interpret=True`` (tests only) runs the kernel in the Pallas interpreter
instead.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30  # large-negative instead of -inf: avoids NaN in exp-diff


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *lse_ref, block_k: int,
                  causal: bool, scale: float, seq_len: int, block_q: int,
                  valid_len: int):
    """One (batch*head, q-block) program: stream K/V blocks, online softmax.

    q_ref (block_q, D); k_ref/v_ref (T, D) — the whole K/V for this head
    (the wrapper budget-checks VMEM and falls back to the XLA reference
    path when a head's K/V would not fit); o_ref (block_q, D).
    ``valid_len`` < seq_len marks wrapper padding: K columns at or past it
    are masked out (static python int — the mask compiles to constants).
    """
    qi = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * scale
    D = q.shape[-1]
    n_kv = seq_len // block_k
    padded = valid_len < seq_len

    def body(j, carry):
        m_prev, l_prev, acc = carry
        k = lax.dynamic_slice_in_dim(
            k_ref[:], j * block_k, block_k, axis=0
        ).astype(jnp.float32)
        v = lax.dynamic_slice_in_dim(
            v_ref[:], j * block_k, block_k, axis=0
        ).astype(jnp.float32)
        s = q @ k.T  # (block_q, block_k) on the MXU
        if causal or padded:
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        if padded:
            s = jnp.where(k_pos < valid_len, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + p @ v
        return m_new, l_new, acc

    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, D), jnp.float32)
    if causal:
        # blocks strictly above the diagonal contribute nothing; bound the
        # loop at the q-block's last row (traced upper bound via while)
        n_kv_eff = lax.min(
            n_kv, (qi * block_q + block_q + block_k - 1) // block_k
        )
    else:
        n_kv_eff = n_kv
    m, l, acc = lax.fori_loop(0, n_kv_eff, body, (m0, l0, acc0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)
    if lse_ref:
        # per-row log-sum-exp of the (masked) scores: the cross-block
        # merge statistic for ring attention (sequence parallelism);
        # fully-masked rows keep a large-negative lse (l == 0)
        lse = jnp.where(
            l > 0.0, m + jnp.log(jnp.maximum(l, 1e-30)), _NEG_INF
        )
        lse_ref[0][:] = lse[:, None].astype(jnp.float32)


try:  # imported lazily below for environments without pallas
    from jax.experimental import pallas as pl
except ImportError:  # pragma: no cover
    pl = None


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret",
                     "valid_len", "with_lse"),
)
def _flash_bh(qf, kf, vf, causal: bool, block_q: int, block_k: int,
              interpret: bool, valid_len: int, with_lse: bool = False):
    """(BH, Tq, D) + (BH, Tk, D) K/V -> (BH, Tq, D) [+ (BH, Tq, 1) f32
    lse]; grid over (BH, Tq/block_q).  Tk may differ from Tq (ring hops /
    partial-key calls) — causal requires Tq == Tk (aligned positions)."""
    BH, Tq, D = qf.shape
    Tk = kf.shape[1]
    if causal and Tq != Tk:
        # ValueError, not assert: survives python -O — a misaligned direct
        # caller must fail loud, never silently mis-mask
        raise ValueError(
            f"causal flash needs aligned q/k positions (Tq={Tq}, Tk={Tk})"
        )
    scale = 1.0 / (D**0.5)
    kern = functools.partial(
        _flash_kernel, block_k=block_k, causal=causal, scale=scale,
        seq_len=Tk, block_q=block_q, valid_len=valid_len,
    )
    # under shard_map (ring hops) outputs must declare their varying
    # mesh axes (jax >= 0.9 vma typing); inherit from the traced input
    vma = getattr(qf.aval, "vma", None)

    def _sds(shape, dtype):
        if vma:
            try:
                return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
            except TypeError:  # pragma: no cover — older jax
                pass
        return jax.ShapeDtypeStruct(shape, dtype)

    out_shape = [_sds((BH, Tq, D), qf.dtype)]
    out_specs = [pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0))]
    if with_lse:
        # trailing length-1 lane dim keeps the ref 2-D for Mosaic tiling
        out_shape.append(_sds((BH, Tq, 1), jnp.float32))
        out_specs.append(
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0))
        )
    res = pl.pallas_call(
        kern,
        out_shape=out_shape,
        grid=(BH, Tq // block_q),
        in_specs=[
            # None squeezes the batch*head dim out of the kernel refs
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Tk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Tk, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=out_specs,
        interpret=interpret,
    )(qf, kf, vf)
    return res if with_lse else res[0]


def _kernel_usable(Tq, Tk, D, dtype, bq, bk, interpret, causal=False,
                   aligned=True):
    """Shared gate for both entry points: can the Pallas kernel run here,
    or must the call fall back to the fused-XLA reference path?  One
    predicate so the two entry points can never drift to different
    fallback shapes."""
    if pl is None:
        return False
    if jax.default_backend() != "tpu" and not interpret:
        return False
    itemsize = jnp.dtype(dtype).itemsize
    # VMEM: one head's full K/V + the q block + f32 accumulators; past
    # ~3/4 of the ~16 MB VMEM fall back instead of an opaque Mosaic
    # overflow.  Constrains only the compiled kernel, not the interpreter.
    vmem_est = (2 * Tk * D) * itemsize + bq * D * (itemsize + 4) \
        + bq * bk * 4
    if vmem_est > 12 * 1024 * 1024 and not interpret:
        return False
    if interpret and max(Tq, Tk) > 4096:
        return False
    if causal and not aligned:
        return False
    return True


def flash_attention(q, k, v, *, causal: bool = True, block_q: int = 128,
                    block_k: int = 128, interpret: Optional[bool] = None):
    """Exact attention, (B, T, H, D) -> (B, T, H, D).

    TPU: real Pallas kernel.  Elsewhere: interpret mode when requested
    (tests), else the fused-XLA reference path (same numerics contract).
    """
    B, T, H, D = q.shape
    # interpret mode is for TESTS only (explicitly requested): it executes
    # the kernel block-by-block in the interpreter, orders of magnitude
    # slower than XLA.  Off-TPU without an explicit request -> reference.
    if interpret is None:
        interpret = False
    # non-divisible T (e.g. ViT's (S/p)^2 + 1 tokens): pad K/V/Q up to a
    # multiple of BOTH block sizes; padded K columns are masked inside the
    # kernel via the static valid_len, padded Q rows are sliced off below
    bq, bk = min(block_q, T), min(block_k, T)
    T_pad = T
    if T % bq or T % bk:
        import math

        blk = max(block_q, block_k)
        if blk % min(block_q, block_k):
            blk = math.lcm(block_q, block_k)
        T_pad = -(-T // blk) * blk
        # T_pad >= blk >= both requested blocks, and divides both
        bq, bk = min(block_q, T_pad), min(block_k, T_pad)
    if not _kernel_usable(T_pad, T_pad, D, q.dtype, bq, bk, interpret):
        from ..parallel.ring_attention import reference_attention

        return reference_attention(q, k, v, causal=causal).astype(q.dtype)
    # (B, T, H, D) -> (B*H, T, D): each (batch, head) is one independent
    # attention problem; kernel VMEM holds one head's K/V
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    if T_pad != T:
        pad = ((0, 0), (0, T_pad - T), (0, 0))
        qf = jnp.pad(qf, pad)
        kf = jnp.pad(kf, pad)
        vf = jnp.pad(vf, pad)
    out = _flash_bh(
        qf, kf, vf, causal, bq, bk, bool(interpret), valid_len=T
    )
    if T_pad != T:
        out = out[:, :T]
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)


def flash_attention_lse(q, k, v, *, causal: bool = True, block_q: int = 128,
                        block_k: int = 128,
                        interpret: Optional[bool] = None):
    """Exact attention + per-row log-sum-exp: (B, T, H, D) ->
    ((B, T, H, D), (B, H, T) f32).

    The lse is the cross-block merge statistic: two attention partials
    over disjoint key sets combine exactly as

        lse = logaddexp(lse1, lse2)
        out = out1 * exp(lse1 - lse) + out2 * exp(lse2 - lse)

    which is how ``parallel/ring_attention.py`` composes this kernel
    across the ``sp`` ring (each hop's K/V block -> one kernel call).
    Falls back to the fused-XLA reference (same contract) off-TPU unless
    ``interpret=True``.
    """
    B, T, H, D = q.shape
    Tk = k.shape[1]
    if interpret is None:
        interpret = False
    bq, bk = min(block_q, T), min(block_k, Tk)
    if (
        not _kernel_usable(T, Tk, D, q.dtype, bq, bk, interpret,
                           causal=causal, aligned=(T == Tk))
        or T % bq or Tk % bk  # ring blocks are uniform; no padding path
    ):
        return reference_attention_lse(q, k, v, causal=causal)
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    out, lse = _flash_bh(
        qf, kf, vf, causal, bq, bk, bool(interpret), valid_len=Tk,
        with_lse=True,
    )
    return (
        out.reshape(B, H, T, D).transpose(0, 2, 1, 3),
        lse.reshape(B, H, T),
    )


def reference_attention_lse(q, k, v, causal: bool = True):
    """Unsharded exact attention + lse (kernel-free contract twin)."""
    B, T, H, D = q.shape
    Tk = k.shape[1]
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / (D**0.5)
    if causal:
        assert T == Tk, "causal reference needs aligned q/k positions"
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    lse = jax.nn.logsumexp(s, axis=-1)  # (B,H,T); -inf on fully-masked rows
    p = jnp.exp(s - jnp.where(jnp.isinf(lse), 0.0, lse)[..., None])
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)
    lse = jnp.where(jnp.isinf(lse), jnp.float32(_NEG_INF), lse)
    return out, lse.astype(jnp.float32)


# ---------------------------------------------------------------------------
# Differentiable wrapper: kernel forward, recompute backward.
#
# The Pallas kernel defines no VJP; a hand-written backward kernel is the
# eventual optimization, but the standard interim pattern is forward-fast /
# backward-recompute: the forward saves only (q, k, v) as residuals, and
# the backward re-derives gradients through an f32-accumulated XLA
# reference attention.  NOTE the O(T) memory property is the FORWARD's:
# the recompute backward still materializes the (B,H,T,T) score matrix
# under XLA autodiff, so training peak memory stays O(T^2) per layer
# until a backward kernel lands (long-context training shards T via
# parallel/ring_attention.py instead).  The model zoo's flash branches
# call this entry point; inference-only code may call flash_attention.
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_grad(q, k, v, causal: bool = True, block_q: int = 128,
                         block_k: int = 128,
                         interpret: Optional[bool] = None):
    """Differentiable flash attention: (B, T, H, D) -> (B, T, H, D).

    Forward runs the Pallas kernel (or its documented fallbacks);
    backward recomputes through ``reference_attention`` under XLA
    autodiff — same numerics contract, no score matrix saved between
    passes."""
    return flash_attention(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )


def _fa_fwd(q, k, v, causal, block_q, block_k, interpret):
    out = flash_attention(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return out, (q, k, v)


def _fa_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v = res

    def ref(q_, k_, v_):
        # f32 score accumulation + f32 softmax, matching the kernel's
        # forward numerics — a bf16 recompute would round the softmax
        # row-sums and skew gradients ~2% at T=128 (growing with T)
        out, _ = reference_attention_lse(q_, k_, v_, causal=causal)
        return out.astype(q_.dtype)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


flash_attention_grad.defvjp(_fa_fwd, _fa_bwd)
