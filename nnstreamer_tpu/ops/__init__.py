"""TPU compute kernels (the "ops" layer of the round-1 package plan).

The reference accelerates hot elementwise paths with ORC SIMD
(``gsttensor_transform.c`` orc_typecast macros :463-533) and leaves NMS /
argmax post-processing to C loops in the decoders.  The TPU equivalents
live here: Pallas kernels for the fused elementwise hot paths (VMEM-tiled,
VPU-friendly) and jit/lax implementations for control-flow-heavy ops
(batched NMS) — everything falls back to a pure jax.numpy path off-TPU.
"""

from .flash_attention import flash_attention  # noqa: F401
from .labeling import top1  # noqa: F401
from .nms import batched_nms  # noqa: F401
from .preprocess import normalize_u8  # noqa: F401
