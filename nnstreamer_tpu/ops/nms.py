"""Batched non-maximum suppression on device (lax control flow).

≙ the C NMS loops in ``tensordec-boundingbox.c`` (``nms`` per frame on
host).  Control-flow heavy, so this is a jit/lax implementation (static
shapes, fori_loop) rather than Pallas: XLA schedules it fine, and the win
is running NMS for a whole micro-batch in one device call instead of N
Python loops.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _iou_matrix(boxes):
    """boxes (N,4) x1,y1,x2,y2 -> pairwise IoU (N,N)."""
    area = jnp.maximum(boxes[:, 2] - boxes[:, 0], 0) * jnp.maximum(
        boxes[:, 3] - boxes[:, 1], 0
    )
    x1 = jnp.maximum(boxes[:, None, 0], boxes[None, :, 0])
    y1 = jnp.maximum(boxes[:, None, 1], boxes[None, :, 1])
    x2 = jnp.minimum(boxes[:, None, 2], boxes[None, :, 2])
    y2 = jnp.minimum(boxes[:, None, 3], boxes[None, :, 3])
    inter = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    union = area[:, None] + area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@functools.partial(jax.jit, static_argnames=("iou_thr",))
def _nms_one(boxes, scores, iou_thr: float):
    """Greedy NMS, static shapes: returns keep mask (N,) bool."""
    N = boxes.shape[0]
    iou = _iou_matrix(boxes)
    order = jnp.argsort(-scores)

    def body(i, state):
        keep, suppressed = state
        cand = order[i]
        ok = ~suppressed[cand]
        keep = keep.at[cand].set(ok)
        # suppress everything the candidate overlaps (only if kept)
        sup = ok & (iou[cand] > iou_thr)
        suppressed = suppressed | (sup & (jnp.arange(N) != cand))
        return keep, suppressed

    keep, _ = jax.lax.fori_loop(
        0, N, body,
        (jnp.zeros(N, bool), jnp.zeros(N, bool)),
    )
    return keep


def batched_nms(boxes, scores, iou_thr: float = 0.45):
    """boxes (B,N,4) or (N,4), scores (B,N) or (N,) -> bool keep mask of the
    same leading shape.  Scores <= 0 are never kept (use as a validity
    mask for padded candidates)."""
    boxes = jnp.asarray(boxes)
    scores = jnp.asarray(scores)
    single = boxes.ndim == 2
    if single:
        boxes, scores = boxes[None], scores[None]
    keep = jax.vmap(lambda b, s: _nms_one(b, s, iou_thr))(boxes, scores)
    keep = keep & (scores > 0)
    return keep[0] if single else keep
