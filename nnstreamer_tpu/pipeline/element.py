"""Element model: the composable unit of a pipeline.

Reference analog: GStreamer GstElement/GstPad conventions as used by the
nnstreamer elements (``gst/nnstreamer/elements/``, registered in
``gst/nnstreamer/registerer/nnstreamer.c:91-122``):

* properties — the reference's entire user API is stringly-typed GObject
  properties embedded in pipeline text; here each Element declares a
  ``PROPERTIES`` table (name -> Property) and values are set/parsed the same
  way from pipeline descriptions.
* pads & negotiation — elements declare how many sink/src pads they expose
  and negotiate schemas by intersection (``accept_spec`` / ``derive_spec``),
  the analog of caps negotiation (fixed at PLAYING transition, reference
  ``tensor_filter.c:1157-1314``).
* processing — 1:1/1:N elements implement ``handle_frame``; N:1 elements get
  a time-sync :class:`~nnstreamer_tpu.core.sync.Collator`; sources implement
  ``frames()``; sinks ``render()``.

TPU-first: elements never copy payloads; they pass numpy/jax arrays through
and are encouraged to express compute as jit-able functions so chains fuse.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple, Union

from ..core.buffer import EOS, CapsEvent, CustomEvent, Event, Flush, TensorFrame
from ..core.liveness import _check_stall_policy
from ..core.log import get_logger
from ..core.types import ANY, StreamSpec


# ---------------------------------------------------------------------------
# Property system (≙ GObject properties)
# ---------------------------------------------------------------------------
# properties every element answers, merged under each class's declared
# PROPERTIES (a class declaring its own wins) — ≙ the reference's
# near-universal GObject props (silent on ~every element)
COMMON_PROPERTIES: Dict[str, "Property"] = {}  # filled after Property def


@dataclass
class Property:
    """Declared element property: type-checked, string-parsable."""

    type: type = str
    default: Any = None
    doc: str = ""
    # optional validator/transformer applied after type conversion
    convert: Optional[Callable[[Any], Any]] = None

    def parse(self, value: Any) -> Any:
        if isinstance(value, str) and self.type is not str:
            if self.type is bool:
                value = value.strip().lower() in ("1", "true", "yes", "on")
            elif self.type in (int, float):
                value = self.type(value)
            elif self.type in (list, tuple):
                value = self.type(
                    s.strip() for s in value.split(",") if s.strip() != ""
                )
        if self.type is not None and value is not None and not isinstance(value, self.type):
            try:
                value = self.type(value)
            except Exception:
                raise ValueError(f"cannot convert {value!r} to {self.type.__name__}")
        return self.convert(value) if self.convert else value


def enum_prop_check(prop: str, *choices: str):
    """Converter factory for enum-valued properties: eager validation so
    a typo fails at set time with a uniform message, not at first use."""
    def convert(v: str) -> str:
        if v not in choices:
            raise ValueError(f"{prop} {v!r} (want {' | '.join(choices)})")
        return v
    return convert


COMMON_PROPERTIES.update({
    # ≙ the reference's universal `silent` prop (e.g. gsttensor_rate.c
    # PROP_SILENT: "Don't produce verbose output"): false lowers this
    # element's logger to DEBUG so per-frame diagnostics stream out
    "silent": Property(bool, True, "false = verbose (debug-level) logging"),
    # supervision (core/resilience.py + the pipeline worker loop): what
    # the scheduler does when THIS element raises while processing a
    # frame.  Events (caps/EOS/flush) always fail-stop — losing one
    # desynchronizes the stream.  See Documentation/resilience.md.
    "error-policy": Property(
        str, "fail-stop",
        "on frame error: fail-stop (kill the pipeline, default) | skip "
        "(drop the poisoned frame to the dead-letter queue, warn on the "
        "bus) | restart (supervisor restarts the element with backoff, "
        "then retries the frame; degrades to fail-stop after "
        "max-restarts)",
        convert=enum_prop_check("error-policy", "fail-stop", "skip", "restart"),
    ),
    "max-restarts": Property(
        int, 3, "restart policy: restarts allowed (within restart-window) "
        "before degrading to fail-stop"),
    "restart-backoff": Property(
        float, 0.05, "restart policy: base backoff seconds (doubles per "
        "restart, capped at 2s)"),
    # always-on contract: a budget that never refills would guarantee
    # eventual degradation — N isolated glitches spread over weeks must
    # not kill the pipeline the way N back-to-back crash-loops should
    "restart-window": Property(
        float, 60.0, "restart policy: seconds of sustained health after "
        "which the restart budget (and backoff) fully refills; 0 = "
        "lifetime budget, never refills"),
    "dead-letter-max": Property(
        int, 16, "skip policy: poisoned frames retained for inspection "
        "(older ones roll off; 0 = count drops but retain nothing; the "
        "drop COUNTER is unbounded)"),
    # liveness (core/liveness.py + the pipeline watchdog): catches the
    # failures that never raise — a silent hang, a frame too late to
    # matter.  See Documentation/resilience.md "Liveness & overload".
    "frame-deadline": Property(
        float, 0.0, "watchdog: max seconds ONE frame call may run before "
        "an overrun is flagged (0 = disabled)"),
    "stall-timeout": Property(
        float, 0.0, "watchdog: seconds with input queued but no frame "
        "completed before a stall is flagged (0 = disabled)"),
    "stall-policy": Property(
        str, "warn",
        "on watchdog stall/overrun: warn (bus warning + health counter) "
        "| restart (interrupt the hung call cooperatively, then the "
        "restart machinery retries the frame) | fail (interrupt + tear "
        "the pipeline down)",
        convert=_check_stall_policy,
    ),
    "late-policy": Property(
        str, "drop",
        "frames carrying an expired deadline (core/liveness.py deadline "
        "QoS): drop (default — dropped before processing, with exact "
        "accounting in health()) | deliver (process regardless)",
        convert=enum_prop_check("late-policy", "drop", "deliver"),
    ),
    # deadline stamping (sources only; ignored elsewhere): every emitted
    # frame gets a latency budget that downstream elements honor
    "deadline-s": Property(
        float, 0.0, "sources: stamp each emitted frame with this latency "
        "budget, seconds (0 = no deadline)"),
    "deadline-anchor": Property(
        str, "arrival",
        "deadline-s anchoring: arrival (wall clock at emission — the "
        "serving contract) | pts (stream epoch + pts — live playback)",
        convert=enum_prop_check("deadline-anchor", "arrival", "pts"),
    ),
})


class ElementError(RuntimeError):
    pass


def parse_host_list(raw: str, owner: str, prop: str) -> List[Tuple[str, int]]:
    """Parse a 'h1:p1,h2:p2' property value into [(host, port), ...].

    Shared by every element exposing a multi-remote list (query client
    ``hosts``, edgesrc ``dest-hosts``) so the syntax and its errors
    cannot drift apart."""
    targets: List[Tuple[str, int]] = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        h, sep, p = part.rpartition(":")
        if not sep or not h or not p.isdigit():
            raise ElementError(
                f"{owner}: bad {prop} entry {part!r} (want host:port)")
        targets.append((h, int(p)))
    if not targets:
        raise ElementError(f"{owner}: {prop} parsed to nothing")
    return targets


# ---------------------------------------------------------------------------
# Element registry (≙ gst element factory names)
# ---------------------------------------------------------------------------
ELEMENT_TYPES: Dict[str, type] = {}


def element(name: str, *aliases: str):
    """Class decorator registering an element factory name."""

    def wrap(cls):
        cls.FACTORY_NAME = name
        for n in (name, *aliases):
            ELEMENT_TYPES[n] = cls
        return cls

    return wrap


def make_element(factory: str, name: Optional[str] = None, **props) -> "Element":
    if factory not in ELEMENT_TYPES:
        raise ElementError(f"no such element factory {factory!r}")
    el = ELEMENT_TYPES[factory](name=name)
    for k, v in props.items():
        el.set_property(k, v)
    return el


# ---------------------------------------------------------------------------
# Pads & links
# ---------------------------------------------------------------------------
class SrcPad:
    """An output pad; delivers items to linked sink pads (fan-out copies ≙ tee)."""

    def __init__(self, owner: "Element", index: int):
        self.owner = owner
        self.index = index
        self.links: List[Tuple["Element", int]] = []
        self.spec: Optional[StreamSpec] = None

    def link(self, sink_element: "Element", sink_pad: int = 0) -> None:
        self.links.append((sink_element, sink_pad))

    def push(self, item: Union[TensorFrame, Event]) -> None:
        for el, pad in self.links:
            el.deliver(pad, item)

    @property
    def is_linked(self) -> bool:
        return bool(self.links)


# ---------------------------------------------------------------------------
# Base element
# ---------------------------------------------------------------------------
class Element:
    """Base pipeline element.

    Subclass contract:
      * class attrs ``NUM_SINK_PADS`` / ``NUM_SRC_PADS`` (``None`` = dynamic,
        request pads created on link).
      * ``PROPERTIES``: dict of declared properties.
      * override ``accept_spec`` (validate/intersect incoming schema per pad),
        ``derive_spec`` (compute output schema), ``handle_frame``,
        ``handle_event``, ``start``/``stop`` as needed.
    """

    #: a BatchFrame (N logical frames, one stream item) reaches this
    #: element whole ONLY when True; otherwise the scheduler splits it
    #: into per-frame calls first.  Opt in when the element either
    #: consumes the batch axis (tensor_filter) or is batch-transparent
    #: (queue/tee/capsfilter) or splits blocks itself (tensor_sink).
    BATCH_AWARE = False

    #: streaming-thread fusion opt-OUT (upstream side): True means this
    #: element never fuses INTO its upstream's thread — it keeps its own
    #: worker and mailbox (and, GStreamer-style, drives its fused
    #: downstream from there).  Set it when the element's semantics NEED
    #: the mailbox: `queue` (the explicit boundary element) and the query
    #: client (which wakes its own worker through it).
    THREAD_BOUNDARY = False

    #: streaming-thread fusion opt-OUT (downstream side): False means
    #: downstream elements never run inline on THIS element's thread.
    #: Set False when the pipeline parallelism below this element is
    #: load-bearing (`tensor_query_serversrc`: admission control's
    #: in-flight window only fills when pull and processing overlap).
    FUSE_DOWNSTREAM = True

    FACTORY_NAME = "element"
    NUM_SINK_PADS: Optional[int] = 1
    NUM_SRC_PADS: Optional[int] = 1
    PROPERTIES: Dict[str, Property] = {}

    def __init__(self, name: Optional[str] = None):
        self.name = name or f"{self.FACTORY_NAME}{id(self) & 0xFFFF}"
        self.log = get_logger(self.name)
        self.props: Dict[str, Any] = {
            **{k: p.default for k, p in COMMON_PROPERTIES.items()},
            **{k: p.default for k, p in self.PROPERTIES.items()},
        }
        # keys set explicitly (pipeline text / API) — lets config-file
        # style bulk application defer to explicit settings
        self._explicit_props: set = set()
        nsrc = self.NUM_SRC_PADS if self.NUM_SRC_PADS is not None else 0
        self.srcpads: List[SrcPad] = [SrcPad(self, i) for i in range(nsrc)]
        self.sink_specs: Dict[int, StreamSpec] = {}
        self._pipeline = None  # set by Pipeline.add
        self._mailbox = None  # set by Pipeline at start for elements w/ sinks
        # liveness: set by the watchdog to cooperatively interrupt a hung
        # call (see `interrupted`); cleared when the stall is handled
        self._interrupted = threading.Event()

    # -- properties ---------------------------------------------------------
    def set_property(self, key: str, value: Any) -> None:
        key = key.replace("_", "-")
        decl = self.PROPERTIES.get(key) or COMMON_PROPERTIES.get(key)
        if decl is None:
            raise ElementError(f"{self.name}: unknown property {key!r}")
        self.props[key] = decl.parse(value)
        self._explicit_props.add(key)
        if key == "silent":
            import logging

            self.log.setLevel(
                logging.NOTSET if self.props[key] else logging.DEBUG
            )

    def get_property(self, key: str) -> Any:
        key = key.replace("_", "-")
        if key not in self.props:
            raise ElementError(f"{self.name}: unknown property {key!r}")
        return self.props[key]

    def _apply_config_file(self) -> None:
        """≙ the reference's filter/decoder `config-file` prop: key=value
        lines become properties; properties set explicitly in the
        pipeline text win.  Elements that declare the prop call this at
        the top of start()."""
        path = self.props.get("config-file", "")
        if not path:
            return
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError as e:
            raise ElementError(f"{self.name}: config-file: {e}") from None
        for ln, raw in enumerate(lines, 1):
            line = raw.strip()
            # comment lines only — an inline '#' may be part of a value
            # (custom=color:#ff0000, paths), so never truncate mid-line
            if not line or line.startswith("#"):
                continue
            key, sep, value = line.partition("=")
            if not sep:
                raise ElementError(
                    f"{self.name}: config-file {path}:{ln}: expected "
                    f"key=value, got {raw!r}"
                )
            key = key.strip().replace("_", "-")
            if key == "config-file":
                raise ElementError(
                    f"{self.name}: config-file {path}:{ln}: nested "
                    "config-file not allowed"
                )
            if key in self._explicit_props:
                continue
            try:
                self.set_property(key, value.strip())
            except (ElementError, ValueError) as e:
                raise ElementError(
                    f"{self.name}: config-file {path}:{ln}: {e}"
                ) from None
            self._explicit_props.discard(key)  # config values stay soft

    # -- pads ---------------------------------------------------------------
    def request_src_pad(self) -> SrcPad:
        """Create a new src pad (dynamic-src elements: demux/split/tee)."""
        pad = SrcPad(self, len(self.srcpads))
        self.srcpads.append(pad)
        return pad

    def srcpad(self, i: int = 0) -> SrcPad:
        if self.NUM_SRC_PADS is None:
            while len(self.srcpads) <= i:
                self.request_src_pad()
        return self.srcpads[i]

    def link(self, downstream: "Element", src_pad: int = 0, sink_pad: Optional[int] = None) -> "Element":
        """Link this element's src pad to downstream's sink pad; returns
        downstream for chaining: ``a.link(b).link(c)``."""
        if sink_pad is None:
            sink_pad = downstream.next_sink_pad()
        elif downstream.NUM_SINK_PADS is None:
            # explicit pad index on a request-pad element (pbtxt links):
            # keep the allocation counter consistent so num_sink_pads is right
            downstream._next_sink = max(downstream._next_sink, sink_pad + 1)
        self.srcpad(src_pad).link(downstream, sink_pad)
        return downstream

    _next_sink = 0

    def next_sink_pad(self) -> int:
        """Allocate the next sink pad index (N:1 request pads)."""
        if self.NUM_SINK_PADS == 1:
            return 0
        i = self._next_sink
        self._next_sink += 1
        return i

    @property
    def num_sink_pads(self) -> int:
        if self.NUM_SINK_PADS is not None:
            return self.NUM_SINK_PADS
        return max(self._next_sink, 1)

    # -- delivery (called from upstream worker threads) ---------------------
    def deliver(self, pad: int, item: Union[TensorFrame, Event]) -> None:
        assert self._mailbox is not None, f"{self.name} not scheduled"
        put_frame = getattr(self._mailbox, "put_frame", None)
        if put_frame is not None and isinstance(item, TensorFrame):
            put_frame((pad, item))  # leaky mailbox: drop, never block
            return
        # blocking backpressure semantics, expressed as a bounded-wait
        # retry loop so a leaky mailbox (which forbids timeout=None)
        # behaves the same as queue.Queue here; never raises queue.Full
        import queue as _queue

        while True:
            try:
                self._mailbox.put((pad, item), timeout=0.5)
                return
            except _queue.Full:
                continue

    # -- negotiation --------------------------------------------------------
    def accept_spec(self, pad: int, spec: StreamSpec) -> StreamSpec:
        """Validate/refine the incoming schema on `pad`.

        Raise ElementError to reject (negotiation failure)."""
        return spec

    def derive_spec(self, pad: int = 0) -> StreamSpec:
        """Output schema for src pad `pad`, given ``self.sink_specs``."""
        return self.sink_specs.get(0, ANY)

    def set_sink_spec(self, pad: int, spec: StreamSpec) -> None:
        self.sink_specs[pad] = self.accept_spec(pad, spec)

    # -- liveness -----------------------------------------------------------
    @property
    def interrupted(self) -> bool:
        """True when the watchdog (stall-policy escalation) or pipeline
        stop wants this element's current call to give up NOW.

        The cooperative-interruption contract: element code doing long
        waits or chunked work should poll this between steps and raise
        :class:`~nnstreamer_tpu.core.liveness.StallError` (or simply
        return) when set — a hung Python call cannot be killed from
        outside, so liveness restart/fail escalation only works for
        calls that cooperate.  Injected ``hang=`` faults poll it."""
        if self._interrupted.is_set():
            return True
        p = self._pipeline
        return p is not None and p._stop_flag.is_set()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        """Transition to running (open models, allocate state)."""

    def stop(self) -> None:
        """Release resources."""

    # -- processing ---------------------------------------------------------
    def handle_frame(
        self, pad: int, frame: TensorFrame
    ) -> Iterable[Tuple[int, TensorFrame]]:
        """Process one frame from sink pad `pad`; yield (src_pad, frame)."""
        return [(0, frame)]

    def handle_event(self, pad: int, event: Event) -> Iterable[Tuple[int, Event]]:
        """Process an in-band event; default: forward to all src pads once
        (EOS aggregation across sink pads is handled by the scheduler)."""
        return [(i, event) for i in range(len(self.srcpads))]

    def __repr__(self):
        return f"<{type(self).__name__} {self.name!r}>"


class SourceElement(Element):
    """Element with no sink pads; produces frames from ``frames()``."""

    NUM_SINK_PADS = 0

    def frames(self) -> Iterator[TensorFrame]:
        raise NotImplementedError

    def output_spec(self) -> StreamSpec:
        """Schema this source produces (sent as CapsEvent before data)."""
        return ANY


class SinkElement(Element):
    """Element with no src pads; consumes frames via ``render()``."""

    NUM_SRC_PADS = 0
    # non-aware sinks receive logical frames (the scheduler splits blocks)

    def render(self, frame: TensorFrame) -> None:
        raise NotImplementedError

    def handle_frame(self, pad, frame):
        self.render(frame)
        return []


class TransformElement(Element):
    """1:1 element transforming each frame (≙ GstBaseTransform)."""

    def transform(self, frame: TensorFrame) -> Optional[TensorFrame]:
        raise NotImplementedError

    def handle_frame(self, pad, frame):
        out = self.transform(frame)
        return [] if out is None else [(0, out)]
