"""Textual pipeline descriptions (gst-launch dialect).

The reference's de-facto CLI is ``gst-launch-1.0`` pipeline text (SURVEY §1
L6); keeping the same dialect lets reference examples map 1:1::

    videotestsrc num-buffers=8 ! tensor_converter !
      tensor_filter framework=jax-xla model=m.msgpack !
      tensor_decoder mode=image_labeling option1=labels.txt ! tensor_sink name=out

Supported subset:
  * ``!`` links elements left to right.
  * ``key=value`` tokens set properties on the preceding element
    (``name=x`` registers the element under a pipeline-wide name).
  * ``x.`` starts a new chain from the named element ``x`` (tee branches,
    mux inputs): ``tee name=t  t. ! a  t. ! b`` and ``a ! m.  b ! m.``.
  * a bare schema string (``tensors,format=...``) becomes a capsfilter.
  * quotes protect spaces in values.

Reference grammar analog: ``tools/development/parser/{parse.l,grammar.y}``.
"""

from __future__ import annotations

import shlex
from typing import Dict, List, Optional

from ..pipeline.element import Element, ElementError, make_element, ELEMENT_TYPES
from ..pipeline.pipeline import Pipeline

# elements register themselves on import (≙ plugin registration,
# reference gst/nnstreamer/registerer/nnstreamer.c:91-122)
from .. import elements as _elements  # noqa: F401


def _is_caps(token: str) -> bool:
    head = token.split(",", 1)[0]
    return head in ("tensors", "other/tensors") or head.startswith("other/")


class ParseError(ValueError):
    pass


def parse_pipeline(
    text: str, name: str = "pipeline", fuse: "bool | None" = None
) -> Pipeline:
    """Parse a pipeline description into an (unstarted) Pipeline.

    ``fuse`` controls streaming-thread fusion (None = the ``NNS_FUSE``
    env default, on): linear chains share one worker thread unless an
    explicit ``queue`` element inserts a boundary — GStreamer
    semantics; see Documentation/performance.md."""
    try:
        tokens = shlex.split(text.replace("\n", " "))
    except ValueError as e:
        raise ParseError(f"tokenize failed: {e}") from None
    if not tokens:
        raise ParseError("empty pipeline description")

    pipe = Pipeline(name, fuse=fuse)
    named: Dict[str, Element] = {}
    deferred: List[tuple] = []  # (src_element, target_name) forward links
    current: Optional[Element] = None
    pending_src: Optional[Element] = None
    link_requested = False
    caps_n = 0

    branch_counts: Dict[int, int] = {}  # id(element) -> src pads handed out

    def link_from(src: Element, dst: Element) -> None:
        # dynamic-src elements (tee/demux/split/if) get a fresh src pad per
        # textual branch ("t. ! ..." twice = pads 0 and 1)
        if src.NUM_SRC_PADS is None:
            idx = branch_counts.get(id(src), 0)
            branch_counts[id(src)] = idx + 1
            src.link(dst, src_pad=idx)
        else:
            src.link(dst)

    def new_node(el: Element) -> None:
        nonlocal current, pending_src, link_requested
        pipe.add(el)
        if link_requested:
            if pending_src is None:
                raise ParseError("dangling '!' with no upstream element")
            link_from(pending_src, el)
        pending_src = None
        link_requested = False
        current = el

    for tok in tokens:
        if tok == "!":
            if current is None:
                raise ParseError("'!' with no preceding element")
            pending_src = current
            link_requested = True
            continue
        if tok.endswith(".") and len(tok) > 1:
            ref = tok[:-1]
            if link_requested:
                # "a ! m." — link current chain INTO the named element; the
                # name may be defined later in the text (forward reference,
                # gst-launch allows it), so defer resolution.  The src pad is
                # claimed NOW so dynamic-src branch order follows the text,
                # not the resolution order.
                src_pad = None
                if pending_src.NUM_SRC_PADS is None:
                    src_pad = branch_counts.get(id(pending_src), 0)
                    branch_counts[id(pending_src)] = src_pad + 1
                deferred.append((pending_src, src_pad, ref))
                pending_src = None
                link_requested = False
                current = None
            else:
                # "t. ! a" — start a new chain FROM the named element
                if ref not in named:
                    raise ParseError(f"reference to unknown element {ref!r}")
                current = named[ref]
            continue
        if _is_caps(tok):
            caps_n += 1
            el = make_element("capsfilter", name=f"capsfilter{caps_n}", caps=tok)
            new_node(el)
            continue
        if "=" in tok and tok.split("=", 1)[0] not in ELEMENT_TYPES:
            if current is None:
                raise ParseError(f"property {tok!r} with no preceding element")
            key, value = tok.split("=", 1)
            if key == "name":
                # re-register under the user-visible name
                if value in named:
                    raise ParseError(f"duplicate element name {value!r}")
                del pipe.elements[current.name]
                current.name = value
                pipe.elements[value] = current
                named[value] = current
            else:
                current.set_property(key, value)
            continue
        # element factory
        try:
            el = make_element(tok)
        except ElementError as e:
            raise ParseError(str(e)) from None
        # ensure unique auto-name within the pipeline
        base = el.name
        n = 2
        while el.name in pipe.elements:
            el.name = f"{base}_{n}"
            n += 1
        new_node(el)

    if link_requested:
        raise ParseError("pipeline text ends with dangling '!'")
    for src_el, src_pad, ref in deferred:
        if ref not in named:
            raise ParseError(f"reference to unknown element {ref!r}")
        if src_pad is not None:
            src_el.link(named[ref], src_pad=src_pad)
        else:
            link_from(src_el, named[ref])
    return pipe


def launch(text: str, timeout: Optional[float] = None) -> Pipeline:
    """Parse + run to completion (≙ gst-launch): returns the finished pipeline."""
    pipe = parse_pipeline(text)
    pipe.run(timeout)
    return pipe
