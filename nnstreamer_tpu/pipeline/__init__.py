"""Pipeline runtime (L0/L3 skeleton): elements, threaded scheduler, parser."""

from .element import (  # noqa: F401
    Element,
    ElementError,
    Property,
    SinkElement,
    SourceElement,
    TransformElement,
    element,
    make_element,
    ELEMENT_TYPES,
)
from .pipeline import BusMessage, Pipeline  # noqa: F401
from .parser import ParseError, launch, parse_pipeline  # noqa: F401
