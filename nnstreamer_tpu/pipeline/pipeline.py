"""Pipeline scheduler: fused streaming threads with bounded queues.

Reference analog: GStreamer's execution model (L0 in SURVEY.md) — elements
run on streaming threads connected by pads, and a linear chain SHARES one
streaming thread unless an explicit ``queue`` element inserts a thread
boundary.  The scheduler fuses each maximal linear chain into one worker
(eliding the per-frame mailbox handoffs entirely — the per-buffer-overhead
bottleneck the NNStreamer papers attack with shared streaming threads);
branches, muxes, micro-batching elements, and explicit ``queue``s keep
their own threads and bounded mailboxes, so pipeline parallelism remains
available exactly where it pays, and a full mailbox blocks the upstream
thread — the backpressure analog.  ``Pipeline(fuse=False)`` (or
``NNS_FUSE=0``) restores the one-thread-per-element seed model.

Lifecycle ≙ NULL→PLAYING: ``start()`` negotiates schemas (CapsEvents flow
before data), spawns workers; ``stop()`` tears down; ``wait()`` joins until
EOS has reached every sink (≙ bus EOS message), re-raising element errors.

The bus carries out-of-band messages (errors, element custom messages like
training stats) to the application (≙ GstBus).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..core.buffer import (
    EOS,
    FRAME_POOL,
    BatchFrame,
    CapsEvent,
    Event,
    Flush,
    TensorFrame,
)
from ..core.liveness import DEADLINE_META, StallError, Watchdog, stamp_deadline
from ..core.log import get_logger
from ..core.resilience import FAULTS
from ..core.telemetry import TL_QPUT_META
from ..core.tracer import META_SRC_TS, PipelineTracer, frame_nbytes
from .element import Element, ElementError, SinkElement, SourceElement

_STOP = object()  # out-of-band worker shutdown sentinel


class _LeakyMailbox:
    """Bounded mailbox with GstQueue leaky semantics, all decisions taken
    atomically under one lock: a frame arriving at a full box either
    replaces the oldest queued FRAME (``downstream`` — events keep their
    exact position) or is itself discarded (``upstream``).  Events go
    through ``put``, which requires a bounded timeout (there is no
    stop-flag escape here); callers retry in a loop so events are never
    dropped or reordered."""

    def __init__(self, maxsize: int, policy: str):
        import collections

        self._dq = collections.deque()
        self._max = max(1, maxsize)
        self.policy = policy  # "upstream" | "downstream"
        self._mtx = threading.Lock()
        self._not_empty = threading.Condition(self._mtx)
        self._not_full = threading.Condition(self._mtx)

    def _put_frame_locked(self, item) -> None:
        """Leaky policy for ONE frame entry; caller holds the lock.  A
        frame arriving at a full box either evicts the oldest queued
        FRAME (``downstream`` — events keep their exact position) or is
        itself the loss (``upstream``); either way the frame is
        'consumed' without blocking."""
        if len(self._dq) >= self._max:
            if self.policy == "upstream":
                return  # live semantics: lose the newest frame
            # downstream: drop the oldest FRAME in place; if only
            # events are queued, the incoming frame is the loss
            for i, old in enumerate(self._dq):
                if isinstance(old[1], TensorFrame):
                    del self._dq[i]
                    break
            else:
                return
        self._dq.append(item)

    def put_frame(self, item) -> None:
        """Non-blocking frame delivery with the leaky policy."""
        with self._mtx:
            self._put_frame_locked(item)
            self._not_empty.notify()

    # -- queue.Queue-compatible subset (events, sentinel, worker get) ----
    def put(self, item, timeout: Optional[float] = None) -> None:
        # no stop-flag escape exists here, so an unbounded block on a full
        # box could hang shutdown; Pipeline._push loops with bounded waits
        if timeout is None:
            raise ValueError("_LeakyMailbox.put requires a bounded timeout")
        with self._mtx:
            if len(self._dq) >= self._max:
                self._not_full.wait_for(
                    lambda: len(self._dq) < self._max, timeout=timeout
                )
                if len(self._dq) >= self._max:
                    raise queue.Full
            self._dq.append(item)
            self._not_empty.notify()

    def put_nowait(self, item) -> None:
        self.put(item, timeout=0.0)

    def put_many(self, items, timeout: float = 0.0) -> int:
        """Block handoff: deliver a RUN of ``(pad, item)`` entries under ONE
        lock acquisition, applying the leaky policy per frame.  Frames never
        block (drop semantics); the run stops at the first EVENT that does
        not fit (events must block — the caller retries the remainder).
        Returns the number of leading items consumed."""
        n = 0
        with self._mtx:
            for entry in items:
                if isinstance(entry[1], TensorFrame):
                    self._put_frame_locked(entry)  # never blocks: drop policy
                    n += 1
                    continue
                # event: only append when space exists; otherwise stop the
                # run — the caller falls back to the blocking put loop
                if len(self._dq) >= self._max:
                    break
                self._dq.append(entry)
                n += 1
            if n:
                self._not_empty.notify()
        return n

    def get(self, timeout: Optional[float] = None):
        with self._mtx:
            if not self._dq:
                self._not_empty.wait_for(
                    lambda: bool(self._dq), timeout=timeout
                )
                if not self._dq:
                    raise queue.Empty
            item = self._dq.popleft()
            self._not_full.notify()
            return item

    def get_nowait(self):
        return self.get(timeout=0.0)

    def qsize(self) -> int:
        with self._mtx:
            return len(self._dq)

    @property
    def maxsize(self) -> int:
        return self._max


@dataclass
class BusMessage:
    """Out-of-band message to the application (≙ GstMessage)."""

    kind: str  # "error" | "eos" | "element" | "warning" | "health"
    source: str
    data: Any = None


@dataclass
class ElementHealth:
    """Supervision record for one element (see ``Pipeline.health()``).

    ``dead_letters`` counts every frame dropped under the ``skip``
    policy for the element's lifetime; ``dlq`` retains only the most
    recent ``dead-letter-max`` of them as ``(frame, error_repr)`` pairs
    for post-mortem inspection."""

    state: str = "idle"  # idle|running|restarting|degraded|failed|finished|stalled
    restarts: int = 0  # within the current restart-window (gates the budget)
    restarts_total: int = 0  # lifetime, for health reporting
    last_restart_ts: float = 0.0
    dead_letters: int = 0
    deadline_drops: int = 0  # frames expired before this element processed them
    last_error: str = ""
    dlq: deque = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.dlq is None:
            self.dlq = deque(maxlen=16)


class _ElemState:
    """Per-element dispatch state inside one streaming-thread worker.

    Exists for every element (fused or solo) so the dispatch loop touches
    precomputed locals instead of re-deriving graph facts per frame — part
    of the hot-path allocation diet."""

    __slots__ = (
        "el", "connected", "eos_pads", "caps_pads", "finished",
        "next_state", "next_pad", "out_pad", "watch",
        "terminal", "delivered", "in_call",
    )

    def __init__(self, el: Element):
        self.el = el
        self.connected: set = {0}
        self.eos_pads: set = set()
        self.caps_pads: set = set()
        self.finished = False
        # drain accounting: terminal elements (stream endpoints) count
        # the logical frames they consume — one int add per frame, only
        # at endpoints, single-writer per streaming thread (summed by
        # Pipeline.delivered_frames)
        self.terminal = False
        self.delivered = 0
        # logical frames consumed from a queue but not yet fully routed
        # (exact dropped accounting for a halt that lands mid-call)
        self.in_call = 0
        # in-segment routing: the fused downstream element (None = outputs
        # leave through mailboxes), the src pad carrying that link, and the
        # downstream sink pad it lands on
        self.next_state: Optional["_ElemState"] = None
        self.next_pad = 0
        self.out_pad = 0
        self.watch = None  # liveness watch, bound at worker start


class _Seg:
    """One streaming thread: a maximal fusable linear chain of elements.

    ``chain[0]`` is the head (a source, or the one element with a mailbox);
    every later element receives its input inline on the head's thread —
    GStreamer semantics: elements share a streaming thread unless an
    explicit ``queue`` boundary is inserted."""

    __slots__ = ("chain", "states", "stash")

    def __init__(self, chain: List[Element]):
        self.chain = chain
        self.states: Dict[str, _ElemState] = {}
        # items popped from the head mailbox but not yet processed (bulk
        # pops past a batch boundary); lives on the segment so halt-time
        # accounting (_count_abandoned) can see it
        self.stash: deque = deque()


def _env_fuse() -> bool:
    return os.environ.get("NNS_FUSE", "1").lower() not in ("0", "false", "no")


class Pipeline:
    """A running graph of elements."""

    def __init__(
        self,
        name: str = "pipeline",
        default_queue_size: int = 16,
        tracer=None,
        fuse: Optional[bool] = None,
    ):
        self.name = name
        self.log = get_logger(name)
        self.elements: Dict[str, Element] = {}
        self.default_queue_size = default_queue_size
        self._threads: List[threading.Thread] = []
        self._stop_flag = threading.Event()
        # graceful drain (core/lifecycle.py "Zero-downtime operations"):
        # set by drain() — sources stop producing and flush EOS so every
        # in-flight frame reaches the sinks before teardown
        self._drain_flag = threading.Event()
        self._started = False
        self.errors: List[BaseException] = []
        self._bus: "queue.Queue[BusMessage]" = queue.Queue()
        self._bus_watchers: List[Callable[[BusMessage], None]] = []
        self._sinks_done = threading.Event()
        self._pending_sinks = 0
        self._sink_lock = threading.Lock()
        # supervision: per-element health records (error-policy support)
        self.health_map: Dict[str, ElementHealth] = {}
        # liveness (core/liveness.py): built at start() iff any element
        # arms stall-timeout/frame-deadline; the sweeper thread polls it
        self._watchdog: Optional[Watchdog] = None
        self._watches: Dict[str, Any] = {}
        self._wd_thread: Optional[threading.Thread] = None
        self._upstream: Dict[str, List[Element]] = {}  # QoS feedback routing
        self._qos_warn_ts: Dict[str, float] = {}  # per-element warn throttle
        # GstShark-analog tracing (core/tracer.py): None = zero-overhead off
        self.tracer = tracer
        # fleet telemetry (core/telemetry.py): the registry collector is
        # registered at start() and the exposition endpoint is opened by
        # serve_metrics() / NNS_METRICS_PORT; the flight recorder rides
        # the tracer so the disabled hot path stays one branch per frame
        self._recorder = None
        self._metrics_server = None
        self._collector_registered = False
        # memory-pressure watermark monitor (core/liveness.py): polled
        # on the watchdog-sweeper cadence; None = zero cost everywhere
        self._mem_monitor = None
        # generic sweeper hooks (fn, min_poll_s): slow-cadence pollers
        # elements register at start() (the serversrc's telemetry-digest
        # publisher) — called from the watchdog sweeper thread, NEVER on
        # a per-frame path; hooks rate-limit internally
        self._sweep_hooks: List[Tuple[Callable[[], Any], float]] = []
        # registry label: claimed lazily (names default to "pipeline", so
        # the label must be unique among LIVE pipelines or one stop()
        # would evict a concurrent namesake's instruments)
        self._telemetry_label: Optional[str] = None
        # streaming-thread fusion (GStreamer semantics): linear chains share
        # one worker unless a boundary (queue / batcher / branch) intervenes
        self._fuse = _env_fuse() if fuse is None else bool(fuse)
        self._segments: List[_Seg] = []
        self._seg_of: Dict[str, _Seg] = {}

    def to_dot(self) -> str:
        """Graphviz DOT of the element graph (≙ GStreamer's
        GST_DEBUG_DUMP_DOT_DIR pipeline dumps): one node per element
        (shape by role), one edge per pad link, negotiated schemas as
        edge labels when known."""
        def esc(s: str) -> str:  # DOT quoted strings: no raw double quotes
            return str(s).replace('"', "'")

        lines = [
            "digraph pipeline {",
            "  rankdir=LR;",
            "  node [fontsize=10 shape=box style=rounded];",
        ]
        for el in self.elements.values():
            kind = type(el).__name__
            shape = (
                "invhouse" if isinstance(el, SourceElement)
                else "house" if isinstance(el, SinkElement)
                else "box"
            )
            lines.append(
                f'  "{esc(el.name)}" '
                f'[label="{esc(el.name)}\\n({kind})" shape={shape}];'
            )
        for el in self.elements.values():
            for sp_i, sp in enumerate(el.srcpads):
                for dst, sink_pad in sp.links:
                    spec = dst.sink_specs.get(sink_pad)
                    label = (
                        esc(spec.to_string())
                        if spec is not None and getattr(spec, "tensors", None)
                        else ""
                    )
                    lines.append(
                        f'  "{esc(el.name)}" -> "{esc(dst.name)}" '
                        f'[taillabel="{sp_i}" headlabel="{sink_pad}" '
                        f'label="{label}" fontsize=8];'
                    )
        lines.append("}")
        return "\n".join(lines)

    def enable_tracing(self, detail: bool = False) -> PipelineTracer:
        """Attach a fresh PipelineTracer (before start()); returns it.
        ``detail=True`` also records per-call spans for
        ``export_chrome_trace``."""
        recorder = self.tracer.recorder if self.tracer is not None else None
        self.tracer = PipelineTracer(detail=detail, recorder=recorder)
        return self.tracer

    # -- fleet telemetry (core/telemetry.py) ---------------------------------
    def enable_flight_recorder(self, capacity: int = 4096,
                               dump_dir: Optional[str] = None,
                               min_dump_interval_s: float = 5.0,
                               profile_incidents: bool = True,
                               profile_duration_s: float = 0.2):
        """Attach a flight recorder: a bounded ring of recent per-frame
        span timelines, dumped automatically (rate-limited, to log + a
        JSON file) on watchdog stall, dead-letter, swap rollback, or
        breaker trip.  Rides the tracer (one is attached if absent), so
        pipelines without it keep the one-branch-per-frame disabled
        path.  ``profile_incidents`` (default on) additionally attaches
        an incident-time thread profile — collapsed top-stacks of the
        named framework threads over a ``profile_duration_s`` sampling
        window — to every dump (core/profiler.py).  Returns the
        recorder."""
        from ..core.telemetry import FlightRecorder

        if self.tracer is None:
            self.enable_tracing()
        self._recorder = FlightRecorder(
            capacity=capacity, dump_dir=dump_dir,
            min_dump_interval_s=min_dump_interval_s,
            profile_incidents=profile_incidents,
            profile_duration_s=profile_duration_s,
        )
        self.tracer.recorder = self._recorder
        return self._recorder

    @property
    def flight_recorder(self):
        return self._recorder

    # -- memory-pressure watermarks (core/liveness.py) -----------------------
    def enable_memory_monitor(self, high: float = 0.90, low: float = 0.75,
                              sustain_s: float = 2.0,
                              host_limit_bytes: int = 0,
                              sample=None, clock=None,
                              min_poll_s: float = 0.25):
        """Attach a :class:`~..core.liveness.MemoryPressureMonitor`:
        device HBM (and host RSS) watermarks polled on the watchdog-
        sweeper cadence — NEVER on a per-frame path.  Crossing the high
        watermark trims the process frame/staging pools and every
        owned filter backend's compiled-program cache; pressure
        sustained for ``sustain_s`` fires a rate-limited
        ``memory_pressure`` flight-recorder incident (with the
        incident-time thread profiler attached when the recorder has
        one); a query serversrc on this pipeline couples the monitor
        into admission, shedding BUSY *before* the chip OOMs.  Returns
        the monitor (``sample``/``clock`` injectable for tests)."""
        from ..core.buffer import DEVICE_POOL, FRAME_POOL
        from ..core.liveness import MemoryPressureMonitor

        def trim_prefixes() -> int:
            # cold shared-prefix entries are the cheapest HBM to give
            # back (refcounted pages under live readers are never
            # touched) — so they go FIRST on the trim ladder, before
            # frame/staging pools and compiled-program caches.
            freed = 0
            for el in self.elements.values():
                trim = getattr(el, "trim_prefix_cache", None)
                if trim is not None:
                    try:
                        freed += int(trim() or 0)
                    except Exception:
                        self.log.exception(
                            "trim_prefix_cache failed for %s", el.name)
            return freed

        def trim_backends() -> int:
            freed = 0
            for el in self.elements.values():
                be = getattr(el, "backend", None)
                trim = getattr(be, "trim_caches", None)
                if trim is not None:
                    try:
                        freed += int(trim() or 0)
                    except Exception:
                        self.log.exception(
                            "trim_caches failed for %s", el.name)
            return freed

        kwargs = {}
        if sample is not None:
            kwargs["sample"] = sample
        if clock is not None:
            kwargs["clock"] = clock
        mon = MemoryPressureMonitor(
            high=high, low=low, sustain_s=sustain_s,
            min_poll_s=min_poll_s, host_limit_bytes=host_limit_bytes,
            on_pressure=lambda snap: self.incident(
                "memory_pressure", self.name, snap),
            trim_hooks=(trim_prefixes, FRAME_POOL.trim, DEVICE_POOL.trim,
                        trim_backends),
            **kwargs,
        )
        self._mem_monitor = mon
        if self._started and (self._wd_thread is None
                              or not self._wd_thread.is_alive()):
            # armed mid-run with no sweeper: start one for the monitor
            self._wd_thread = threading.Thread(
                target=self._watchdog_loop, args=(mon.min_poll_s,),
                name=f"{self.name}-watchdog", daemon=True,
            )
            self._wd_thread.start()
        return mon

    @property
    def memory_monitor(self):
        return self._mem_monitor

    # -- degraded-capacity feedback (device loss) ----------------------------
    def degraded_feedback(self, source: str, detail: str = "") -> None:
        """An element of THIS pipeline lost a device and re-sharded onto
        survivors: tell every element exposing ``note_degraded`` (the
        query serversrc) so the discovery plane announces
        ``degraded:true`` and fleet routing deprioritizes this server
        ahead of its next failure.  Also posted on the bus."""
        self.post(BusMessage("warning", source, {"degraded": detail}))
        for el in self.elements.values():
            note = getattr(el, "note_degraded", None)
            if note is None:
                continue
            try:
                note(detail)
            except Exception:
                self.log.exception("note_degraded failed for %s", el.name)

    def incident(self, kind: str, source: str, detail: Any = None
                 ) -> Optional[str]:
        """Incident hook (watchdog stall / dead-letter / swap rollback /
        breaker trip land here): dump the flight recorder, post the dump
        path on the bus.  No-op without a recorder; rate-limited by the
        recorder itself.  Returns the dump path, if one was written."""
        rec = self._recorder
        if rec is None:
            return None
        path = rec.dump(kind, source, detail, logger=self.log)
        if path is not None:
            self.post(BusMessage("warning", source, {
                "incident": kind, "flight_dump": path,
            }))
        return path

    @property
    def telemetry_label(self) -> str:
        """The ``pipeline=`` label this pipeline's registry series carry:
        the name when it is unique among live pipelines, else
        ``name#N``.  Claimed at start(), released at stop(); a pipeline
        that is not running reads as its bare name WITHOUT claiming — a
        scrape must never be the claimant (a registry scrape racing
        stop(), or walking the collector of a pipeline a sloppy caller
        abandoned, would otherwise hold the label forever)."""
        return self._telemetry_label or self.name

    def metrics_snapshot(self):
        """Pollable telemetry snapshot of THIS pipeline: every signal
        source under its stable dotted name (see
        Documentation/observability.md).  Cheap enough to poll."""
        from ..core.telemetry import (
            REGISTRY,
            TelemetrySnapshot,
            collect_pipeline,
        )

        return TelemetrySnapshot(
            collect_pipeline(self)
            + REGISTRY.collect_labeled(pipeline=self.telemetry_label)
        )

    def telemetry_summary(self) -> Dict[str, float]:
        """Compact {metric_name: value} dump (counters summed across
        elements, gauges maxed) — the labeled snapshot bench.py attaches
        to each evidence row."""
        return self.metrics_snapshot().flat()

    def serve_metrics(self, port: int = 0, host: str = "127.0.0.1") -> int:
        """Open the Prometheus text exposition endpoint (process-wide
        registry — every running pipeline's series, labeled).  Returns
        the bound port; ``stop()`` shuts the endpoint down.  Also armed
        by ``NNS_METRICS_PORT`` at start()."""
        from ..core.telemetry import MetricsServer

        if self._metrics_server is not None:
            return self._metrics_server.port
        self._metrics_server = MetricsServer(
            port=port, host=host, name=self.name)
        return self._metrics_server.port

    @property
    def metrics_port(self) -> Optional[int]:
        srv = self._metrics_server
        return srv.port if srv is not None else None

    def _register_telemetry(self) -> None:
        from ..core.telemetry import REGISTRY, collect_pipeline

        if not self._collector_registered:
            self._collector = lambda: collect_pipeline(self)
            REGISTRY.register_collector(self._collector)
            self._collector_registered = True
        env_port = os.environ.get("NNS_METRICS_PORT", "")
        if env_port and self._metrics_server is None:
            try:
                self.serve_metrics(int(env_port))
            except (OSError, ValueError) as e:
                # another pipeline already owns the port (its endpoint
                # serves the shared registry, so nothing is lost)
                self.log.info(
                    "NNS_METRICS_PORT=%s not bound by this pipeline: %s",
                    env_port, e)

    def _unregister_telemetry(self) -> None:
        from ..core.telemetry import REGISTRY, release_pipeline_label

        if self._collector_registered:
            REGISTRY.unregister_collector(self._collector)
            self._collector_registered = False
        if self._telemetry_label is not None:
            REGISTRY.remove_labeled(pipeline=self._telemetry_label)
            release_pipeline_label(self._telemetry_label)
            self._telemetry_label = None
        if self._metrics_server is not None:
            self._metrics_server.close()
            self._metrics_server = None

    # -- construction -------------------------------------------------------
    def add(self, *elements: Element) -> Element:
        for el in elements:
            if el.name in self.elements and self.elements[el.name] is not el:
                raise ElementError(f"duplicate element name {el.name!r}")
            self.elements[el.name] = el
            el._pipeline = self
        return elements[-1]

    def chain(self, *elements: Element) -> Element:
        """add + link a linear chain; returns the last element."""
        self.add(*elements)
        for a, b in zip(elements, elements[1:]):
            a.link(b)
        return elements[-1]

    def __getitem__(self, name: str) -> Element:
        return self.elements[name]

    # -- bus ----------------------------------------------------------------
    def post(self, msg: BusMessage) -> None:
        self._bus.put(msg)
        for cb in list(self._bus_watchers):
            try:
                cb(msg)
            except Exception:  # watcher bugs must not kill workers
                self.log.exception("bus watcher failed")

    def add_bus_watcher(self, cb: Callable[[BusMessage], None]) -> None:
        self._bus_watchers.append(cb)

    def pop_message(self, timeout: Optional[float] = 0) -> Optional[BusMessage]:
        try:
            return self._bus.get(timeout=timeout) if timeout else self._bus.get_nowait()
        except queue.Empty:
            return None

    # -- schema negotiation (static pass, ≙ initial caps negotiation) -------
    def _negotiate(self) -> None:
        """Propagate output schemas topologically and let each element
        validate via accept_spec.  Dynamic/renegotiation still happens via
        in-band CapsEvents at runtime; this pass fails fast at start()."""
        in_degree: Dict[str, int] = {n: 0 for n in self.elements}
        for el in self.elements.values():
            for pad in el.srcpads:
                for dst, _ in pad.links:
                    in_degree[dst.name] += 1
        ready = [self.elements[n] for n, d in in_degree.items() if d == 0]
        seen = 0
        while ready:
            el = ready.pop()
            seen += 1
            if isinstance(el, SourceElement):
                for pad in el.srcpads:
                    pad.spec = el.output_spec()
            else:
                for i, pad in enumerate(el.srcpads):
                    pad.spec = el.derive_spec(i)
            for pad in el.srcpads:
                for dst, sink_pad in pad.links:
                    if pad.spec is not None:
                        dst.set_sink_spec(sink_pad, pad.spec)
                    in_degree[dst.name] -= 1
                    if in_degree[dst.name] == 0:
                        ready.append(dst)
        if seen != len(self.elements):
            # cycles are legal only through repo src/sink (out-of-band), which
            # do not create graph edges — anything else is a bug.
            raise ElementError("pipeline graph has a cycle through pad links")

    # -- device fusion pass (no reference analog; SURVEY §7 design stance:
    # "compile element graphs down to as few XLA programs as possible") ----
    def _fuse_device_chains(self) -> None:
        """Fold fusable decoder device halves into their upstream jax-xla
        filter's compiled program and switch the pair to device-resident
        batch-through flow.

        Conditions (all checked, else the chain runs unfused):
        the filter owns its backend and has no output-combination/dynamic
        output; its single src pad feeds exactly one tensor_decoder whose
        subplugin exposes a device half (``device_fn``/``decode_fused``)
        and whose only input is this filter.  Runs after element start()
        (subplugins exist) and before negotiation (fused schemas
        propagate).
        """
        incoming: Dict[str, int] = {n: 0 for n in self.elements}
        for el in self.elements.values():
            for pad in el.srcpads:
                for dst, _ in pad.links:
                    incoming[dst.name] += 1
        for el in self.elements.values():
            if not getattr(el, "can_fuse_postprocess", False):
                continue
            if len(el.srcpads) != 1 or len(el.srcpads[0].links) != 1:
                continue
            dst, _ = el.srcpads[0].links[0]
            if not getattr(dst, "can_fuse_device", False):
                continue
            if incoming[dst.name] != 1:
                continue
            el.fuse_device_postprocess(dst._dec.device_fn)
            dst.enable_fused()
            if el.preferred_batch > 1:
                el._auto_batch_through = True
            self.log.info(
                "device-fused %s -> %s (decoder half compiled into the "
                "filter's XLA program)", el.name, dst.name,
            )

    # -- streaming-thread fusion pass (≙ GStreamer: elements share a
    # streaming thread unless an explicit queue boundary is inserted) ------
    def _compute_segments(self) -> List[_Seg]:
        """Partition the element graph into streaming threads: each maximal
        fusable linear chain becomes ONE worker (intermediate mailboxes are
        elided entirely).  An edge up->down fuses iff:

        * fusion is enabled (``fuse=``/``NNS_FUSE``),
        * ``up``'s ONLY outgoing link is to ``down`` and ``down``'s only
          input is ``up`` (branches/tees/muxes keep thread boundaries),
        * ``down`` does not declare ``THREAD_BOUNDARY`` (``queue``, the
          query client — elements whose semantics need a private mailbox;
          they still drive their own fused downstream, GStreamer-style),
        * ``up`` does not declare ``FUSE_DOWNSTREAM = False``
          (``tensor_query_serversrc`` — admission control needs the
          pipeline parallelism below it),
        * ``down`` has no leaky policy (leaky drop decisions need a queue),
        * neither side micro-batches (``preferred_batch > 1`` needs a
          mailbox to drain batches from, and its downstream boundary is
          what overlaps invoke with decode).

        Runs after element start() (``preferred_batch`` needs live
        backends) and after negotiation."""
        incoming: Dict[str, int] = {n: 0 for n in self.elements}
        for el in self.elements.values():
            for pad in el.srcpads:
                for dst, _ in pad.links:
                    incoming[dst.name] += 1

        def total_out(el: Element) -> int:
            return sum(len(p.links) for p in el.srcpads)

        def fusable(up: Element, down: Element) -> bool:
            if not self._fuse or isinstance(down, SourceElement):
                return False
            if total_out(up) != 1 or incoming[down.name] != 1:
                return False
            if getattr(down, "THREAD_BOUNDARY", False):
                return False  # down keeps its own mailbox/thread (queue…)
            if not getattr(up, "FUSE_DOWNSTREAM", True):
                return False  # up's downstream parallelism is load-bearing
            if getattr(down, "leaky_policy", ""):
                return False
            if getattr(up, "preferred_batch", 1) > 1 or getattr(
                    down, "preferred_batch", 1) > 1:
                return False
            return True

        fused_up: Dict[str, Element] = {}  # down name -> its fused upstream
        for el in self.elements.values():
            if total_out(el) == 1:
                for pad in el.srcpads:
                    for dst, _ in pad.links:
                        if fusable(el, dst):
                            fused_up[dst.name] = el
        segs: List[_Seg] = []
        self._seg_of = {}
        for el in self.elements.values():
            if el.name in fused_up:
                continue  # not a head
            chain = [el]
            cur = el
            while True:
                nxt = None
                if total_out(cur) == 1:
                    for pad in cur.srcpads:
                        for dst, _ in pad.links:
                            if fused_up.get(dst.name) is cur:
                                nxt = dst
                if nxt is None:
                    break
                chain.append(nxt)
                cur = nxt
            seg = _Seg(chain)
            for e in chain:
                st = _ElemState(e)
                st.connected = {
                    pad
                    for other in self.elements.values()
                    for sp in other.srcpads
                    for d, pad in sp.links
                    if d is e
                } or {0}
                st.terminal = not isinstance(e, SourceElement) and not any(
                    p.is_linked for p in e.srcpads
                )
                seg.states[e.name] = st
                self._seg_of[e.name] = seg
            # in-segment routing links
            for a, b in zip(chain, chain[1:]):
                sa = seg.states[a.name]
                for i, pad in enumerate(a.srcpads):
                    for dst, sink_pad in pad.links:
                        if dst is b:
                            sa.next_state = seg.states[b.name]
                            sa.out_pad = i
                            sa.next_pad = sink_pad
            segs.append(seg)
        if self._fuse and any(len(s.chain) > 1 for s in segs):
            self.log.info(
                "fused %d elements onto %d streaming thread(s): %s",
                len(self.elements), len(segs),
                " | ".join(
                    "+".join(e.name for e in s.chain) for s in segs
                ),
            )
        return segs

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "Pipeline":
        if self._started:
            return self
        # claim the registry label BEFORE any element start: elements
        # bind instruments to it in their start() (the query client's
        # rtt histogram), so the label must be settled first — and
        # claiming here (not lazily at scrape time) means a scrape can
        # never resurrect a released label
        if self._telemetry_label is None:
            from ..core.telemetry import claim_pipeline_label

            self._telemetry_label = claim_pipeline_label(self.name)
        started: List[Element] = []
        try:
            # start (open models/resources) BEFORE the static negotiation
            # pass so elements can expose model-derived schemas (reference:
            # caps negotiation triggers subplugin open, tensor_filter.c:1157)
            for el in self.elements.values():
                el.start()
                started.append(el)
            self._fuse_device_chains()
            self._negotiate()
        except BaseException:
            for el in started:
                try:
                    el.stop()
                except Exception:
                    self.log.exception("stop() failed for %s", el.name)
            from ..core.telemetry import release_pipeline_label

            release_pipeline_label(self._telemetry_label)
            self._telemetry_label = None
            raise
        # a terminal is any non-source element with no LINKED src pad (a
        # trailing element whose output nobody consumes still ends the
        # stream, e.g. a pipeline ending at tensor_trainer)
        self._pending_sinks = sum(
            1
            for el in self.elements.values()
            if not isinstance(el, SourceElement)
            and not any(p.is_linked for p in el.srcpads)
        )
        if self._pending_sinks == 0:
            self._sinks_done.set()
        # streaming-thread partition (after element start: preferred_batch
        # needs live backends); mailboxes only where thread boundaries
        # remain — fused elements receive their input inline, so the
        # per-frame lock/condvar handoff between them is gone entirely
        self._segments = self._compute_segments()
        fused_tail = {
            e.name for seg in self._segments for e in seg.chain[1:]
        }
        # mailboxes for every segment-head element with sink pads — native
        # C++ condvar queues when the core library is available (immediate
        # wakeups, GIL released while blocked), stdlib queue.Queue otherwise
        for el in self.elements.values():
            if isinstance(el, SourceElement):
                continue
            if el.name in fused_tail:
                el._mailbox = None  # input arrives inline on the segment
                continue
            size = self.default_queue_size
            if "max-buffers" in el.props and el.props["max-buffers"]:
                size = int(el.props["max-buffers"])
            # a micro-batching element needs its full batch to fit in the
            # mailbox or batches can never form at max-batch size
            size = max(size, getattr(el, "preferred_batch", 1))
            el._mailbox = self._make_mailbox(
                size, getattr(el, "leaky_policy", "")
            )
        def _dlq_maxlen(el: Element) -> int:
            v = el.props.get("dead-letter-max")
            # 0 is a VALID setting (count drops, retain no frame payloads
            # — large tensors must not pin memory); only absent means 16
            return 16 if v is None else max(0, int(v))

        self.health_map = {
            el.name: ElementHealth(
                state="running", dlq=deque(maxlen=_dlq_maxlen(el)),
            )
            for el in self.elements.values()
        }
        self._stop_flag.clear()
        self._drain_flag.clear()
        # upstream adjacency for deadline-QoS feedback (a downstream
        # deadline drop throttles every upstream tensor_rate, ≙ the
        # reference's QoS events travelling upstream)
        self._upstream = {n: [] for n in self.elements}
        for el in self.elements.values():
            for pad in el.srcpads:
                for dst, _ in pad.links:
                    self._upstream[dst.name].append(el)
        self._arm_watchdog()
        self._register_telemetry()
        for el in self.elements.values():
            el._interrupted.clear()
        for seg in self._segments:
            t = threading.Thread(
                target=self._run_segment, args=(seg,),
                name=seg.chain[0].name, daemon=True,
            )
            self._threads.append(t)
        for t in self._threads:
            t.start()
        if self._wd_thread is not None:
            self._wd_thread.start()
        self._started = True
        return self

    def register_sweep(self, fn: Callable[[], Any],
                       min_poll_s: float = 1.0) -> None:
        """Register a slow-cadence poller on the watchdog sweeper thread
        (elements call this from ``start()`` — before ``_arm_watchdog``
        runs, so the sweeper picks it up).  ``fn`` must rate-limit
        itself; ``min_poll_s`` only bounds the sweeper's wakeup
        interval.  Hooks are cleared at the next ``start()``."""
        self._sweep_hooks.append((fn, max(0.05, float(min_poll_s))))

    def _arm_watchdog(self) -> None:
        """Build the liveness watchdog for every element that armed a
        stall-timeout / frame-deadline; no-op (zero threads, zero hot-path
        cost) when nothing is armed."""
        self._watchdog = None
        self._watches = {}
        self._wd_thread = None
        armed = [
            el for el in self.elements.values()
            if float(el.props.get("stall-timeout") or 0.0) > 0
            or float(el.props.get("frame-deadline") or 0.0) > 0
        ]
        if not armed:
            extra = [s for _, s in self._sweep_hooks]
            if self._mem_monitor is not None:
                extra.append(self._mem_monitor.min_poll_s)
            if extra:
                # no liveness watches, but the memory monitor / sweep
                # hooks (digest publisher) still need the cadence
                self._wd_thread = threading.Thread(
                    target=self._watchdog_loop,
                    args=(min(extra),),
                    name=f"{self.name}-watchdog", daemon=True,
                )
            return
        self._watchdog = Watchdog()
        for el in armed:
            # a fused element has no mailbox of its own: pending work for
            # the whole segment sits in the head's mailbox (or a source
            # head's internal queue), so stall detection watches that
            box = el._mailbox
            if box is None:
                seg = self._seg_of.get(el.name)
                head = seg.chain[0] if seg else el
                box = head._mailbox or getattr(head, "_q", None)
            qsize = box.qsize if hasattr(box, "qsize") else (lambda: 0)
            self._watches[el.name] = self._watchdog.register(
                el.name,
                stall_timeout=float(el.props.get("stall-timeout") or 0.0),
                frame_deadline=float(el.props.get("frame-deadline") or 0.0),
                policy=el.props.get("stall-policy", "warn"),
                qsize=qsize,
                on_event=lambda w, kind, elapsed, el=el: self._on_liveness(
                    el, kind, elapsed),
            )
        interval = min(
            [self._watchdog.min_interval()]
            + [s for _, s in self._sweep_hooks])
        self._wd_thread = threading.Thread(
            target=self._watchdog_loop,
            args=(interval,),
            name=f"{self.name}-watchdog", daemon=True,
        )

    def _watchdog_loop(self, interval: float) -> None:
        while not self._stop_flag.wait(interval):
            try:
                if self._watchdog is not None:
                    self._watchdog.check()
            except Exception:  # a sweep bug must never kill liveness
                self.log.exception("watchdog sweep failed")
            mon = self._mem_monitor
            if mon is not None:
                try:
                    mon.poll()  # rate-limited internally
                except Exception:
                    self.log.exception("memory-pressure poll failed")
            for fn, _ in self._sweep_hooks:
                try:
                    fn()  # rate-limited internally (register_sweep)
                except Exception:
                    self.log.exception("sweep hook %r failed", fn)

    def _on_liveness(self, el: Element, kind: str, elapsed: float) -> None:
        """Watchdog escalation (runs on the sweeper thread): bus warning
        always; stall-policy restart/fail additionally interrupt the hung
        call cooperatively (the worker's StallError handling does the
        actual restart — only the hung thread itself can retry its
        frame)."""
        policy = el.props.get("stall-policy", "warn")
        h = self.health_map.get(el.name)
        if h is not None:
            h.last_error = f"liveness: {kind} after {elapsed:.3f}s"
        self.post(BusMessage("warning", el.name, {
            "liveness": kind, "elapsed": elapsed, "policy": policy,
        }))
        # first question after a stall is "where did the time go": dump
        # the flight recorder (rate-limited no-op without one) while the
        # stalled frame's open span is still in the ring
        self.incident(f"watchdog_{kind}", el.name,
                      {"elapsed": elapsed, "policy": policy})
        if policy == "warn":
            return
        el._interrupted.set()
        if policy == "fail":
            # the element may be wedged non-cooperatively: surface the
            # failure NOW so wait() raises, instead of hoping the hung
            # thread ever comes back to report it
            err = StallError(
                f"{el.name}: {kind} after {elapsed:.3f}s (stall-policy=fail)"
            )
            if h is not None:
                h.state = "stalled"
            self.errors.append(err)
            self.post(BusMessage("error", el.name, err))
            self._stop_flag.set()
            self._sinks_done.set()

    def _make_mailbox(self, size: int, leaky: str = ""):
        if leaky:
            return _LeakyMailbox(size, leaky)
        try:
            from ..native.runtime import NativeMailbox, available

            if available():
                return NativeMailbox(size)
        except Exception:  # pragma: no cover — toolchain quirks
            self.log.exception("native mailbox unavailable; using queue.Queue")
        return queue.Queue(maxsize=size)

    def _halt_workers(self) -> None:
        """Immediate worker shutdown: stop flag + mailbox sentinels +
        join.  Frames still queued are abandoned (count them with
        ``_count_abandoned`` before element state is torn down)."""
        self._stop_flag.set()
        self._halt_discarded = 0
        for el in self.elements.values():
            if el._mailbox is not None:
                try:
                    el._mailbox.put_nowait((0, _STOP))
                except queue.Full:
                    # drain one slot so the sentinel fits — the evicted
                    # frame is abandoned too, so count it for
                    # _count_abandoned's exact-dropped contract
                    try:
                        _, item = el._mailbox.get_nowait()
                        if isinstance(item, TensorFrame):
                            self._halt_discarded += getattr(
                                item, "batch_size", 1)
                        el._mailbox.put_nowait((0, _STOP))
                    except (queue.Empty, queue.Full):
                        pass
        for t in self._threads:
            t.join(timeout=5.0)

    def stop(self, drain: bool = False,
             drain_timeout: Optional[float] = None) -> None:
        """Tear the pipeline down.  ``drain=True`` first flushes every
        in-flight frame to the sinks via :meth:`drain` (bounded by
        ``drain_timeout``) — planned shutdowns lose nothing; the default
        remains the immediate teardown (queued frames are abandoned)."""
        if drain and self._started and not self._stop_flag.is_set():
            self.drain(drain_timeout)
            if not self._started:
                return  # an expired drain already tore the pipeline down
        self._halt_workers()
        if self._wd_thread is not None:
            if self._wd_thread.is_alive():
                self._wd_thread.join(timeout=2.0)
            self._wd_thread = None
            # _watchdog/_watches survive stop(): a straggler worker whose
            # join timed out may still ping them (harmless — the sweeper
            # is gone), and health() keeps reporting the final counters;
            # the next start() rebuilds both in _arm_watchdog()
        for el in self.elements.values():
            try:
                el.stop()
            except Exception:
                self.log.exception("stop() failed for %s", el.name)
        # telemetry teardown AFTER element stop: a scrape racing the
        # shutdown still sees consistent health; the exposition listener
        # socket is closed synchronously here (leak-check contract)
        self._unregister_telemetry()
        self._threads.clear()
        # sweep hooks die with the run (elements re-register at the
        # next start(); a restart must not accumulate stale pollers)
        self._sweep_hooks = []
        self._started = False

    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until EOS reached every sink; re-raise the first element
        error.  ≙ waiting for EOS/ERROR on the GstBus.

        A timed-out wait TEARS THE PIPELINE DOWN (``stop()``) before
        raising ``TimeoutError``: a stuck pipeline must not leak live
        worker threads into the caller (they would poison later tests /
        pipelines in the same process).  A timeout is a terminal
        condition, not a poll — use ``pop_message``/bus watchers to
        observe a pipeline that should keep running."""
        finished = self._sinks_done.wait(timeout)
        if self.errors:
            raise self.errors[0]
        if not finished:
            self.stop()
            if self.errors:
                # an error that raced the timeout is the truer cause
                raise self.errors[0]
            raise TimeoutError(f"pipeline {self.name!r} did not finish in {timeout}s")

    # -- zero-downtime operations (core/lifecycle.py) ------------------------
    @property
    def draining(self) -> bool:
        """True between ``drain()`` and completion/teardown — sources
        (including ones blocking inside ``frames()``, via
        ``lifecycle.pipeline_quiescing``) stop producing and flush EOS."""
        return self._drain_flag.is_set()

    def delivered_frames(self) -> int:
        """Logical frames consumed by terminal elements since start()
        (single-writer per-streaming-thread counters, summed here)."""
        return sum(
            st.delivered
            for seg in self._segments
            for st in seg.states.values()
        )

    def _count_abandoned(self) -> int:
        """Exact count of logical frames abandoned by an immediate halt:
        everything still queued in mailboxes plus whatever elements
        report as parked in-flight (``pending_frames`` hook, e.g. the
        filter's dispatch window).  Call after ``_halt_workers`` and
        before element ``stop()`` clears that state."""
        n = getattr(self, "_halt_discarded", 0)
        for el in self.elements.values():
            box = el._mailbox
            if box is not None:
                try:
                    while True:
                        _, item = box.get_nowait()
                        if isinstance(item, TensorFrame):
                            n += getattr(item, "batch_size", 1)
                except queue.Empty:
                    pass
            pending = getattr(el, "pending_frames", None)
            if pending is not None:
                try:
                    n += int(pending() or 0)
                except Exception:
                    self.log.exception(
                        "pending_frames failed for %s", el.name)
        for seg in self._segments:
            for st in seg.states.values():
                n += st.in_call  # halted mid-call: the frame never left
            for _, item in seg.stash:
                if isinstance(item, TensorFrame):
                    n += getattr(item, "batch_size", 1)
        return n

    def drain(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        """Graceful drain: quiesce every source, flush all in-flight
        frames through to the sinks via the existing EOS machinery, and
        return exact accounting::

            {"drained": <frames delivered to terminal elements since the
                         drain began>,
             "dropped": <frames abandoned because the deadline expired —
                         the pipeline is torn down in that case>,
             "elapsed": <seconds>}

        Semantics are identical fused and unfused (the counters live at
        the terminal dispatch, which both modes share).  A completed
        drain leaves the pipeline stopped-at-EOS but not torn down —
        call ``stop()`` (or use ``stop(drain=True)``) to release
        resources."""
        t0 = time.monotonic()
        if not self._started:
            return {"drained": 0, "dropped": 0, "elapsed": 0.0}
        base = self.delivered_frames()
        self.log.info(
            "draining pipeline%s",
            f" (deadline {timeout}s)" if timeout else "",
        )
        self._drain_flag.set()
        finished = self._sinks_done.wait(timeout)
        dropped = 0
        if not finished:
            # deadline expired: halt NOW and account every frame that
            # did not make it out
            self._halt_workers()
            dropped = self._count_abandoned()
        drained = self.delivered_frames() - base
        elapsed = time.monotonic() - t0
        self.post(BusMessage("element", self.name, {
            "drain": {
                "drained": drained, "dropped": dropped,
                "elapsed": elapsed, "completed": finished,
            },
        }))
        if not finished:
            self.stop()  # finish the teardown (workers already joined)
        return {"drained": drained, "dropped": dropped, "elapsed": elapsed}

    def reload_model(self, element, model: str = ""):
        """Zero-downtime model rollout: stage, validate, and JIT-warm
        ``model`` on a second backend instance off the hot path, then
        hot-swap the named ``tensor_filter`` at a frame boundary (see
        ``core/lifecycle.py``; swap/rollback counters surface in
        :meth:`health`).  Returns the :class:`~..core.lifecycle.SwapTicket`."""
        el = self.elements[element] if isinstance(element, str) else element
        request = getattr(el, "request_reload", None)
        if request is None:
            raise ElementError(
                f"{el.name} does not support hot model reload")
        return request(model)

    # -- supervision ---------------------------------------------------------
    def health(self) -> Dict[str, Dict[str, Any]]:
        """Live supervision snapshot: per-element state, restart count,
        dead-letter depth/total, and any element-specific health (e.g.
        the query client's per-remote circuit-breaker states via
        ``Element.health_info()``).  Cheap enough to poll."""
        out: Dict[str, Dict[str, Any]] = {}
        for name, el in self.elements.items():
            h = self.health_map.get(name) or ElementHealth()
            entry: Dict[str, Any] = {
                "state": h.state,
                "policy": el.props.get("error-policy", "fail-stop"),
                "restarts": h.restarts_total,
                "restarts_window": h.restarts,
                "dead_letters": h.dead_letters,
                "dead_letter_depth": len(h.dlq),
                "deadline_drops": h.deadline_drops,
                "last_error": h.last_error,
            }
            w = self._watches.get(name)
            if w is not None:
                entry["stalls"] = w.stalls
                entry["overruns"] = w.overruns
            info = getattr(el, "health_info", None)
            if info is not None:
                try:
                    entry.update(info() or {})
                except Exception:  # health must never kill the caller
                    self.log.exception("health_info failed for %s", name)
            out[name] = entry
        return out

    def post_health(self) -> None:
        """Publish the current health snapshot on the bus (kind
        ``health``); also posted automatically when an element degrades."""
        self.post(BusMessage("health", self.name, self.health()))

    # -- deadline QoS ---------------------------------------------------------
    def _expire_late(self, el: Element, frames: list) -> list:
        """Deadline QoS: drop frames whose latency budget is exhausted
        before `el` processes them (``late-policy=drop``), with exact
        accounting (``health()[el]["deadline_drops"]``), a rate-limited
        bus warning, and QoS feedback to upstream throttlers
        (``note_qos``, implemented by tensor_rate).  Frames with no
        deadline cost one dict lookup each."""
        keep = None  # lazily forked: the no-drop path must not copy
        now = time.monotonic()
        for i, f in enumerate(frames):
            ts = f.meta.get(DEADLINE_META)
            # boundary contract: delivered strictly BEFORE the deadline,
            # dropped from the instant now >= deadline (liveness.is_expired)
            if ts is None or now < ts:
                if keep is not None:
                    keep.append(f)
                continue
            if keep is None:
                if el.props.get("late-policy", "drop") != "drop":
                    return frames
                keep = list(frames[:i])
            n = getattr(f, "batch_size", 1)
            h = self.health_map.get(el.name)
            if h is not None:
                h.deadline_drops += n
            lateness = now - ts
            last = self._qos_warn_ts.get(el.name, float("-inf"))
            if now - last >= 1.0:  # 1/s per element: drops come in bursts
                self._qos_warn_ts[el.name] = now
                self.log.warning(
                    "%s: dropped %d frame(s) %.3fs past deadline "
                    "(late-policy=drop)", el.name, n, lateness,
                )
                self.post(BusMessage("warning", el.name, {
                    "qos": "deadline", "dropped": n, "lateness": lateness,
                }))
            self._qos_feedback(el, f, lateness)
        return frames if keep is None else keep

    def _qos_feedback(self, el: Element, frame, lateness: float) -> None:
        """Tell every upstream throttler a deadline was missed (≙ the
        reference's QoS events travelling upstream to tensor_rate,
        gsttensor_rate.c): elements exposing ``note_qos(pts, lateness)``
        hear about it and shed earlier, where dropping is cheapest."""
        seen = {el.name}
        stack = [el.name]
        while stack:
            for up in self._upstream.get(stack.pop(), ()):
                if up.name in seen:
                    continue
                seen.add(up.name)
                note = getattr(up, "note_qos", None)
                if note is not None:
                    try:
                        note(frame.pts, lateness)
                    except Exception:
                        self.log.exception("note_qos failed for %s", up.name)
                stack.append(up.name)

    def stream_cancel_feedback(self, el: Element, meta: dict) -> None:
        """A downstream consumer of a generation stream is GONE (the
        serversink's client vanished mid-stream): walk upstream — the
        ``note_qos`` routing — and tell every element exposing
        ``note_stream_cancel(meta)``, so a continuous-batching slot
        engine frees the dead stream's slot instead of decoding tokens
        nobody will read."""
        seen = {el.name}
        stack = [el.name]
        while stack:
            for up in self._upstream.get(stack.pop(), ()):
                if up.name in seen:
                    continue
                seen.add(up.name)
                note = getattr(up, "note_stream_cancel", None)
                if note is not None:
                    try:
                        note(meta)
                    except Exception:
                        self.log.exception(
                            "note_stream_cancel failed for %s", up.name)
                stack.append(up.name)

    def stream_drain_feedback(self) -> None:
        """A query serversrc of THIS pipeline entered its rolling-restart
        drain: tell every element exposing ``note_stream_drain()`` (the
        continuous-batching generator) so live generation streams are
        handed off as resumable GOAWAY chunks — the client migrates them
        to a healthy server — instead of the drain-deadline racing whole
        generations.  Never fired by a plain ``drain()`` on a pipeline
        without a serversrc: local streams flush, they don't migrate."""
        for el in self.elements.values():
            note = getattr(el, "note_stream_drain", None)
            if note is None:
                continue
            try:
                note()
            except Exception:
                self.log.exception(
                    "note_stream_drain failed for %s", el.name)

    def _dead_letter(self, el: Element, frames, err: BaseException) -> None:
        """skip policy: record dropped frame(s) + bus warning."""
        h = self.health_map[el.name]
        frames = frames if isinstance(frames, list) else [frames]
        n = sum(getattr(f, "batch_size", 1) for f in frames)
        for f in frames:
            h.dlq.append((f, repr(err)))
        h.dead_letters += n
        h.last_error = repr(err)
        self.log.warning(
            "%s: dropped %d poisoned frame(s) (error-policy=skip): %s",
            el.name, n, err,
        )
        self.post(BusMessage("warning", el.name, {
            "policy": "skip", "dropped": n, "error": err,
        }))
        self.incident("dead_letter", el.name, err)

    def _restart_element(self, el: Element, err: BaseException) -> str:
        """restart policy: stop+start `el` with exponential backoff.

        Returns ``"retry"`` (restarted — re-run the failed call),
        ``"degraded"`` (max-restarts exhausted or start() itself failed
        — caller falls back to fail-stop), or ``"stopping"`` (pipeline
        shut down mid-backoff — caller exits quietly)."""
        h = self.health_map[el.name]
        el._interrupted.clear()  # a liveness interrupt is consumed here
        h.last_error = repr(err)
        limit = int(el.props.get("max-restarts", 3))
        window = float(el.props.get("restart-window", 60.0) or 0.0)
        now = time.monotonic()
        if window > 0 and h.last_restart_ts and (
                now - h.last_restart_ts) > window:
            # sustained health since the last restart: the budget (and
            # the backoff curve) refills — isolated glitches over days
            # must not accumulate into an inevitable degradation
            h.restarts = 0
        h.last_restart_ts = now
        if h.restarts >= limit:
            h.state = "degraded"
            self.log.error(
                "%s: max-restarts=%d exhausted; degrading to fail-stop",
                el.name, limit,
            )
            self.post(BusMessage("warning", el.name, {
                "policy": "restart", "degraded": True, "error": err,
            }))
            self.post_health()
            return "degraded"
        h.restarts += 1
        h.restarts_total += 1
        h.state = "restarting"
        from ..core.resilience import RetryPolicy

        base = float(el.props.get("restart-backoff", 0.05) or 0.0)
        # RetryPolicy owns the backoff curve (capped exponential + jitter
        # so many elements restarting together don't thundering-herd)
        delay = RetryPolicy(
            base_delay_s=base, max_delay_s=2.0, jitter=0.1,
        ).delay_for(h.restarts) if base > 0 else 0.0
        self.log.warning(
            "%s: restart %d/%d after error (backoff %.3fs): %s",
            el.name, h.restarts, limit, delay, err,
        )
        self.post(BusMessage("warning", el.name, {
            "policy": "restart", "restart": h.restarts, "error": err,
        }))
        try:
            el.stop()
        except Exception:
            self.log.exception("%s: stop() during restart failed", el.name)
        if delay > 0 and self._stop_flag.wait(delay):
            return "stopping"
        if self._stop_flag.is_set():
            return "stopping"
        try:
            el.start()
        except Exception:  # interrupts must propagate, not "degrade"
            self.log.exception("%s: start() during restart failed", el.name)
            h.state = "degraded"
            self.post_health()
            return "degraded"
        h.state = "running"
        return "retry"

    _SUPERVISED_STOPPING = object()  # sentinel: worker must exit quietly

    def _skip_failed(self, el: Element, frames, err: BaseException,
                     per_item) -> list:
        """skip semantics for a failed call: when the call covered
        MULTIPLE frames and a per-item re-call is available, isolate the
        poison — re-run each frame alone so one bad frame in a
        micro-batch doesn't take its batchmates to the dead-letter
        queue; otherwise dead-letter the whole input.  Assumes the batch
        call failed atomically (true for the invoke-style elements that
        batch: one backend call, outputs only on success) — a stateful
        element that partially consumed the batch before raising would
        see the survivors twice."""
        if per_item is not None and isinstance(frames, list) and len(frames) > 1:
            outs: list = []
            for f in frames:
                try:
                    outs.extend(per_item(f) or [])
                except (KeyboardInterrupt, SystemExit):
                    raise
                except BaseException as e2:  # noqa: BLE001 — policy boundary
                    self._dead_letter(el, [f], e2)
            return outs
        self._dead_letter(el, frames, err)
        return []

    def _supervised(self, el: Element, call, frames, per_item=None):
        """Run one frame-processing call under `el`'s error-policy.

        fail-stop re-raises (the `_guard` boundary turns it into a bus
        error + pipeline teardown); skip dead-letters the poisoned
        frame(s) — isolating them per-frame via `per_item` when the
        failed call was a micro-batch — and yields the rest; restart
        restarts the element and RETRIES the same call (zero frame loss
        for transient faults), degrading to fail-stop once max-restarts
        is exhausted, and treats fatal (bad-input) errors like skip.

        Elements with ``SUPERVISES_OWN_ERRORS`` (async in-flight
        dispatch, e.g. the query client) always run fail-stop here: an
        error surfacing during frame B's call may belong to in-flight
        frame A, so skip/restart would dead-letter or re-dispatch the
        WRONG frame — such elements degrade via their own mechanism
        (``degrade=`` on the query client) instead."""
        policy = el.props.get("error-policy", "fail-stop")
        if getattr(el, "SUPERVISES_OWN_ERRORS", False):
            policy = "fail-stop"
        # locals: stop() may run concurrently with a straggler worker —
        # the pings must never dereference a half-torn-down pipeline
        wd, watch = self._watchdog, self._watches.get(el.name)
        while True:
            try:
                if el._interrupted.is_set():
                    # a STALE interrupt (the flagged call completed on
                    # its own, or the stall was a transient push-block)
                    # must not leak into this healthy call — it would
                    # raise a spurious StallError and burn the restart
                    # budget on an element that is progressing
                    el._interrupted.clear()
                if watch is not None:
                    # heartbeat: the busy window spans the whole call so
                    # the watchdog can flag a per-frame overrun (pinged
                    # BEFORE the fault site — an injected hang must land
                    # inside the monitored window)
                    wd.begin(watch)
                try:
                    # fault-injection site INSIDE the policy boundary, so
                    # injected faults exercise the same machinery real
                    # ones do; the interrupt predicate lets watchdog
                    # escalation / pipeline stop break hang= faults
                    if FAULTS.is_armed():
                        FAULTS.check(
                            f"element.{el.name}.handle_frame",
                            interrupt=lambda: el.interrupted,
                        )
                    result = call()
                    if policy != "fail-stop" and not isinstance(
                            result, (list, tuple)):
                        # lazy outputs (generators, e.g. the query client's
                        # stream mode) raise during ITERATION, which happens
                        # outside this try under fail-stop; with skip/restart
                        # the errors must land here, so materialize — the
                        # cost of supervision is losing output laziness
                        result = list(result)
                finally:
                    if watch is not None:
                        # any outcome is progress: the item left the queue
                        wd.done(watch)
                return result
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — policy boundary
                if isinstance(e, StallError):
                    # a hung call surfaced via cooperative interruption:
                    # STALL-policy governs (independent of error-policy —
                    # a fail-stop element can still be stall-restarted)
                    el._interrupted.clear()
                    sp = el.props.get("stall-policy", "warn")
                    if sp == "restart":
                        verdict = self._restart_element(el, e)
                        if verdict == "retry":
                            continue
                        if verdict == "stopping":
                            return self._SUPERVISED_STOPPING
                        raise  # degraded: fall back to fail-stop
                    if sp == "fail":
                        raise
                    # warn (element code raised StallError on its own):
                    # fall through to the normal error-policy handling
                if policy == "skip":
                    return self._skip_failed(el, frames, e, per_item)
                if policy == "restart":
                    from ..core.resilience import is_transient

                    if not is_transient(e):
                        # fatal classification (bad input, schema bug):
                        # restarting cannot fix the frame — dead-letter
                        # (isolating within a batch) and keep the restart
                        # budget for faults a restart CAN cure
                        return self._skip_failed(el, frames, e, per_item)
                    verdict = self._restart_element(el, e)
                    if verdict == "retry":
                        continue
                    if verdict == "stopping":
                        return self._SUPERVISED_STOPPING
                raise

    def run(self, timeout: Optional[float] = None) -> None:
        """start + wait + stop."""
        self.start()
        try:
            self.wait(timeout)
        finally:
            self.stop()

    # -- worker runtime ------------------------------------------------------
    # One worker thread per SEGMENT (a maximal fusable linear chain).  The
    # head pulls items (source generator or mailbox); every downstream
    # element in the segment processes inline on the same streaming thread
    # via _dispatch — no intermediate mailbox, no lock/condvar handoff, no
    # per-frame wakeup.  Items leaving the segment go through _push /
    # _push_outs (block handoff: one queue operation per run of outputs).

    def _fail(self, el: Element, e: BaseException) -> bool:
        """Record a fatal element failure (≙ GstBus error posting) and tear
        the pipeline down; returns False so dispatch chains unwind.  Must
        be called from an ``except`` context (log.exception)."""
        self.log.exception("element %s failed", el.name)
        h = self.health_map.get(el.name)
        if h is not None:
            h.state = "failed"
            h.last_error = repr(e)
        self.errors.append(e)
        self.post(BusMessage("error", el.name, e))
        self._stop_flag.set()
        self._sinks_done.set()  # unblock wait()
        return False

    def _guard(self, el: Element, fn, *args):
        try:
            return fn(*args)
        except BaseException as e:  # noqa: BLE001 — worker boundary
            self._fail(el, e)
            return None

    def _push(self, el: Element, src_pad: int, item) -> bool:
        """Push one item downstream with backpressure; False if stopping.

        Frames bound for a leaky queue are dropped instead of blocking
        (``upstream``: the incoming frame; ``downstream``: the oldest
        queued frame).  Events always use the blocking path — caps/EOS
        must never be lost."""
        pad = el.srcpads[src_pad]
        is_frame = isinstance(item, TensorFrame)
        if is_frame and self.tracer is not None:
            # queue-wait origin stamp (host-local, popped at dequeue);
            # tracer-armed only — the disabled path stays one branch
            item.meta[TL_QPUT_META] = time.perf_counter()
        for dst, sink_pad in pad.links:
            box = dst._mailbox
            if is_frame and isinstance(box, _LeakyMailbox):
                box.put_frame((sink_pad, item))
                continue
            while not self._stop_flag.is_set():
                try:
                    box.put((sink_pad, item), timeout=0.1)
                    break
                except queue.Full:
                    continue
            else:
                return False
        return True

    def _put_many(self, dst: Element, items: list) -> int:
        """Deliver an ordered run of ``(pad, item)`` entries into ``dst``'s
        mailbox, amortizing the lock/condvar cost over the run when the
        mailbox supports bulk insertion (block handoff); falls back to the
        per-item blocking path otherwise.  Returns the number of entries
        delivered — short of ``len(items)`` only when stopping (the halt
        accounting needs the exact split: delivered entries are counted
        in the mailbox sweep, the rest stay on the emitter)."""
        box = dst._mailbox
        if self.tracer is not None:
            now = time.perf_counter()
            for _, it in items:
                if isinstance(it, TensorFrame):
                    it.meta[TL_QPUT_META] = now
        put_many = getattr(box, "put_many", None)
        idx, n_items = 0, len(items)
        while idx < n_items:
            if put_many is not None:
                n = put_many(items[idx:] if idx else items, timeout=0.1)
                idx += n
                if idx >= n_items:
                    return idx
                if n > 0:
                    continue  # partial progress: retry the remainder
            # blocked (or no bulk support): bounded-wait single put so the
            # stop flag stays responsive and events are never dropped
            entry = items[idx]
            while not self._stop_flag.is_set():
                try:
                    box.put(entry, timeout=0.1)
                    break
                except queue.Full:
                    continue
            else:
                return idx
            idx += 1
        return idx

    def _push_outs(self, el: Element, outs, st: "_ElemState" = None) -> bool:
        """Deliver a call's outputs through mailboxes.  Consecutive items
        bound for the same destination travel as ONE queue operation, so
        the lock/wakeup cost amortizes over the run (a micro-batching
        filter emitting N per-frame outputs pays ~1 handoff, not N).

        With ``st``, ``st.in_call`` is decremented as frames land in a
        mailbox (where the halt-time sweep takes over counting them) —
        per delivered entry on the common single-destination shape, in
        one step on full success for fan-outs (a frame delivered to one
        of two branches has no exact owner; the all-or-nothing fallback
        at worst overcounts that stop-race edge)."""
        if not outs:
            return True
        if len(outs) == 1:
            sp, out = outs[0]
            if not self._push(el, sp, out):
                return False
            if st is not None and isinstance(out, TensorFrame):
                st.in_call = max(
                    0, st.in_call - getattr(out, "batch_size", 1))
            return True
        runs: list = []  # [(dst, [(pad, item), ...])], order kept per dst
        index: Dict[str, int] = {}
        for sp, out in outs:
            for dst, sink_pad in el.srcpads[sp].links:
                k = index.get(dst.name)
                if k is None:
                    index[dst.name] = len(runs)
                    runs.append((dst, [(sink_pad, out)]))
                else:
                    runs[k][1].append((sink_pad, out))
        track_each = st is not None and len(runs) == 1
        for dst, items in runs:
            n = self._put_many(dst, items)
            if track_each:
                for _, item in items[:n]:
                    if isinstance(item, TensorFrame):
                        st.in_call = max(
                            0, st.in_call - getattr(item, "batch_size", 1))
            if n < len(items):
                return False
        if st is not None and not track_each:
            st.in_call = max(0, st.in_call - self._outs_logical(outs))
        return True

    def _route_one(self, seg: _Seg, st: _ElemState, sp: int, item) -> bool:
        """Route one output item: inline into the fused downstream element
        when the link stays inside the segment, else out through its
        mailbox.  False = the worker must exit."""
        nxt = st.next_state
        if nxt is not None:
            if sp == st.out_pad:
                return self._dispatch(seg, nxt, st.next_pad, item)
            return True  # unlinked src pad: dropped (parity with _push)
        return self._push(st.el, sp, item)

    @staticmethod
    def _outs_logical(outs) -> int:
        """Logical frames in a materialized outs list/tuple (0 for lazy
        iterables, which produce frames on demand).  Drain accounting:
        once a handler returns, its INPUT frames are gone (emitted as
        these outs, parked behind a ``pending_frames`` hook, or consumed)
        — ``st.in_call`` transfers to this count so a halt mid-route
        never double-counts parked frames yet still sees unrouted
        outputs."""
        if not isinstance(outs, (list, tuple)):
            return 0
        n = 0
        for _, out in outs:
            if isinstance(out, TensorFrame):
                n += getattr(out, "batch_size", 1)
        return n

    def _route_outs(self, seg: _Seg, st: _ElemState, outs) -> bool:
        """Route a call's outputs (list, tuple, or lazy iterable).  Lists
        are consumed destructively so frame carcasses can return to the
        pool the moment downstream is done with them; lazy iterables (the
        query client's stream mode) are forwarded as they are produced.
        ``st.in_call`` is decremented as each frame is handed off
        (mailbox put or inline dispatch — where the downstream element's
        own accounting takes over), keeping halt-time abandoned counts
        exact."""
        nxt = st.next_state
        if nxt is None:
            if isinstance(outs, (list, tuple)):
                return self._push_outs(st.el, outs, st)
            for sp, out in outs:  # lazy stream: emit answers as they land
                if not self._push(st.el, sp, out):
                    return False
            return True
        out_pad, next_pad = st.out_pad, st.next_pad
        if isinstance(outs, (list, tuple)):
            is_list = isinstance(outs, list)
            for k in range(len(outs)):
                sp, out = outs[k]
                if is_list:
                    outs[k] = None  # drop the ref so recycle can reclaim
                if isinstance(out, TensorFrame):
                    # handed off: the fused downstream call (or its drop
                    # on an unlinked pad) owns the frame from here
                    st.in_call = max(
                        0, st.in_call - getattr(out, "batch_size", 1))
                if sp == out_pad:
                    if not self._dispatch(seg, nxt, next_pad, out):
                        return False
                if isinstance(out, TensorFrame):
                    FRAME_POOL.recycle(out)
            return True
        for sp, out in outs:
            if sp == out_pad:
                if not self._dispatch(seg, nxt, next_pad, out):
                    return False
            if isinstance(out, TensorFrame):
                FRAME_POOL.recycle(out)
        return True

    def _fast_path(self, el: Element, watch) -> bool:
        """True when the full _supervised wrapper would change nothing for
        this call — no watchdog heartbeat to ping, no fault site armed, no
        pending interrupt, fail-stop error policy and warn stall policy —
        so the dispatch loop may call the handler directly (errors still
        reach the worker boundary exactly as _supervised's re-raise
        would).  Saves the per-frame closure allocations and the
        try/finally machinery on the hot path."""
        return (
            watch is None
            and not FAULTS.is_armed()
            and not el._interrupted.is_set()
            and el.props.get("error-policy", "fail-stop") == "fail-stop"
            and el.props.get("stall-policy", "warn") == "warn"
        )

    def _finish_eos(self, seg: _Seg, st: _ElemState) -> bool:
        """`st.el` consumed EOS on every connected pad: propagate it (or
        terminate the stream when this element is a terminal).  Returns
        False: the element — and, via the inline EOS cascade, everything
        downstream of it in this segment — is done, so the worker
        unwinds."""
        el = st.el
        st.finished = True
        h = self.health_map.get(el.name)
        if h is not None and h.state not in ("degraded", "failed"):
            h.state = "finished"
        if any(p.is_linked for p in el.srcpads):
            for i in range(len(el.srcpads)):
                self._route_one(seg, st, i, EOS())
        else:
            with self._sink_lock:
                self._pending_sinks -= 1
                if self._pending_sinks <= 0:
                    self._sinks_done.set()
            self.post(BusMessage("eos", el.name))
        return False

    def _dispatch(self, seg: _Seg, st: _ElemState, pad: int, item) -> bool:
        """Process one in-band item on `st.el`, inline on the segment's
        streaming thread, with full per-ELEMENT supervision (error-policy,
        watchdog heartbeats, deadline expiry, tracing all attribute to the
        element, not the thread).  Returns False when the worker must exit
        (error recorded, stopping, or the stream finished)."""
        el = st.el
        try:
            if isinstance(item, TensorFrame):
                return self._dispatch_frame(seg, st, pad, item)
            if isinstance(item, CapsEvent):
                el.set_sink_spec(pad, item.spec)
                st.caps_pads.add(pad)
                if st.caps_pads >= st.connected:
                    for i in range(len(el.srcpads)):
                        if not self._route_one(
                                seg, st, i, CapsEvent(el.derive_spec(i))):
                            return False
                return True
            if isinstance(item, EOS):
                st.eos_pads.add(pad)
                outs = (
                    el.handle_eos(pad) if hasattr(el, "handle_eos") else None
                )
                if outs and not self._route_outs(seg, st, list(outs)):
                    return False
                if st.eos_pads >= st.connected:
                    return self._finish_eos(seg, st)
                return True
            if isinstance(item, Flush):
                # drop queued FRAMES only (head mailboxes; fused links
                # hold nothing in flight); events behind the flush must
                # survive in order
                box = el._mailbox
                if box is not None:
                    kept = []
                    try:
                        while True:
                            p2, nxt = box.get_nowait()
                            if not isinstance(nxt, TensorFrame):
                                kept.append((p2, nxt))
                    except queue.Empty:
                        pass
                    for entry in kept:
                        while not self._stop_flag.is_set():
                            try:
                                box.put(entry, timeout=0.1)
                                break
                            except queue.Full:
                                continue
                for sp, ev in el.handle_event(pad, item) or []:
                    self._route_one(seg, st, sp, ev)
                return True
            for sp, ev in el.handle_event(pad, item) or []:  # custom events
                if not self._route_one(seg, st, sp, ev):
                    return False
            return True
        except BaseException as e:  # noqa: BLE001 — worker boundary
            return self._fail(el, e)

    def _dispatch_frame(
        self, seg: _Seg, st: _ElemState, pad: int, frame
    ) -> bool:
        """Run one frame (or non-aware block, split per-frame) through
        `st.el` and route the outputs.  Caller owns `frame`'s carcass."""
        el = st.el
        tracer = self.tracer
        if isinstance(frame, BatchFrame) and not el.BATCH_AWARE:
            # block safety net: per-frame elements get logical frames,
            # never a surprise batch axis; each is supervised INDIVIDUALLY
            # (a batch-call-then-replay would re-run the already-processed
            # prefix on a stateful element)
            t_in = time.perf_counter() if tracer is not None else 0.0
            nlog = frame.batch_size
            nbytes = frame_nbytes(frame) if tracer is not None else 0
            src_ts = (
                frame.meta.get(META_SRC_TS) if tracer is not None else None
            )
            if tracer is not None:
                tracer.frame_begin(el.name, frame)
            lfs = self._expire_late(el, frame.split())
            st.in_call = len(lfs)
            for k in range(len(lfs)):
                lf = lfs[k]
                lfs[k] = None  # release the list's ref for the pool
                if self._fast_path(el, st.watch):
                    outs = el.handle_frame(pad, lf) or []
                else:
                    outs = self._supervised(
                        el,
                        lambda lf=lf, pad=pad: el.handle_frame(pad, lf) or [],
                        lf,
                    )
                    if outs is self._SUPERVISED_STOPPING:
                        return False
                if st.terminal:
                    st.delivered += 1
                # this input frame is consumed: what remains at risk is
                # the unprocessed tail plus this call's unrouted outputs
                remaining = len(lfs) - k - 1
                st.in_call = remaining + self._outs_logical(outs)
                if not self._route_outs(seg, st, outs):
                    return False
                st.in_call = remaining
                FRAME_POOL.recycle(lf)
            if tracer is not None:
                tracer.frame_out(
                    el.name, t_in, time.perf_counter(), nlog, nbytes, src_ts,
                    frame=frame,
                )
            return True
        if not self._expire_late(el, (frame,)):
            return True  # deadline passed: accounted drop (caller recycles)
        st.in_call = getattr(frame, "batch_size", 1)
        t_in = time.perf_counter() if tracer is not None else 0.0
        if tracer is not None:
            tracer.frame_begin(el.name, frame)
        if self._fast_path(el, st.watch):
            outs = el.handle_frame(pad, frame) or []
        else:
            outs = self._supervised(
                el,
                lambda frame=frame, pad=pad: el.handle_frame(pad, frame)
                or [],
                frame,
            )
            if outs is self._SUPERVISED_STOPPING:
                return False
        if st.terminal:
            st.delivered += getattr(frame, "batch_size", 1)
        if tracer is not None:
            tracer.frame_out(
                el.name, t_in, time.perf_counter(),
                getattr(frame, "batch_size", 1),
                frame_nbytes(frame),
                frame.meta.get(META_SRC_TS),
                frame=frame,
            )
        # input consumed (emitted / parked behind pending_frames /
        # delivered): transfer in_call to the unrouted outputs, which
        # _route_outs decrements as each is handed off
        st.in_call = self._outs_logical(outs)
        return self._route_outs(seg, st, outs)

    def _run_segment(self, seg: _Seg) -> None:
        for st in seg.states.values():
            st.watch = self._watches.get(st.el.name)
        head = seg.chain[0]
        if isinstance(head, SourceElement):
            self._run_source(seg)
        else:
            self._guard(head, self._run_chain_head, seg)

    def _run_source(self, seg: _Seg) -> None:
        el = seg.chain[0]
        st = seg.states[el.name]

        def body():
            # deadline QoS stamping (deadline-s prop): every emitted frame
            # carries a latency budget downstream elements honor.  The pts
            # anchor (live playback) is the wall instant of the FIRST
            # frame minus its pts, so frame 0 gets its full budget.
            budget = float(el.props.get("deadline-s") or 0.0)
            pts_anchored = el.props.get("deadline-anchor") == "pts"
            anchor = None
            for i in range(len(el.srcpads)):
                spec = (
                    el.output_spec() if len(el.srcpads) == 1
                    else el.derive_spec(i)
                )
                if not self._route_one(seg, st, i, CapsEvent(spec)):
                    return
            # liveness on sources: the busy window wraps each next() on
            # the frames() generator (and the per-frame fault site), so
            # frame-deadline bounds the gap between productions (a
            # stalled camera/publisher) and stall-timeout catches a
            # producer hung mid-pull.  Downstream routing stays OUTSIDE
            # the window — blocking on backpressure (or a fused
            # downstream element's work) is healthy, not a stall.
            wd, watch = self._watchdog, self._watches.get(el.name)
            frames_it = iter(el.frames())
            owns_drain = getattr(el, "OWNS_DRAIN", False)
            src_pending = getattr(el, "pending_frames", None)
            while True:
                if self._drain_flag.is_set() and not owns_drain and (
                        src_pending is None or src_pending() <= 0):
                    # graceful drain: stop pulling and fall through to
                    # the EOS routing below, flushing everything already
                    # in flight through to the sinks.  A source holding
                    # buffered input (appsrc) reports it via
                    # pending_frames and keeps getting pulled until that
                    # is flushed too; sources that wait INSIDE frames()
                    # additionally poll lifecycle.pipeline_quiescing;
                    # sources with their own drain state machine
                    # (serversrc) opt out via OWNS_DRAIN and end their
                    # stream themselves.
                    break
                if el._interrupted.is_set():
                    # stale interrupt from an escalation whose pull
                    # completed anyway: consume it (see _supervised)
                    el._interrupted.clear()
                if watch is not None:
                    wd.begin(watch)
                try:
                    try:
                        frame = next(frames_it)
                    except StopIteration:
                        break
                    if self._stop_flag.is_set():
                        return
                    if not isinstance(frame, Event) and FAULTS.is_armed():
                        FAULTS.check(f"element.{el.name}.frames",
                                     interrupt=lambda: el.interrupted)
                finally:
                    if watch is not None:
                        # always clears the busy window (also on stream
                        # end), or the sweeper would flag a finished
                        # element's stale episode
                        wd.done(watch)
                if isinstance(frame, Event):
                    outs = el.handle_event(0, frame) or []
                    for sp, ev in outs:
                        if not self._route_one(seg, st, sp, ev):
                            return
                    continue
                if budget > 0:
                    if (pts_anchored and anchor is None
                            and frame.pts is not None):
                        anchor = time.monotonic() - frame.pts
                    stamp_deadline(frame, budget,
                                   anchor=anchor if pts_anchored else None)
                if self.tracer is not None:
                    self.tracer.stamp_source(frame)
                if not self._route_one(seg, st, 0, frame):
                    return
                FRAME_POOL.recycle(frame)
            for i in range(len(el.srcpads)):
                # EOS routing result intentionally unchecked: a fused
                # downstream finishing returns False (normal unwind), and
                # an external push fails only when already stopping
                self._route_one(seg, st, i, EOS())
            h = self.health_map.get(el.name)
            if h is not None and h.state == "running":
                h.state = "finished"

        def supervised_body():
            # source supervision: `restart` re-opens the element (the
            # flaky-camera case) and re-enters frames() from its current
            # state — fresh CapsEvents re-negotiate downstream; frames
            # emitted before the crash are NOT replayed.  `skip` cannot
            # resume a broken generator mid-frame, so sources treat it
            # as fail-stop.  Errors raised by FUSED DOWNSTREAM elements
            # never reach here: _dispatch handles them against their own
            # element and unwinds via a False return.
            while True:
                try:
                    return body()
                except (KeyboardInterrupt, SystemExit):
                    raise
                except BaseException as e:  # noqa: BLE001 — policy boundary
                    # a watchdog-interrupted hang (StallError) restarts
                    # under stall-policy=restart even when error-policy
                    # is the fail-stop default — same contract as the
                    # non-source path in _supervised
                    stall_restart = (
                        isinstance(e, StallError)
                        and el.props.get("stall-policy") == "restart")
                    if isinstance(e, StallError):
                        el._interrupted.clear()
                    if (el.props.get("error-policy") != "restart"
                            and not stall_restart):
                        raise
                    from ..core.resilience import is_transient

                    if not is_transient(e):
                        # fatal classification: a deterministic bug a
                        # restart cannot cure — fail fast instead of
                        # crash-looping through the budget (a source has
                        # no input frame to dead-letter)
                        raise
                    verdict = self._restart_element(el, e)
                    if verdict == "retry":
                        continue
                    if verdict == "stopping":
                        return
                    raise

        self._guard(el, supervised_body)

    def _run_chain_head(self, seg: _Seg) -> None:
        el = seg.chain[0]
        st = seg.states[el.name]
        box = el._mailbox
        # hot-loop constants, latched at start() like the mailbox itself
        # (part of the allocation diet: no per-frame getattr/hasattr)
        get_many = getattr(box, "get_many", None)
        has_qsize = hasattr(box, "qsize")
        idle = getattr(el, "handle_idle", None)
        # fused tails with deferred output: today unreachable in practice
        # (parking needs preferred_batch>1, which blocks fusion), but any
        # future element deferring output inside a fused chain must still
        # get its idle flush when the head's input goes quiet
        tail_idles = [
            (seg.states[e.name], e.handle_idle)
            for e in seg.chain[1:]
            if hasattr(e, "handle_idle")
        ]
        want = getattr(el, "preferred_batch", 1)
        batching = want > 1 and hasattr(el, "handle_frame_batch")
        wait_s = getattr(el, "batch_wait_s", 0.0)
        # async device feed: an element holding parked in-flight work
        # (the filter's completion window / staged ingest batch) gets a
        # short mailbox poll so completed batches emit promptly instead
        # of aging up to the full idle period at a live stream's tail
        pending = getattr(el, "pending_frames", None)
        stop_flag = self._stop_flag
        # items popped from the mailbox but not yet processed (bulk pops
        # can pull events/other-pad items past a batch boundary); lives
        # on the segment so halt-time accounting can count it
        stash = seg.stash
        stash.clear()
        while not stop_flag.is_set():
            if stash:
                pad, item = stash.popleft()
            else:
                try:
                    try:
                        # hot path: items queued — no pending_frames()
                        # probe, no lock, no timeout bookkeeping
                        pad, item = box.get_nowait()
                    except queue.Empty:
                        poll = 0.1
                        if pending is not None:
                            try:
                                if pending() > 0:
                                    poll = 0.02
                            except Exception:
                                self.log.exception(
                                    "pending_frames failed for %s", el.name)
                                pending = None
                        pad, item = box.get(timeout=poll)
                except queue.Empty:
                    # idle hook: elements holding deferred output (the
                    # filter's dispatch window) release it when the
                    # input goes quiet — a live stream's tail must not
                    # wait for the next frame or EOS
                    if idle is not None:
                        outs = idle() or []
                        if outs and not self._route_outs(seg, st, outs):
                            return
                    for t_st, t_idle in tail_idles:
                        try:
                            t_outs = t_idle() or []
                            if t_outs and not self._route_outs(
                                    seg, t_st, t_outs):
                                return
                        except BaseException as e:  # noqa: BLE001
                            self._fail(t_st.el, e)
                            return
                    continue
            if item is _STOP:
                return
            tracer = self.tracer
            if tracer is not None:
                if has_qsize:
                    try:
                        tracer.queue_level(
                            el.name, box.qsize(), getattr(box, "maxsize", 0),
                        )
                    except Exception:
                        self.log.debug(
                            "tracer queue_level failed", exc_info=True)
                if isinstance(item, TensorFrame):
                    # queue-wait histogram: enqueue stamp -> this dequeue
                    # (stash dwell counts too — the frame was waiting)
                    t_q = item.meta.pop(TL_QPUT_META, None)
                    if t_q is not None:
                        tracer.queue_wait(
                            el.name, time.perf_counter() - t_q)
            if batching and isinstance(item, TensorFrame):
                # micro-batching: batch-capable elements drain extra
                # queued frames and process them in one call (the TPU
                # dispatch-amortization lever; no reference analog).
                # batch-timeout > 0 waits to FILL the batch; 0 keeps the
                # lossless drain-what's-queued behavior
                deadline = time.monotonic() + wait_s
                frames = [item]
                # LOGICAL frame count: a block-ingest BatchFrame counts as
                # its batch_size, so max-batch bounds the invoke's batch
                # axis, not the queue-item count
                nlog = getattr(item, "batch_size", 1)
                while nlog < want:
                    # consume stashed items first (a previous bulk pop may
                    # have pulled qualifying frames); an event at the
                    # stash head ends the batch IN PLACE — never rotate
                    # it behind later items
                    if stash:
                        p2, nxt = stash[0]
                        if isinstance(nxt, TensorFrame) and p2 == pad:
                            frames.append(stash.popleft()[1])
                            nlog += getattr(nxt, "batch_size", 1)
                            continue
                        break
                    try:
                        wait = deadline - time.monotonic()
                        if get_many is not None:
                            chunk = get_many(
                                want - nlog, timeout=max(0.0, wait),
                            )
                        elif wait > 0:
                            chunk = [box.get(timeout=wait)]
                        else:
                            chunk = [box.get_nowait()]
                    except queue.Empty:
                        break
                    boundary = False
                    now_q = (
                        time.perf_counter() if tracer is not None else 0.0
                    )
                    for p2, nxt in chunk:
                        if tracer is not None and isinstance(
                                nxt, TensorFrame):
                            t_q = nxt.meta.pop(TL_QPUT_META, None)
                            if t_q is not None:
                                tracer.queue_wait(el.name, now_q - t_q)
                        if (not boundary
                                and isinstance(nxt, TensorFrame)
                                and p2 == pad
                                and nlog < want):
                            # nlog<want re-checked per item: blocks count
                            # as batch_size, so a bulk pop (item-granular)
                            # can overshoot the LOGICAL bound mid-chunk —
                            # the excess stashes for the next micro-batch
                            frames.append(nxt)
                            nlog += getattr(nxt, "batch_size", 1)
                        else:
                            # event/other-pad item ends the batch; it and
                            # everything popped after it run after, in order
                            boundary = True
                            stash.append((p2, nxt))
                    if boundary:
                        break
                if not el.BATCH_AWARE:
                    # same safety net as the per-frame branch: the block
                    # opt-in is BATCH_AWARE, not the mere presence of
                    # handle_frame_batch
                    frames = [
                        lf for f in frames for lf in (
                            f.split() if isinstance(f, BatchFrame)
                            else (f,)
                        )
                    ]
                frames = self._expire_late(el, frames)
                if not frames:
                    continue  # whole micro-batch expired
                st.in_call = sum(
                    getattr(f, "batch_size", 1) for f in frames)
                t_in = time.perf_counter() if tracer is not None else 0.0
                if tracer is not None:
                    tracer.frame_begin(el.name, frames[0])
                outs = self._supervised(
                    el,
                    lambda frames=frames, pad=pad:
                    el.handle_frame_batch(pad, frames) or [],
                    frames,
                    per_item=lambda f, pad=pad: (
                        el.handle_frame_batch(pad, [f]) or []),
                )
                if outs is self._SUPERVISED_STOPPING:
                    return
                if st.terminal:
                    st.delivered += sum(
                        getattr(f, "batch_size", 1) for f in frames)
                if tracer is not None:
                    tracer.frame_out(
                        el.name, t_in, time.perf_counter(),
                        sum(getattr(f, "batch_size", 1) for f in frames),
                        sum(frame_nbytes(f) for f in frames),
                        frames[0].meta.get(META_SRC_TS),
                        frame=frames[0],
                    )
                # inputs consumed (emitted / parked behind the element's
                # pending_frames hook / delivered): in_call transfers to
                # the unrouted outputs so a halt mid-route never
                # double-counts the filter's parked dispatch window
                st.in_call = self._outs_logical(outs)
                if not self._route_outs(seg, st, outs):
                    return
                st.in_call = 0
            else:
                if not self._dispatch(seg, st, pad, item):
                    return
                if isinstance(item, TensorFrame):
                    # the head owns the popped item's carcass once the
                    # dispatch chain is done with it
                    FRAME_POOL.recycle(item)
                if st.finished:
                    return
