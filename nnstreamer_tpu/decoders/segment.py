"""image_segment decoder: per-pixel class tensors -> RGBA color-map video.

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-imagesegment.c`` (665
LoC).  Option contract preserved (reference header :30-35):

- option1: mode — ``tflite-deeplab`` (class-score grid, argmax over channel),
  ``snpe-deeplab`` (already-argmaxed class-index grid),
  ``snpe-depth`` (single-channel depth map -> normalized grayscale)
- option2: max number of class labels, default 20 (Pascal VOC)

Output: RGBA (H, W, 4) with one palette color per class (alpha 160 so it
composites over the source video), background class 0 transparent.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..core.buffer import TensorFrame
from ..core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from . import util

_MODES = ("tflite-deeplab", "snpe-deeplab", "snpe-depth")


class ImageSegment:
    NAME = "image_segment"

    def __init__(self):
        self.mode = "tflite-deeplab"
        self.max_labels = 20

    def set_options(self, options: List[str]) -> None:
        o = list(options) + [""] * 9
        if o[0]:
            mode = o[0].strip()
            if mode not in _MODES:
                raise ValueError(f"image_segment: unknown mode {mode!r}")
            self.mode = mode
        if o[1]:
            try:
                self.max_labels = max(1, int(o[1]))
            except ValueError:
                pass

    def get_out_spec(self, in_spec: StreamSpec) -> StreamSpec:
        # H/W follow the input grid; static when the input spec is.
        if in_spec and in_spec.tensors and in_spec.tensors[0].is_static:
            shp = in_spec.tensors[0].shape
            h, w = int(shp[-3] if len(shp) >= 3 else shp[0]), int(shp[-2] if len(shp) >= 3 else shp[1])
            return StreamSpec(
                (TensorSpec((h, w, 4), np.uint8, "video_rgba"),),
                FORMAT_STATIC,
                in_spec.framerate,
            )
        from ..core.types import ANY
        return ANY

    def decode(self, frame: TensorFrame, in_spec) -> TensorFrame:
        t = np.asarray(frame.tensors[0])
        t = t.reshape(t.shape[-3], t.shape[-2], t.shape[-1]) if t.ndim > 3 else t

        if self.mode == "snpe-depth":
            depth = t.reshape(t.shape[0], t.shape[1]).astype(np.float64)
            lo, hi = depth.min(), depth.max()
            gray = np.zeros_like(depth, np.uint8) if hi <= lo else (
                ((depth - lo) / (hi - lo)) * 255.0).astype(np.uint8)
            rgba = np.stack([gray, gray, gray,
                             np.full_like(gray, 255)], axis=-1)
            out = frame.with_tensors([rgba])
            out.meta["depth_range"] = [float(lo), float(hi)]
            return out

        if self.mode == "tflite-deeplab" and t.ndim == 3 and t.shape[-1] > 1:
            classes = t.argmax(axis=-1)
        else:  # snpe-deeplab or already-argmaxed grid
            classes = t.reshape(t.shape[0], t.shape[1]).astype(np.int64)
        classes = np.clip(classes, 0, self.max_labels)

        return self._render_classes(frame, classes)

    def _render_classes(self, frame: TensorFrame,
                        classes: np.ndarray) -> TensorFrame:
        palette = np.zeros((self.max_labels + 1, 4), np.uint8)
        palette[1:] = [util.class_color(i) for i in range(self.max_labels)]
        palette[1:, 3] = 160  # semi-transparent overlay; class 0 transparent
        rgba = palette[classes]
        out = frame.with_tensors([rgba])
        present = np.unique(classes)
        out.meta["classes_present"] = [int(c) for c in present if c > 0]
        return out

    # -- device-fused half (pipeline fusion pass) ---------------------------
    def supports_device_fn(self) -> bool:
        # per-pixel argmax is the transfer-heavy mode worth fusing; the
        # other modes already ship index/depth grids.  uint8 wire grid
        # caps the class space at 255 (Pascal VOC default is 20).
        return self.mode == "tflite-deeplab" and self.max_labels <= 255

    def device_fn(self, outs, platform=None):
        """jit-traceable half: per-pixel argmax + clip on device, so a
        (H, W) uint8 class grid (~66 KB at deeplab 257) crosses the link
        instead of the (H, W, C) float score volume (~5.5 MB at C=21).
        Mirrors ``decode``'s tflite-deeplab branch
        (tensordec-imagesegment.c)."""
        import jax.numpy as jnp

        t = outs[0]
        if t.ndim == 3:  # single-frame invoke path: no batch axis
            t = t[None]
        t = jnp.reshape(t, (t.shape[0],) + tuple(t.shape[-3:]))
        classes = jnp.argmax(t, axis=-1)
        classes = jnp.clip(classes, 0, self.max_labels)
        return [classes.astype(jnp.uint8)]  # (B, H, W)

    def decode_fused(self, frame: TensorFrame, in_spec) -> TensorFrame:
        """Host finishing after device_fn: tensor is the class grid."""
        classes = np.asarray(frame.tensors[0], np.int64)
        classes = classes.reshape(classes.shape[-2], classes.shape[-1])
        return self._render_classes(frame, classes)
