"""Decoder subplugins (≙ ext/nnstreamer/tensor_decoder/).

Importing registers every decoder mode in the subplugin registry.
"""

from ..core import registry

registry.register_lazy(registry.KIND_DECODER, "direct_video", "nnstreamer_tpu.decoders.direct_video:DirectVideo")
registry.register_lazy(registry.KIND_DECODER, "image_labeling", "nnstreamer_tpu.decoders.image_label:ImageLabeling")
registry.register_lazy(registry.KIND_DECODER, "bounding_boxes", "nnstreamer_tpu.decoders.bounding_box:BoundingBoxes")
registry.register_lazy(registry.KIND_DECODER, "pose_estimation", "nnstreamer_tpu.decoders.pose:PoseEstimation")
registry.register_lazy(registry.KIND_DECODER, "image_segment", "nnstreamer_tpu.decoders.segment:ImageSegment")
registry.register_lazy(registry.KIND_DECODER, "tensor_region", "nnstreamer_tpu.decoders.tensor_region:TensorRegion")
registry.register_lazy(registry.KIND_DECODER, "octet_stream", "nnstreamer_tpu.decoders.octet:OctetStream")
registry.register_lazy(registry.KIND_DECODER, "flexbuf", "nnstreamer_tpu.decoders.serialize:FlexbufDecoder")
registry.register_lazy(registry.KIND_DECODER, "flatbuf", "nnstreamer_tpu.decoders.serialize:FlatbufDecoder")
registry.register_lazy(registry.KIND_DECODER, "protobuf", "nnstreamer_tpu.decoders.serialize:ProtobufDecoder")
registry.register_lazy(registry.KIND_DECODER, "python3", "nnstreamer_tpu.decoders.python3:Python3Decoder")
registry.register_lazy(registry.KIND_DECODER, "detokenizer", "nnstreamer_tpu.decoders.detokenizer:Detokenizer")
