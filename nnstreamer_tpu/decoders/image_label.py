"""image_labeling decoder: classification scores -> text label.

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-imagelabel.c`` — argmax
over the score tensor, map through a label file (option1), output
text/x-raw.  Label-file loading analog: ``tensordecutil.c``.

Output frame: tensor = [argmax index] (int32); ``meta["label"]`` carries the
text (the text/x-raw analog).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.buffer import TensorFrame
from ..core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from .util import load_labels


class ImageLabeling:
    NAME = "image_labeling"

    def __init__(self):
        self.labels: Optional[List[str]] = None

    def set_options(self, options):
        if options and options[0]:
            self.labels = load_labels(options[0])

    def get_out_spec(self, in_spec: StreamSpec) -> StreamSpec:
        return StreamSpec(
            (TensorSpec((1,), np.int32, "label_index"),),
            FORMAT_STATIC,
            in_spec.framerate if in_spec else None,
        )

    def decode(self, frame: TensorFrame, in_spec) -> TensorFrame:
        scores = np.asarray(frame.tensors[0]).reshape(-1)
        idx = int(np.argmax(scores))
        return self._emit(frame, idx, float(scores[idx]))

    def _emit(self, frame: TensorFrame, idx: int, score: float) -> TensorFrame:
        out = frame.with_tensors([np.asarray([idx], np.int32)])
        out.meta["label_index"] = idx
        out.meta["label_score"] = score
        if self.labels and idx < len(self.labels):
            out.meta["label"] = self.labels[idx]
        return out

    # -- device-fused half (pipeline fusion pass) ---------------------------
    def device_fn(self, outs, platform=None):
        """jit-traceable half, folded into the upstream filter's XLA
        program: fused argmax+max (Pallas row-reduction on TPU,
        ``ops/labeling.py``) so only (index, score) — 8 bytes/frame —
        ever crosses PCIe instead of the full score tensor.  ``platform``
        comes from the backend that compiles this (its actual device, not
        the process default).

        The pair is packed into ONE float32 (B, 2) tensor so the host
        boundary pays a single transfer per micro-batch instead of two —
        on a latency-bound link each extra output tensor is an extra
        round trip.  float32 holds the index exactly (class counts are
        << 2^24)."""
        import jax.numpy as jnp

        from ..ops.labeling import top1

        idx, score = top1(outs[0], platform=platform)
        return [
            jnp.stack(
                [idx.astype(jnp.float32), score.astype(jnp.float32)],
                axis=-1,
            )
        ]  # (B, 2)

    def decode_fused(self, frame: TensorFrame, in_spec) -> TensorFrame:
        """Host finishing after device_fn: tensor is [[idx, score]]."""
        packed = np.asarray(frame.tensors[0], np.float64).reshape(-1)
        return self._emit(frame, int(packed[0]), float(packed[1]))

    def decode_fused_batch(self, frame, in_spec):
        """Vectorized host finish for a whole block: one (B, 2) packed
        tensor in, one BatchFrame of (1,) label indices out, per-logical
        labels stamped into frames_info meta (decoder split-batches=false;
        at chip rates the per-frame fan-out dominates the decode)."""
        from ..core.buffer import BatchFrame

        packed = np.asarray(frame.tensors[0], np.float64).reshape(-1, 2)
        idx = packed[:, 0].astype(np.int32)
        labels = self.labels
        infos = []
        for j, (p, d, m) in enumerate(frame.frames_info):
            m2 = dict(m)
            i = int(idx[j])
            m2["label_index"] = i
            m2["label_score"] = float(packed[j, 1])
            if labels and i < len(labels):
                m2["label"] = labels[i]
            infos.append((p, d, m2))
        return BatchFrame(
            tensors=[idx[:, None]],
            pts=frame.pts, duration=frame.duration, meta=dict(frame.meta),
            frames_info=infos,
        )
