"""python3 decoder: user-scripted decode loaded from a .py file.

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-python3.cc`` (393 LoC) —
loads a user script whose class implements ``decode`` (and optionally
``getOutCaps``).  Contract here:

- option1: path to the script file
- the script defines either a class ``CustomDecoder`` (methods
  ``decode(self, tensors, meta) -> tensors-or-frame-dict`` and optionally
  ``get_out_spec(self, in_spec)`` / ``set_options(self, options)``) or a
  module-level function ``decode(tensors)``.
"""

from __future__ import annotations

import importlib.util
import os
from typing import List

import numpy as np

from ..core.buffer import TensorFrame
from ..core.types import ANY, StreamSpec


def _load_script(path: str):
    if not os.path.isfile(path):
        raise FileNotFoundError(f"python3 decoder script not found: {path}")
    name = "nns_tpu_decoder_" + os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class Python3Decoder:
    NAME = "python3"

    def __init__(self):
        self._impl = None
        self._fn = None

    def set_options(self, options: List[str]) -> None:
        if not options or not options[0]:
            raise ValueError("python3 decoder requires option1=<script.py>")
        mod = _load_script(options[0])
        if hasattr(mod, "CustomDecoder"):
            self._impl = mod.CustomDecoder()
            if hasattr(self._impl, "set_options"):
                self._impl.set_options(options[1:])
        elif hasattr(mod, "decode"):
            self._fn = mod.decode
        else:
            raise ValueError(
                f"{options[0]}: defines neither CustomDecoder nor decode()")

    def get_out_spec(self, in_spec: StreamSpec) -> StreamSpec:
        if self._impl is not None and hasattr(self._impl, "get_out_spec"):
            return self._impl.get_out_spec(in_spec)
        return ANY

    def decode(self, frame: TensorFrame, in_spec) -> TensorFrame:
        tensors = [np.asarray(t) for t in frame.tensors]
        if self._impl is not None:
            res = self._impl.decode(tensors, dict(frame.meta))
        else:
            res = self._fn(tensors)
        if isinstance(res, TensorFrame):
            return res
        if isinstance(res, dict):  # {"tensors": [...], "meta": {...}}
            out = frame.with_tensors([np.asarray(t) for t in res["tensors"]])
            out.meta.update(res.get("meta", {}))
            return out
        if not isinstance(res, (list, tuple)):
            res = [res]
        return frame.with_tensors([np.asarray(t) for t in res])
