"""octet_stream decoder: tensors -> raw application/octet-stream bytes.

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-octetstream.c`` —
concatenates every tensor's bytes into one octet buffer.
"""

from __future__ import annotations

import numpy as np

from ..core.buffer import TensorFrame
from ..core.types import FORMAT_FLEXIBLE, StreamSpec, TensorSpec


class OctetStream:
    NAME = "octet_stream"

    def set_options(self, options) -> None:
        pass

    def get_out_spec(self, in_spec: StreamSpec) -> StreamSpec:
        nbytes = in_spec.nbytes() if in_spec and in_spec.is_static else None
        tensors = ((TensorSpec((nbytes,), np.uint8, "octets"),)
                   if nbytes else ())
        return StreamSpec(tensors, FORMAT_FLEXIBLE,
                          in_spec.framerate if in_spec else None)

    def decode(self, frame: TensorFrame, in_spec) -> TensorFrame:
        payload = b"".join(np.ascontiguousarray(np.asarray(t)).tobytes()
                           for t in frame.tensors)
        return frame.with_tensors([np.frombuffer(payload, np.uint8)])
