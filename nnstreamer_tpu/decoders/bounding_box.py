"""bounding_boxes decoder: detection tensors -> RGBA box-overlay video.

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-boundingbox.c`` (2292
LoC).  Option contract preserved (header comment :28-92 of the reference):

- option1: box mode — ``mobilenet-ssd`` (alias ``tflite-ssd``),
  ``mobilenet-ssd-postprocess`` (alias ``tf-ssd``), ``ov-person-detection``,
  ``ov-face-detection``, ``yolov5``, ``yolov8``, ``mp-palm-detection``
- option2: label file path
- option3: mode-dependent (priors file / scales / thresholds — see per-mode
  docstrings)
- option4: video output dimension ``WIDTH:HEIGHT``
- option5: model input dimension ``WIDTH:HEIGHT``
- option6: tracking flag (carried in meta; no renderer-side ID persistence)
- option7: log flag (prints detections)

Output: one RGBA tensor (H, W, 4) with box outlines + label stamps, plus
``meta["boxes"]`` = list of ``{x, y, w, h, score, class, label}`` in output
coordinates — the machine-readable analog of the reference's video overlay.

Host path: vectorized numpy decode + per-class NMS.  Device path (pipeline
device-fusion pass, ``Pipeline._fuse_device_chains``): for the box modes
whose raw head is large (mobilenet-ssd with priors, yolov5/yolov8 with
~25k×85 candidate grids), ``device_fn`` folds box decode + score threshold +
top-k + batched per-class NMS (``ops/nms.py``) into the upstream filter's
XLA program, so only the surviving top-K boxes — a few KB — cross the
host↔device link instead of the multi-MB logits the reference transfers
before its host-side NMS loops (tensordec-boundingbox.c ``nms``).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..core.buffer import TensorFrame
from ..core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from . import util

_MODES = (
    "mobilenet-ssd", "tflite-ssd",
    "mobilenet-ssd-postprocess", "tf-ssd",
    "ov-person-detection", "ov-face-detection",
    "yolov5", "yolov8",
    "mp-palm-detection",
)

_DEFAULT_OUT = (640, 480)
_DEFAULT_IN = (300, 300)


def _floats(parts: List[str], defaults: List[float]) -> List[float]:
    out = list(defaults)
    for i, p in enumerate(parts[: len(defaults)]):
        if p:
            try:
                out[i] = float(p)
            except ValueError:
                pass
    return out


class BoundingBoxes:
    NAME = "bounding_boxes"

    def __init__(self):
        self.mode = "mobilenet-ssd"
        self.labels: Optional[List[str]] = None
        self.out_wh = _DEFAULT_OUT
        self.in_wh = _DEFAULT_IN
        self.option3 = ""
        self.tracking = False
        self.log = False
        self._priors: Optional[np.ndarray] = None
        self._anchors: Optional[np.ndarray] = None

    # -- configuration ------------------------------------------------------

    def set_options(self, options: List[str]) -> None:
        o = list(options) + [""] * 9
        if o[0]:
            mode = o[0].strip()
            if mode not in _MODES:
                raise ValueError(f"bounding_boxes: unknown mode {mode!r}")
            self.mode = mode
        if o[1]:
            self.labels = util.load_labels(o[1])
        self.option3 = o[2]
        self.out_wh = util.parse_wh(o[3], _DEFAULT_OUT)
        self.in_wh = util.parse_wh(o[4], _DEFAULT_IN)
        self.tracking = o[5].strip() in ("1", "true", "TRUE")
        self.log = o[6].strip() in ("1", "true", "TRUE")
        if self.mode in ("mobilenet-ssd", "tflite-ssd"):
            self._parse_ssd_option3()
        if self.mode == "mp-palm-detection":
            self._parse_palm_option3()

    def _parse_ssd_option3(self) -> None:
        """option3 = priors.txt[:sigmoid_thr:y_scale:x_scale:h_scale:w_scale
        [:iou_thr]] (reference :47-66)."""
        parts = self.option3.split(":") if self.option3 else [""]
        if parts[0]:
            self._priors = _load_box_priors(parts[0])
        (self.ssd_thr, self.ssd_ys, self.ssd_xs, self.ssd_hs, self.ssd_ws,
         self.ssd_iou) = _floats(parts[1:], [0.5, 10.0, 10.0, 5.0, 5.0, 0.5])

    def _parse_palm_option3(self) -> None:
        """option3 = score_thr[:num_layers:min_scale:max_scale:offset_x
        :offset_y:stride...] (reference :76-88)."""
        parts = self.option3.split(":") if self.option3 else []
        vals = _floats(parts, [0.5, 4, 1.0, 1.0, 0.5, 0.5])
        self.palm_thr = vals[0]
        self.palm_layers = int(vals[1])
        self.palm_min_scale, self.palm_max_scale = vals[2], vals[3]
        self.palm_offset = (vals[4], vals[5])
        strides = [int(float(p)) for p in parts[6:] if p]
        self.palm_strides = strides or [8, 16, 16, 16][: self.palm_layers]
        self._anchors = None  # regenerate lazily

    # -- decoder ABI ---------------------------------------------------------

    def get_out_spec(self, in_spec: StreamSpec) -> StreamSpec:
        w, h = self.out_wh
        return StreamSpec(
            (TensorSpec((h, w, 4), np.uint8, "video_rgba"),),
            FORMAT_STATIC,
            in_spec.framerate if in_spec else None,
        )

    def decode(self, frame: TensorFrame, in_spec) -> TensorFrame:
        tensors = [np.asarray(t) for t in frame.tensors]
        dets = self._detect(tensors)  # [N,6] x1,y1,x2,y2,score,cls in in_wh px
        dets = util.nms(dets, getattr(self, "ssd_iou", 0.5))
        return self._render(frame, dets)

    def _render(self, frame: TensorFrame, dets: np.ndarray) -> TensorFrame:
        """[N,6] detections in model-input px -> RGBA overlay + boxes meta."""
        dets = dets.reshape(-1, 6)
        if dets.size:
            dets = dets.copy()
            dets[:, :4] = util.scale_boxes(dets[:, :4], self.in_wh, self.out_wh)

        w, h = self.out_wh
        canvas = util.blank_canvas(w, h)
        boxes_meta = []
        for x1, y1, x2, y2, score, cls in dets:
            color = util.class_color(int(cls))
            util.draw_rect(canvas, x1, y1, x2, y2, color, thickness=2)
            label = (self.labels[int(cls)]
                     if self.labels and int(cls) < len(self.labels) else str(int(cls)))
            util.draw_label(canvas, x1 + 2, max(0, y1 - 8), label, color)
            boxes_meta.append({
                "x": float(x1), "y": float(y1),
                "w": float(x2 - x1), "h": float(y2 - y1),
                "score": float(score), "class": int(cls), "label": label,
            })
        out = frame.with_tensors([canvas])
        out.meta["boxes"] = boxes_meta
        out.meta["box_mode"] = self.mode
        if self.log and boxes_meta:
            from ..core.log import get_logger
            get_logger("decoder.bounding_boxes").info(
                "bounding_boxes[%s]: %d detections", self.mode, len(boxes_meta))
        return out

    # -- per-mode detection -> [N,6] (x1,y1,x2,y2,score,cls) in input px -----

    def _detect(self, tensors: List[np.ndarray]) -> np.ndarray:
        if self.mode in ("mobilenet-ssd", "tflite-ssd"):
            return self._detect_mobilenet_ssd(tensors)
        if self.mode in ("mobilenet-ssd-postprocess", "tf-ssd"):
            return self._detect_postprocess(tensors)
        if self.mode.startswith("ov-"):
            return self._detect_openvino(tensors)
        if self.mode == "yolov5":
            return self._detect_yolo(tensors[0], has_objectness=True)
        if self.mode == "yolov8":
            return self._detect_yolo(tensors[0], has_objectness=False)
        if self.mode == "mp-palm-detection":
            return self._detect_palm(tensors)
        raise ValueError(self.mode)

    def _detect_mobilenet_ssd(self, tensors) -> np.ndarray:
        """tensors = [locations [P,4] (yc,xc,h,w offsets), scores [P,C]];
        priors from option3 file; reference ``update_mobilenet_ssd``."""
        loc = tensors[0].reshape(-1, 4).astype(np.float64)
        scores = tensors[1].reshape(loc.shape[0], -1).astype(np.float64)
        if self._priors is None:
            raise ValueError("mobilenet-ssd requires box-priors file (option3)")
        pri = self._priors  # [P,4] = yc, xc, h, w
        yc = loc[:, 0] / self.ssd_ys * pri[:, 2] + pri[:, 0]
        xc = loc[:, 1] / self.ssd_xs * pri[:, 3] + pri[:, 1]
        hh = np.exp(loc[:, 2] / self.ssd_hs) * pri[:, 2]
        ww = np.exp(loc[:, 3] / self.ssd_ws) * pri[:, 3]
        w_in, h_in = self.in_wh
        x1 = (xc - ww / 2) * w_in
        y1 = (yc - hh / 2) * h_in
        x2 = (xc + ww / 2) * w_in
        y2 = (yc + hh / 2) * h_in
        probs = util.sigmoid(scores)
        cls = probs.argmax(axis=1)
        best = probs.max(axis=1)
        keep = best >= self.ssd_thr
        return np.stack(
            [x1[keep], y1[keep], x2[keep], y2[keep], best[keep],
             cls[keep].astype(np.float64)], axis=1)

    def _detect_postprocess(self, tensors) -> np.ndarray:
        """Already-decoded SSD head: [boxes [N,4] (ymin,xmin,ymax,xmax, 0..1),
        classes [N], scores [N], count [1]]; option3 may remap tensor order
        as ``%i:%i:%i:%i,%i`` (reference :68-75)."""
        order = [0, 1, 2, 3]
        if self.option3:
            try:
                nums = [int(n) for n in self.option3.replace(",", ":").split(":")]
                order[: len(nums[:4])] = nums[:4]  # partial lists keep defaults
            except ValueError:
                pass
        boxes = tensors[order[0]].reshape(-1, 4).astype(np.float64)
        classes = tensors[order[1]].reshape(-1).astype(np.float64)
        scores = tensors[order[2]].reshape(-1).astype(np.float64)
        n = boxes.shape[0]
        if len(tensors) > max(order[3], 3):
            n = min(n, int(np.asarray(tensors[order[3]]).reshape(-1)[0]))
        boxes, classes, scores = boxes[:n], classes[:n], scores[:n]
        keep = scores >= 0.5
        w_in, h_in = self.in_wh
        ymin, xmin, ymax, xmax = (boxes[keep, i] for i in range(4))
        return np.stack(
            [xmin * w_in, ymin * h_in, xmax * w_in, ymax * h_in,
             scores[keep], classes[keep]], axis=1)

    def _detect_openvino(self, tensors) -> np.ndarray:
        """[1,1,N,7] rows = (image_id, label, conf, xmin, ymin, xmax, ymax),
        coords normalized 0..1 (reference ov_person_detection)."""
        rows = tensors[0].reshape(-1, 7).astype(np.float64)
        keep = (rows[:, 0] >= 0) & (rows[:, 2] >= 0.5)
        rows = rows[keep]
        w_in, h_in = self.in_wh
        return np.stack(
            [rows[:, 3] * w_in, rows[:, 4] * h_in,
             rows[:, 5] * w_in, rows[:, 6] * h_in,
             rows[:, 2], rows[:, 1]], axis=1)

    def _detect_yolo(self, pred: np.ndarray, has_objectness: bool) -> np.ndarray:
        """yolov5: [N, 5+C] (cx,cy,w,h,obj,cls...); yolov8: [4+C, N] or
        [N, 4+C] (no objectness).  option3 = scaled:conf_thr:iou_thr
        (reference :42-66)."""
        parts = self.option3.split(":") if self.option3 else []
        scaled_f, conf_thr, iou_thr = _floats(parts, [0.0, 0.25, 0.45])
        self.ssd_iou = iou_thr  # reused by the NMS stage in decode()
        pred = np.asarray(pred, dtype=np.float64)
        pred = pred.reshape(-1, pred.shape[-1]) if pred.ndim > 2 else pred
        if not has_objectness:
            # yolov8 ships [4+C, N]; detect via label count when known,
            # else assume candidates outnumber channels
            ch = 4 + len(self.labels) if self.labels else None
            if (ch is not None and pred.shape[0] == ch and pred.shape[1] != ch) \
                    or (ch is None and pred.shape[0] < pred.shape[1]):
                pred = pred.T
        cx, cy, w, h = pred[:, 0], pred[:, 1], pred[:, 2], pred[:, 3]
        if has_objectness:
            conf = pred[:, 4:5] * pred[:, 5:]
        else:
            conf = pred[:, 4:]
        if conf.size == 0:  # no class columns: nothing to detect
            return np.zeros((0, 6))
        cls = conf.argmax(axis=1)
        score = conf.max(axis=1)
        if int(scaled_f) == 0:  # normalized 0..1 coords -> input px
            w_in, h_in = self.in_wh
            cx, w = cx * w_in, w * w_in
            cy, h = cy * h_in, h * h_in
        keep = score >= conf_thr
        return np.stack(
            [(cx - w / 2)[keep], (cy - h / 2)[keep],
             (cx + w / 2)[keep], (cy + h / 2)[keep],
             score[keep], cls[keep].astype(np.float64)], axis=1)

    def _detect_palm(self, tensors) -> np.ndarray:
        """MediaPipe palm detection: [boxes [N,18], scores [N]]; SSD anchors
        generated from stride config (reference mp_palm_detection_*)."""
        if self._anchors is None:
            self._anchors = _generate_palm_anchors(
                self.in_wh, self.palm_strides, self.palm_min_scale,
                self.palm_max_scale, self.palm_offset)
        raw = tensors[0].reshape(-1, tensors[0].shape[-1]).astype(np.float64)
        scores = util.sigmoid(tensors[1].reshape(-1).astype(np.float64))
        anchors = self._anchors[: raw.shape[0]]
        w_in, h_in = self.in_wh
        cx = raw[:, 0] / w_in + anchors[:, 0]
        cy = raw[:, 1] / h_in + anchors[:, 1]
        ww = raw[:, 2] / w_in * anchors[:, 2]  # anchor scale from option3
        hh = raw[:, 3] / h_in * anchors[:, 3]
        keep = scores >= self.palm_thr
        return np.stack(
            [(cx - ww / 2)[keep] * w_in, (cy - hh / 2)[keep] * h_in,
             (cx + ww / 2)[keep] * w_in, (cy + hh / 2)[keep] * h_in,
             scores[keep], np.zeros(int(keep.sum()))], axis=1)

    # -- device-fused half (pipeline fusion pass) ---------------------------
    # Max surviving candidates shipped to host per frame.  128 × 6 floats =
    # 3 KB vs e.g. yolov5's 25200×85 float head = 8.5 MB — a ~2800×
    # reduction in link traffic, which is exactly where a PCIe/tunnel-bound
    # deployment loses throughput.
    FUSED_TOPK = 128

    def supports_device_fn(self) -> bool:
        """Only the modes whose decode math is static-shape traceable (and
        whose raw head is big enough to be worth fusing) run on device;
        the rest keep the host path."""
        if self.mode in ("mobilenet-ssd", "tflite-ssd"):
            return self._priors is not None
        return self.mode in ("yolov5", "yolov8")

    def device_fn(self, outs, platform=None):
        """jit-traceable half, folded into the upstream filter's XLA
        program: box decode -> score threshold -> top-k preselect ->
        batched per-class NMS (``ops/nms.py``), all on device.  Returns
        [boxes (B,K,4) px, scores (B,K), classes (B,K)] with suppressed /
        padded rows carrying score 0."""
        import jax
        import jax.numpy as jnp

        from ..ops.nms import batched_nms

        if self.mode in ("mobilenet-ssd", "tflite-ssd"):
            boxes, scores, classes = self._device_ssd(outs)
            thr, iou = self.ssd_thr, self.ssd_iou
        else:
            parts = self.option3.split(":") if self.option3 else []
            scaled_f, thr, iou = _floats(parts, [0.0, 0.25, 0.45])
            boxes, scores, classes = self._device_yolo(outs, scaled_f)
        scores = jnp.where(scores >= thr, scores, 0.0)
        k = min(self.FUSED_TOPK, scores.shape[-1])
        top_s, idx = jax.lax.top_k(scores, k)
        top_b = jnp.take_along_axis(boxes, idx[..., None], axis=1)
        top_c = jnp.take_along_axis(classes, idx, axis=1)
        # per-class NMS (host util.nms semantics) via the class-offset
        # trick: shifting each class's boxes to a disjoint coordinate
        # island makes cross-class IoU zero
        island = jnp.float32(4 * max(*self.in_wh, *self.out_wh))
        keep = batched_nms(
            top_b + top_c[..., None] * island, top_s, iou_thr=float(iou)
        )
        top_s = jnp.where(keep, top_s, 0.0)
        return [top_b, top_s, top_c]

    def _device_ssd(self, outs):
        """mobilenet-ssd decode (``_detect_mobilenet_ssd``) in jnp, batched."""
        import jax
        import jax.numpy as jnp

        loc = outs[0]
        if loc.ndim == 2:  # single-frame invoke path: (P, 4), no batch
            loc = loc[None]
        loc = jnp.reshape(loc, (loc.shape[0], -1, 4)).astype(jnp.float32)
        pri = jnp.asarray(self._priors, jnp.float32)  # [P,4] = yc, xc, h, w
        scores = jnp.reshape(
            outs[1], (loc.shape[0], loc.shape[1], -1)
        ).astype(jnp.float32)
        yc = loc[..., 0] / self.ssd_ys * pri[:, 2] + pri[:, 0]
        xc = loc[..., 1] / self.ssd_xs * pri[:, 3] + pri[:, 1]
        hh = jnp.exp(loc[..., 2] / self.ssd_hs) * pri[:, 2]
        ww = jnp.exp(loc[..., 3] / self.ssd_ws) * pri[:, 3]
        w_in, h_in = self.in_wh
        boxes = jnp.stack(
            [(xc - ww / 2) * w_in, (yc - hh / 2) * h_in,
             (xc + ww / 2) * w_in, (yc + hh / 2) * h_in], axis=-1)
        probs = jax.nn.sigmoid(scores)
        return boxes, jnp.max(probs, -1), jnp.argmax(probs, -1).astype(jnp.float32)

    def _device_yolo(self, outs, scaled_f):
        """yolov5/yolov8 decode (``_detect_yolo``) in jnp, batched; layout
        heuristics run at trace time on static shapes."""
        import jax.numpy as jnp

        pred = outs[0].astype(jnp.float32)
        if pred.ndim == 2:
            pred = pred[None]
        if pred.ndim > 3:
            pred = jnp.reshape(pred, (pred.shape[0], -1, pred.shape[-1]))
        has_obj = self.mode == "yolov5"
        if not has_obj:
            ch = 4 + len(self.labels) if self.labels else None
            if (ch is not None and pred.shape[1] == ch and pred.shape[2] != ch) \
                    or (ch is None and pred.shape[1] < pred.shape[2]):
                pred = jnp.swapaxes(pred, 1, 2)
        if pred.shape[-1] <= (5 if has_obj else 4):  # no class columns
            B = pred.shape[0]
            return (jnp.zeros((B, 1, 4), jnp.float32),
                    jnp.zeros((B, 1), jnp.float32),
                    jnp.zeros((B, 1), jnp.float32))
        cx, cy, w, h = (pred[..., i] for i in range(4))
        conf = pred[..., 4:5] * pred[..., 5:] if has_obj else pred[..., 4:]
        cls = jnp.argmax(conf, -1).astype(jnp.float32)
        score = jnp.max(conf, -1)
        if int(scaled_f) == 0:  # normalized 0..1 coords -> input px
            w_in, h_in = self.in_wh
            cx, w = cx * w_in, w * w_in
            cy, h = cy * h_in, h * h_in
        boxes = jnp.stack(
            [cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
        return boxes, score, cls

    def decode_fused(self, frame: TensorFrame, in_spec) -> TensorFrame:
        """Host finishing after device_fn: tensors are [boxes, scores,
        classes]; NMS and thresholding already happened on device, so this
        is filter + render only."""
        b = np.asarray(frame.tensors[0], np.float64).reshape(-1, 4)
        s = np.asarray(frame.tensors[1], np.float64).reshape(-1)
        c = np.asarray(frame.tensors[2], np.float64).reshape(-1)
        keep = s > 0
        dets = np.concatenate(
            [b[keep], s[keep, None], c[keep, None]], axis=1)
        # top_k emits score-descending order already; keep it stable
        dets = dets[np.argsort(-dets[:, 4], kind="stable")]
        return self._render(frame, dets)


def _load_box_priors(path: str) -> np.ndarray:
    """box-priors.txt: 4 whitespace-separated rows (yc, xc, h, w) x P columns
    (reference ``mobilenet_ssd_load_box_priors``)."""
    rows = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            vals = [float(v) for v in line.split()]
            if vals:
                rows.append(vals)
    if len(rows) < 4:
        raise ValueError(f"box priors file {path!r} needs 4 rows, got {len(rows)}")
    return np.asarray(rows[:4], dtype=np.float64).T  # [P,4]


def _generate_palm_anchors(in_wh: Tuple[int, int], strides, min_scale: float,
                           max_scale: float, offset) -> np.ndarray:
    """SSD anchor generation (MediaPipe ssd_anchors_calculator semantics):
    per stride layer, a grid of (W/stride x H/stride) centers, 2 anchors each
    for the repeated-stride layers."""
    w_in, h_in = in_wh
    anchors = []
    n = len(strides)
    for i, stride in enumerate(strides):
        scale = (min_scale + (max_scale - min_scale) * i / max(1, n - 1))
        # MediaPipe emits 2 anchors per location on every layer (aspect 1.0
        # + the interpolated-scale anchor)
        reps = 2
        gw, gh = max(1, w_in // stride), max(1, h_in // stride)
        ys, xs = np.meshgrid(np.arange(gh), np.arange(gw), indexing="ij")
        cx = ((xs + offset[0]) / gw).reshape(-1)
        cy = ((ys + offset[1]) / gh).reshape(-1)
        for _ in range(reps):
            anchors.append(np.stack([cx, cy,
                                     np.full_like(cx, scale),
                                     np.full_like(cy, scale)], axis=1))
    return np.concatenate(anchors, axis=0)
