"""detokenizer decoder: int32 token ids -> text bytes (net-new).

Inverse of the tokenizer converter (converters/tokenizer.py): byte-level
ids (0-255) become utf-8-ish bytes; out-of-range ids clamp to '?'.  The
decoded text also lands in ``meta["text"]`` (mirroring image_labeling's
``meta["label"]`` contract) so sinks can read it without byte-wrangling.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..core.buffer import TensorFrame
from ..core.types import FORMAT_FLEXIBLE, StreamSpec


class Detokenizer:
    NAME = "detokenizer"

    def set_options(self, options: List[str]) -> None:
        pass

    def get_out_spec(self, in_spec: StreamSpec) -> StreamSpec:
        return StreamSpec((), FORMAT_FLEXIBLE,
                          in_spec.framerate if in_spec else None)

    def decode(self, frame: TensorFrame, in_spec) -> TensorFrame:
        toks = np.asarray(frame.tensors[0]).ravel()
        ok = (toks >= 0) & (toks < 256)
        data = np.where(ok, toks, ord("?")).astype(np.uint8)
        out = frame.with_tensors([data])
        out.meta["media_type"] = "text"
        out.meta["text"] = data.tobytes().decode("utf-8", errors="replace")
        return out
