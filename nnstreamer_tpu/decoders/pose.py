"""pose_estimation decoder: heatmap tensors -> keypoint skeleton overlay.

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-pose.c`` (845 LoC).
Option contract preserved (reference header :29-60):

- option1: video output dimension ``WIDTH:HEIGHT``
- option2: model input dimension ``WIDTH:HEIGHT``
- option3: keypoint label file (optional)
- option4: mode — ``heatmap-only`` (default) or ``heatmap-offset``
  (PoseNet-style: tensors = [heatmap [h,w,K], offsets [h,w,2K]])

Output: RGBA (H, W, 4) overlay with keypoint dots + skeleton edges, plus
``meta["keypoints"]`` = [[x, y, score], ...] in output coordinates.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.buffer import TensorFrame
from ..core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from . import util

_DEFAULT_OUT = (640, 480)
_DEFAULT_IN = (257, 257)

# COCO-17 skeleton edges (keypoint index pairs); used when K == 17.
_COCO_EDGES = (
    (0, 1), (0, 2), (1, 3), (2, 4), (5, 6), (5, 7), (7, 9), (6, 8), (8, 10),
    (5, 11), (6, 12), (11, 12), (11, 13), (13, 15), (12, 14), (14, 16),
)
# 14-keypoint (MPII-like) skeleton; used when K == 14.
_MPII_EDGES = (
    (0, 1), (1, 2), (2, 3), (3, 4), (1, 5), (5, 6), (6, 7), (1, 8),
    (8, 9), (9, 10), (1, 11), (11, 12), (12, 13),
)


class PoseEstimation:
    NAME = "pose_estimation"

    def __init__(self):
        self.out_wh = _DEFAULT_OUT
        self.in_wh = _DEFAULT_IN
        self.labels: Optional[List[str]] = None
        self.mode = "heatmap-only"

    def set_options(self, options: List[str]) -> None:
        o = list(options) + [""] * 9
        self.out_wh = util.parse_wh(o[0], _DEFAULT_OUT)
        self.in_wh = util.parse_wh(o[1], _DEFAULT_IN)
        if o[2]:
            self.labels = util.load_labels(o[2])
        if o[3]:
            mode = o[3].strip()
            if mode not in ("heatmap-only", "heatmap-offset"):
                raise ValueError(f"pose_estimation: unknown option4 {mode!r}")
            self.mode = mode

    def get_out_spec(self, in_spec: StreamSpec) -> StreamSpec:
        w, h = self.out_wh
        return StreamSpec(
            (TensorSpec((h, w, 4), np.uint8, "video_rgba"),),
            FORMAT_STATIC,
            in_spec.framerate if in_spec else None,
        )

    def decode(self, frame: TensorFrame, in_spec) -> TensorFrame:
        heat = np.asarray(frame.tensors[0], dtype=np.float64)
        heat = heat.reshape(heat.shape[-3], heat.shape[-2], heat.shape[-1])
        gh, gw, k = heat.shape
        flat = heat.reshape(-1, k)
        best = flat.argmax(axis=0)  # [K] flattened grid index per keypoint
        gy, gx = best // gw, best % gw
        score = util.sigmoid(flat[best, np.arange(k)])

        # grid -> model-input pixel coords
        x_in = (gx + 0.5) / gw * self.in_wh[0]
        y_in = (gy + 0.5) / gh * self.in_wh[1]
        if self.mode == "heatmap-offset" and len(frame.tensors) > 1:
            # PoseNet offsets: [gh, gw, 2K], first K rows = y, last K = x
            off = np.asarray(frame.tensors[1], dtype=np.float64)
            off = off.reshape(gh, gw, 2 * k)
            y_in = gy / max(1, gh - 1) * self.in_wh[1] + off[gy, gx, np.arange(k)]
            x_in = gx / max(1, gw - 1) * self.in_wh[0] + off[gy, gx, np.arange(k) + k]

        return self._render(frame, x_in, y_in, score)

    def _render(self, frame: TensorFrame, x_in, y_in, score) -> TensorFrame:
        """Keypoints in model-input px -> RGBA overlay + keypoints meta."""
        k = len(score)
        sx = self.out_wh[0] / max(1, self.in_wh[0])
        sy = self.out_wh[1] / max(1, self.in_wh[1])
        x_out, y_out = x_in * sx, y_in * sy

        w, h = self.out_wh
        canvas = util.blank_canvas(w, h)
        edges = _COCO_EDGES if k == 17 else _MPII_EDGES if k == 14 else ()
        bone = (0, 200, 0, 255)
        for a, b in edges:
            if score[a] >= 0.3 and score[b] >= 0.3:
                util.draw_line(canvas, x_out[a], y_out[a], x_out[b], y_out[b], bone)
        for i in range(k):
            if score[i] >= 0.3:
                util.draw_dot(canvas, x_out[i], y_out[i],
                              util.class_color(i), radius=2)

        out = frame.with_tensors([canvas])
        out.meta["keypoints"] = [
            [float(x_out[i]), float(y_out[i]), float(score[i])] for i in range(k)
        ]
        if self.labels:
            out.meta["keypoint_labels"] = self.labels[:k]
        return out

    # -- device-fused half (pipeline fusion pass) ---------------------------
    def supports_device_fn(self) -> bool:
        return True  # both heatmap modes are static-shape traceable

    def device_fn(self, outs, platform=None):
        """jit-traceable half, folded into the upstream filter's XLA
        program: per-keypoint argmax + offset gather on device, so one
        (B, K, 3) [x_in, y_in, score] tensor — ~200 bytes/frame — crosses
        the link instead of the full heatmap/offset stack (PoseNet 257:
        ~4.5 MB/frame).  Mirrors ``decode`` (tensordec-pose.c math)."""
        import jax
        import jax.numpy as jnp

        heat = outs[0].astype(jnp.float32)
        if heat.ndim == 3:  # single-frame invoke path: no batch axis
            heat = heat[None]
        heat = jnp.reshape(heat, (heat.shape[0],) + tuple(heat.shape[-3:]))
        B, gh, gw, k = heat.shape
        flat = jnp.reshape(heat, (B, gh * gw, k))
        best = jnp.argmax(flat, axis=1)                      # (B, K)
        score = jax.nn.sigmoid(jnp.max(flat, axis=1))        # (B, K)
        gy, gx = best // gw, best % gw
        x_in = (gx + 0.5) / gw * self.in_wh[0]
        y_in = (gy + 0.5) / gh * self.in_wh[1]
        if self.mode == "heatmap-offset" and len(outs) > 1:
            off = outs[1].astype(jnp.float32)
            if off.ndim == 3:
                off = off[None]
            off = jnp.reshape(off, (B, gh * gw, 2 * k))
            # per keypoint i: off[b, best[b,i], i] (y) / [.., i+k] (x)
            at_best = jnp.take_along_axis(
                off, best[:, :, None], axis=1)               # (B, K, 2K)
            ks = jnp.arange(k)[None, :, None]
            off_y = jnp.take_along_axis(at_best, ks, axis=2)[..., 0]
            off_x = jnp.take_along_axis(at_best, ks + k, axis=2)[..., 0]
            y_in = gy / max(1, gh - 1) * self.in_wh[1] + off_y
            x_in = gx / max(1, gw - 1) * self.in_wh[0] + off_x
        return [
            jnp.stack(
                [x_in.astype(jnp.float32), y_in.astype(jnp.float32), score],
                axis=-1,
            )
        ]  # (B, K, 3)

    def decode_fused(self, frame: TensorFrame, in_spec) -> TensorFrame:
        """Host finishing after device_fn: tensor is (K, 3) x/y/score."""
        arr = np.asarray(frame.tensors[0], np.float64).reshape(-1, 3)
        return self._render(frame, arr[:, 0], arr[:, 1], arr[:, 2])
