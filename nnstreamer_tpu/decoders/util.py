"""Shared decoder helpers: labels, geometry, NMS, RGBA rasterizing.

Reference analog: ``ext/nnstreamer/tensor_decoder/tensordecutil.c`` (label
loading, font rasterizing) plus the NMS/IoU helpers embedded in
``tensordec-boundingbox.c``.  Here the raster path is vectorized numpy and the
NMS is a single vectorized IoU matrix pass instead of per-box C loops.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


def load_labels(path: str) -> List[str]:
    """Load one label per line (reference: tensordecutil.c loadImageLabels)."""
    with open(path, "r", encoding="utf-8") as f:
        return [ln.strip() for ln in f if ln.strip()]


def parse_wh(text: str, default: Tuple[int, int]) -> Tuple[int, int]:
    """Parse ``WIDTH:HEIGHT`` (option4/option5 of the reference decoders)."""
    if not text:
        return default
    parts = text.split(":")
    try:
        w = int(parts[0]) if parts[0] else default[0]
        h = int(parts[1]) if len(parts) > 1 and parts[1] else default[1]
        return w, h
    except ValueError:
        return default


def sigmoid(x: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-np.clip(x, -50.0, 50.0)))


def iou_matrix(boxes: np.ndarray) -> np.ndarray:
    """Pairwise IoU for boxes given as [N,4] = (x1, y1, x2, y2)."""
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = np.maximum(0.0, x2 - x1) * np.maximum(0.0, y2 - y1)
    ix1 = np.maximum(x1[:, None], x1[None, :])
    iy1 = np.maximum(y1[:, None], y1[None, :])
    ix2 = np.minimum(x2[:, None], x2[None, :])
    iy2 = np.minimum(y2[:, None], y2[None, :])
    inter = np.maximum(0.0, ix2 - ix1) * np.maximum(0.0, iy2 - iy1)
    union = area[:, None] + area[None, :] - inter
    return np.where(union > 0, inter / np.maximum(union, 1e-9), 0.0)


def nms(dets: np.ndarray, iou_threshold: float = 0.5,
        per_class: bool = True) -> np.ndarray:
    """Greedy non-max suppression.

    ``dets``: [N,6] = (x1, y1, x2, y2, score, class).  Returns the surviving
    rows sorted by descending score.  Matches the reference semantics
    (tensordec-boundingbox.c ``nms()``: sort by score, suppress same-class
    overlaps above the threshold).
    """
    if dets.size == 0:
        return dets.reshape(0, 6)
    order = np.argsort(-dets[:, 4], kind="stable")
    dets = dets[order]
    iou = iou_matrix(dets[:, :4])
    n = dets.shape[0]
    keep = np.ones(n, dtype=bool)
    for i in range(n):
        if not keep[i]:
            continue
        over = iou[i] > iou_threshold
        if per_class:
            over &= dets[:, 5] == dets[i, 5]
        over[: i + 1] = False
        keep &= ~over
    return dets[keep]


# -- RGBA raster helpers ----------------------------------------------------

# 20-color palette for classes (RGBA); wraps around for more classes.
PALETTE = np.asarray(
    [
        (230, 25, 75, 255), (60, 180, 75, 255), (255, 225, 25, 255),
        (0, 130, 200, 255), (245, 130, 48, 255), (145, 30, 180, 255),
        (70, 240, 240, 255), (240, 50, 230, 255), (210, 245, 60, 255),
        (250, 190, 212, 255), (0, 128, 128, 255), (220, 190, 255, 255),
        (170, 110, 40, 255), (255, 250, 200, 255), (128, 0, 0, 255),
        (170, 255, 195, 255), (128, 128, 0, 255), (255, 215, 180, 255),
        (0, 0, 128, 255), (128, 128, 128, 255),
    ],
    dtype=np.uint8,
)


def class_color(cls: int) -> np.ndarray:
    return PALETTE[int(cls) % len(PALETTE)]


def blank_canvas(width: int, height: int) -> np.ndarray:
    """Transparent RGBA canvas (the reference draws overlays on RGBA video)."""
    return np.zeros((height, width, 4), dtype=np.uint8)


def draw_rect(canvas: np.ndarray, x1: int, y1: int, x2: int, y2: int,
              color: Sequence[int], thickness: int = 1) -> None:
    """Draw an axis-aligned rectangle outline in-place."""
    h, w = canvas.shape[:2]
    x1, x2 = sorted((int(np.clip(x1, 0, w - 1)), int(np.clip(x2, 0, w - 1))))
    y1, y2 = sorted((int(np.clip(y1, 0, h - 1)), int(np.clip(y2, 0, h - 1))))
    c = np.asarray(color, dtype=np.uint8)
    t = max(1, thickness)
    canvas[y1:min(y1 + t, h), x1:x2 + 1] = c
    canvas[max(y2 - t + 1, 0):y2 + 1, x1:x2 + 1] = c
    canvas[y1:y2 + 1, x1:min(x1 + t, w)] = c
    canvas[y1:y2 + 1, max(x2 - t + 1, 0):x2 + 1] = c


def draw_dot(canvas: np.ndarray, x: int, y: int, color: Sequence[int],
             radius: int = 2) -> None:
    h, w = canvas.shape[:2]
    x, y = int(x), int(y)
    x1, x2 = max(0, x - radius), min(w, x + radius + 1)
    y1, y2 = max(0, y - radius), min(h, y + radius + 1)
    if x1 < x2 and y1 < y2:
        canvas[y1:y2, x1:x2] = np.asarray(color, dtype=np.uint8)


def draw_line(canvas: np.ndarray, x1: int, y1: int, x2: int, y2: int,
              color: Sequence[int]) -> None:
    """Bresenham-free line: sample along the segment (overlay quality only)."""
    n = int(max(abs(x2 - x1), abs(y2 - y1), 1))
    xs = np.linspace(x1, x2, n + 1).round().astype(int)
    ys = np.linspace(y1, y2, n + 1).round().astype(int)
    h, w = canvas.shape[:2]
    ok = (xs >= 0) & (xs < w) & (ys >= 0) & (ys < h)
    canvas[ys[ok], xs[ok]] = np.asarray(color, dtype=np.uint8)


# 5x7 bitmap font for box labels (digits, upper-case, a few symbols).
# Reference rasterizes label text with a baked-in font (tensordecutil.c
# ``rasters``); this is an original minimal glyph set, column-major bits.
_FONT = {
    "0": "0E1119151311E0", "1": "04060404040E00", "2": "0E11081060F100",
    "3": "0E110C01110E00", "4": "08182848FC0800", "5": "1F101E01110E00",
    "6": "0E101E11110E00", "7": "1F010204080800", "8": "0E110E11110E00",
    "9": "0E11110F010E00",
}


def _glyph(ch: str) -> np.ndarray:
    """7x5 boolean bitmap for a character; generated procedurally for
    letters (coarse but legible), table-driven for digits."""
    if ch in _FONT:
        rows = bytes.fromhex(_FONT[ch])[:7]
        return np.array([[(r >> (4 - c)) & 1 for c in range(5)] for r in rows],
                        dtype=bool)
    # fallback: filled 3x5 block marker for unknown glyphs
    g = np.zeros((7, 5), dtype=bool)
    if ch.strip():
        g[1:6, 1:4] = True
    return g


def draw_label(canvas: np.ndarray, x: int, y: int, text: str,
               color: Sequence[int]) -> None:
    """Stamp a short text label (digits render as glyphs, letters as blocks)."""
    cx = int(x)
    for ch in text[:16]:
        g = _glyph(ch)
        h, w = canvas.shape[:2]
        y1, y2 = max(0, int(y)), min(h, int(y) + 7)
        x1, x2 = max(0, cx), min(w, cx + 5)
        if y2 > y1 and x2 > x1:
            sub = g[: y2 - y1, : x2 - x1]
            region = canvas[y1:y2, x1:x2]
            region[sub] = np.asarray(color, dtype=np.uint8)
        cx += 6


def scale_boxes(boxes: np.ndarray, in_wh: Tuple[int, int],
                out_wh: Tuple[int, int]) -> np.ndarray:
    """Rescale [N,>=4] (x1,y1,x2,y2,...) from model-input to output coords."""
    if boxes.size == 0:
        return boxes
    sx = out_wh[0] / max(1, in_wh[0])
    sy = out_wh[1] / max(1, in_wh[1])
    out = boxes.astype(np.float64).copy()
    out[:, [0, 2]] *= sx
    out[:, [1, 3]] *= sy
    return out
