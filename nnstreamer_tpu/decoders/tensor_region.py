"""tensor_region decoder: detection tensors -> crop-region info tensor.

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-tensor_region.c`` (784
LoC) — produces cropping info consumed by ``tensor_crop``.  Option contract
preserved (reference header :17-33):

- option1: number of crop regions to emit (default 1)
- option2: label file (carried to meta)
- option3: priors.txt[:thr:y_scale:x_scale:h_scale:w_scale:iou] — identical
  scheme to the bounding_boxes mobilenet-ssd mode
- option4: video *input* dimension ``WIDTH:HEIGHT`` (default 300:300;
  reference :40 — regions are emitted in input coordinates)

Output: int32 tensor [num_regions, 4] = (x, y, w, h) — exactly the crop-info
stream ``tensor_crop`` (elements/flow.py) consumes on its second sink pad.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.buffer import TensorFrame
from ..core.types import FORMAT_FLEXIBLE, StreamSpec, TensorSpec
from . import util
from .bounding_box import BoundingBoxes


class TensorRegion:
    NAME = "tensor_region"

    def __init__(self):
        self.num_regions = 1
        self.labels: Optional[List[str]] = None
        self._bb = BoundingBoxes()  # reuse the mobilenet-ssd decode math

    def set_options(self, options: List[str]) -> None:
        o = list(options) + [""] * 9
        if o[0]:
            try:
                self.num_regions = max(1, int(o[0]))
            except ValueError:
                pass
        if o[1]:
            self.labels = util.load_labels(o[1])
        # delegate: mode=mobilenet-ssd, option3 scheme shared verbatim;
        # option4 here is the INPUT dims (reference :40) — regions stay in
        # input coordinates for tensor_crop
        self._bb.set_options(["mobilenet-ssd", "", o[2], "", o[3]])

    def get_out_spec(self, in_spec: StreamSpec) -> StreamSpec:
        return StreamSpec(
            (TensorSpec((self.num_regions, 4), np.int32, "crop_info"),),
            FORMAT_FLEXIBLE,
            in_spec.framerate if in_spec else None,
        )

    def decode(self, frame: TensorFrame, in_spec) -> TensorFrame:
        tensors = [np.asarray(t) for t in frame.tensors]
        dets = util.nms(self._bb._detect(tensors), self._bb.ssd_iou)
        dets = dets[: self.num_regions]
        regions = np.zeros((len(dets), 4), np.int32)
        labels = []
        w_in, h_in = self._bb.in_wh
        for i, (x1, y1, x2, y2, score, cls) in enumerate(dets):
            # clamp to the image so tensor_crop truncates instead of shifting
            x1, y1 = max(0.0, x1), max(0.0, y1)
            x2, y2 = min(float(w_in), x2), min(float(h_in), y2)
            regions[i] = (int(x1), int(y1),
                          max(0, int(x2 - x1)), max(0, int(y2 - y1)))
            labels.append(self.labels[int(cls)]
                          if self.labels and int(cls) < len(self.labels)
                          else str(int(cls)))
        out = frame.with_tensors([regions])
        out.meta["region_labels"] = labels
        return out
