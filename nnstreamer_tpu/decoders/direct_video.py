"""direct_video decoder: uint8 tensor -> raw video frames.

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-directvideo.c`` —
re-interprets a uint8 tensor (C:W:H:N with C in {1=GRAY8,3=RGB,4=BGRx}) as
video/x-raw.  Here video frames *are* (H, W, C) uint8 arrays, so decode
validates + squeezes the batch dim and tags the frame as video.
"""

from __future__ import annotations

import numpy as np

from ..core.buffer import TensorFrame
from ..core.types import ANY, FORMAT_STATIC, StreamSpec, TensorSpec


class DirectVideo:
    NAME = "direct_video"

    def set_options(self, options):
        pass

    def get_out_spec(self, in_spec: StreamSpec) -> StreamSpec:
        if not in_spec.tensors:
            return ANY
        t = in_spec.tensors[0]
        shape = t.shape
        if len(shape) == 4 and shape[0] == 1:
            shape = shape[1:]
        if len(shape) != 3 or shape[-1] not in (1, 3, 4):
            raise ValueError(
                f"direct_video: expected (H,W,C) uint8 with C in 1/3/4, got {shape}"
            )
        return StreamSpec(
            (TensorSpec(shape, np.uint8, "video"),), FORMAT_STATIC, in_spec.framerate
        )

    def decode(self, frame: TensorFrame, in_spec) -> TensorFrame:
        arr = np.asarray(frame.tensors[0])
        if arr.ndim == 4 and arr.shape[0] == 1:
            arr = arr[0]
        if arr.dtype != np.uint8:
            raise ValueError(f"direct_video requires uint8, got {arr.dtype}")
        out = frame.with_tensors([arr])
        out.meta["media"] = "video"
        return out
