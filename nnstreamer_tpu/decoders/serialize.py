"""flexbuf / flatbuf / protobuf decoders: tensors -> self-describing bytes.

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-{flexbuf,flatbuf,
protobuf}.cc`` — serialize an ``other/tensors`` frame into a framework-
neutral byte schema so non-GStreamer consumers can parse it.

TPU-native shape: all three modes share this framework's canonical wire
format (``distributed/wire.py`` — the same schema the gRPC query/edge layer
speaks, analog of ``nnstreamer.proto`` / ``nnstreamer.fbs``), tagged with a
mode marker so the matching converter subplugin can round-trip.  Output is a
single uint8 tensor carrying the encoded frame.
"""

from __future__ import annotations

import numpy as np

from ..core.buffer import TensorFrame
from ..core.types import FORMAT_FLEXIBLE, StreamSpec
from ..distributed import wire


class _SerializeBase:
    NAME = "serialize"
    MEDIA = "other/wire"

    def set_options(self, options) -> None:
        pass

    def get_out_spec(self, in_spec: StreamSpec) -> StreamSpec:
        return StreamSpec((), FORMAT_FLEXIBLE,
                          in_spec.framerate if in_spec else None)

    def decode(self, frame: TensorFrame, in_spec) -> TensorFrame:
        payload = wire.encode_frame(frame)
        out = frame.with_tensors([np.frombuffer(payload, np.uint8)])
        out.meta["media_type"] = self.MEDIA
        return out


class FlexbufDecoder(_SerializeBase):
    NAME = "flexbuf"
    MEDIA = "other/flexbuf"


class FlatbufDecoder(_SerializeBase):
    NAME = "flatbuf"
    MEDIA = "other/flatbuf"


class ProtobufDecoder(_SerializeBase):
    NAME = "protobuf"
    MEDIA = "other/protobuf-tensor"
