"""flexbuf / flatbuf / protobuf decoders: tensors -> self-describing bytes.

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-{flexbuf,flatbuf,
protobuf}.cc`` — serialize an ``other/tensors`` frame into a framework-
neutral byte schema so non-GStreamer consumers can parse it.

TPU-native shape: the flexbuf mode uses this framework's canonical wire
format (``distributed/wire.py``); the protobuf mode emits the PUBLIC
``nns_tensors.proto`` schema (``distributed/protobuf_codec.py``) and the
flatbuf mode emits the reference's ACTUAL ``nnstreamer.fbs`` binary
schema (``distributed/flatbuf_codec.py``) so peers with only a
protobuf/flatbuffers runtime can parse the stream — the reference's
``tensordec-{protobuf,flatbuf}.cc`` interop contracts.  Output is a
single uint8 tensor carrying the encoded frame; the matching converter
subplugin (converters/serialize.py) is the exact inverse.
"""

from __future__ import annotations

import numpy as np

from ..core.buffer import TensorFrame
from ..core.types import FORMAT_FLEXIBLE, StreamSpec
from ..distributed import wire


class _SerializeBase:
    NAME = "serialize"
    MEDIA = "other/wire"
    IDL = "flex"  # wire.get_codec name

    def set_options(self, options) -> None:
        pass

    def get_out_spec(self, in_spec: StreamSpec) -> StreamSpec:
        return StreamSpec((), FORMAT_FLEXIBLE,
                          in_spec.framerate if in_spec else None)

    def decode(self, frame: TensorFrame, in_spec) -> TensorFrame:
        encode, _ = wire.get_codec(self.IDL)
        payload = encode(frame)
        out = frame.with_tensors([np.frombuffer(payload, np.uint8)])
        out.meta["media_type"] = self.MEDIA
        return out


class FlexbufDecoder(_SerializeBase):
    NAME = "flexbuf"
    MEDIA = "other/flexbuf"


class FlatbufDecoder(_SerializeBase):
    NAME = "flatbuf"
    MEDIA = "other/flatbuf"
    IDL = "flatbuf"  # real nnstreamer.fbs schema, not the NNSQ framing


class ProtobufDecoder(_SerializeBase):
    NAME = "protobuf"
    MEDIA = "other/protobuf-tensor"
    IDL = "protobuf"
