"""gRPC transport: query (request/response) and edge (pub/sub) services.

Reference architecture (SURVEY §2.3, §3.5): the query elements delegate
transport to nnstreamer-edge (TCP/MQTT/AITT) with a caps handshake before
data and ``client_id`` routing back to the right client
(``tensor_query_client.c:487-542``, ``tensor_query_serversink.c:237-274``);
a process-global registry pairs serversrc/serversink by id
(``tensor_query_server.c:24-100``).  The grpc elements
(``ext/nnstreamer/tensor_source/tensor_src_grpc.c``) speak protobuf IDL.

TPU build: one gRPC data plane for both roles, using generic method
handlers (no codegen) over the :mod:`.wire` framing:

  /nns.Query/Handshake  unary   — client caps string -> server caps string
  /nns.Query/Invoke     unary   — frame bytes -> answer frame bytes
  /nns.Edge/Publish     unary   — push a frame to a topic (broker mode)
  /nns.Edge/Subscribe   stream  — topic -> stream of frame bytes

The unary Invoke carries the client routing implicitly (the RPC context IS
the return path), which collapses the reference's client_id bookkeeping;
client_id meta is still attached for in-pipeline visibility and parity.
"""

from __future__ import annotations

import contextlib
import itertools
import queue
from collections import deque
import threading
import time
from concurrent import futures
from typing import Any, Callable, Dict, List, Optional, Tuple

import grpc

from ..core.buffer import BatchFrame, TensorFrame
from ..core.lifecycle import ServerGoawayError
from ..core.liveness import (
    PRIORITY_MAX,
    PRIORITY_META,
    TENANT_META,
    ServerBusyError,
    TenantAdmissionController,
    clamp_priority,
    stamp_deadline,
)
from ..core.log import get_logger
from ..core.telemetry import SRV_SPAN_META, TL_INVOKE_META, TL_RX_META
from ..core.types import StreamSpec
from .wire import (
    WireCorruptionError,
    WireError,
    decode_frame,
    decode_frames,
    encode_frame,
    encode_frames,
    is_batch_payload,
)

log = get_logger("distributed")


class CapsMismatch(ValueError):
    """Client/server schemas parse but do not intersect."""

_ident = lambda b: b  # bytes-in/bytes-out (de)serializers  # noqa: E731
identity_codec = _ident  # shared by every gRPC element (query/edge/stream)
GRPC_OPTS = [
    ("grpc.max_receive_message_length", 512 * 1024 * 1024),
    ("grpc.max_send_message_length", 512 * 1024 * 1024),
]


# ---------------------------------------------------------------------------
# Server-side pairing registry (≙ tensor_query_server.c global table)
# ---------------------------------------------------------------------------
class QueryServerCore:
    """The in-process core pairing a serversrc (ingress) with a serversink
    (egress) and owning the gRPC server."""

    def __init__(self, port: int, host: str = "[::]"):
        self.port = port
        self.host = host
        self.ingress: "queue.Queue[Tuple[int, TensorFrame]]" = queue.Queue(64)
        self._pending: Dict[int, "queue.Queue[TensorFrame]"] = {}
        self._pending_lock = threading.Lock()
        # client ids whose stream closed via the absent-'final'-key
        # heuristic (bounded; diagnosis only — see resolve())
        self._heuristic_closed: "deque[int]" = deque(maxlen=64)
        self._client_seq = itertools.count(1)
        self.caps: Optional[str] = None  # serversrc announces
        self._server: Optional[grpc.Server] = None
        self._tcp = None  # raw-TCP transport (tcp_query.TcpQueryServer)
        self.refs = 0
        # overload admission (core/liveness.py): default unlimited; the
        # serversrc's max-inflight/low-watermark/tenant-quota props
        # rebuild it.  Shed requests are refused with BUSY before
        # touching the ingress queue — overload answers in O(1) instead
        # of timing out deep in the pipeline.  Tenant identity and
        # priority ride the request meta (TENANT_META / PRIORITY_META),
        # so per-tenant quotas and weighted shedding work identically
        # over both transports with no wire-format change.
        self.admission = TenantAdmissionController(0)
        self.busy_retry_after = 0.05
        self.expired_drops = 0  # requests expired before ingest
        # data-plane integrity (Documentation/wire-protocol.md): both
        # transports verify request checksums at decode and refuse
        # corrupt requests ('C' on raw TCP / DATA_LOSS on gRPC) without
        # dying; the serversrc's verify-checksum / wire-version props
        # rebuild these before start()/start_tcp()
        self.verify_checksum = True
        self.wire_version = 2
        self.corrupt_requests = 0  # corrupt requests refused, all transports
        # rolling restart (core/lifecycle.py): a draining server refuses
        # NEW requests with GOAWAY ('G' on raw TCP / UNAVAILABLE+goaway
        # detail on gRPC) — an immediate, resend-safe failover signal
        # that never trips client breakers — while in-flight requests
        # finish normally; then the serversrc closes the listeners
        self.draining = False
        self.goaway_sent = 0  # requests refused with GOAWAY
        # hard stop: answer waits poll this so a handler thread blocked
        # on a stream the engine abandoned unwinds in ~0.25s instead of
        # wedging until its whole budget (a killed server must release
        # its reader threads promptly — the fleet kill-latency contract)
        self.closed = False

    # -- transport-agnostic handlers ----------------------------------------
    def check_caps(self, client_caps: str) -> str:
        """Caps handshake: intersect client/server schemas.  Raises
        :class:`CapsMismatch` on a genuine schema conflict and plain
        ``ValueError`` on unparseable caps.  Shared by every transport."""
        server_caps = self.caps or ""
        if server_caps and client_caps:
            a = StreamSpec.from_string(client_caps)
            b = StreamSpec.from_string(server_caps)
            if a.intersect(b) is None:
                raise CapsMismatch(
                    f"caps mismatch: client {client_caps} "
                    f"vs server {server_caps}"
                )
        return server_caps

    # -- rpc handlers -------------------------------------------------------
    def _handshake(self, request: bytes, context) -> bytes:
        try:
            return self.check_caps(request.decode()).encode()
        except CapsMismatch as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))

    @contextlib.contextmanager
    def _pending_client(self, frames: List[TensorFrame], qsize: int = 0):
        """Register a fresh client slot, stamp+inject the frames, and
        guarantee cleanup — the shared bookkeeping of the unary
        (:meth:`process`) and streaming (:meth:`_invoke_stream`) paths."""
        client_id = next(self._client_seq)
        answer_q: "queue.Queue[TensorFrame]" = queue.Queue(qsize)
        with self._pending_lock:
            self._pending[client_id] = answer_q
        try:
            for frame in frames:
                frame.meta["client_id"] = client_id
            for item in self._ingress_items(frames):
                self.ingress.put((client_id, item), timeout=10)
            yield answer_q
        finally:
            with self._pending_lock:
                self._pending.pop(client_id, None)

    def process(self, frames: List[TensorFrame], timeout: float
                ) -> List[TensorFrame]:
        """Route frames through the paired server pipeline and collect the
        answers in stream order.  Shared by every transport (gRPC unary
        handler, raw-TCP connection threads).  Raises TimeoutError when
        the pipeline produces no answer in time, :class:`ServerBusyError`
        when admission control sheds the request (before any ingest).

        Deadline QoS: each frame is stamped with the request's remaining
        budget (re-anchored on THIS host's clock — budgets cross the
        wire, instants don't), so server pipeline elements can expire
        late work BEFORE the invoke instead of burning chip time on an
        answer the client has already abandoned."""
        if self.draining:
            # checked BEFORE admission: the refusal must be O(1) and the
            # request provably never executed (resend-safe failover)
            self.goaway_sent += 1
            raise ServerGoawayError()
        tenant = self._admit(frames)
        try:
            budget = min(timeout, 300.0)
            # trace spans (core/telemetry.py): stamp the receive instant
            # (host-local, stripped at encode) so the answer can carry a
            # server-side DURATION decomposition back to the client
            rx = time.perf_counter()
            for frame in frames:
                stamp_deadline(frame, budget)
                frame.meta[TL_RX_META] = rx
            with self._pending_client(frames, qsize=len(frames)) as answer_q:
                answers = []
                deadline = time.monotonic() + budget
                for _ in frames:
                    answers.append(
                        self._await_answer(answer_q, deadline))
                self._stamp_server_spans(answers)
                return answers
        finally:
            self._release(tenant)

    def _await_answer(self, answer_q: "queue.Queue[TensorFrame]",
                      deadline: float) -> TensorFrame:
        """One answer off the client's queue, bounded by ``deadline``
        AND responsive to :attr:`closed`: short poll slices so a
        handler thread waiting on an answer that will never come (the
        server was hard-stopped mid-request) unwinds promptly instead
        of wedging ``stop()`` behind its whole budget."""
        while True:
            try:
                return answer_q.get(
                    timeout=min(0.25, max(0.0,
                                          deadline - time.monotonic())))
            except queue.Empty:
                if self.closed:
                    raise TimeoutError("server stopping") from None
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        "server pipeline produced no answer in time"
                    ) from None

    @staticmethod
    def request_identity(frames: List[TensorFrame]) -> Tuple[str, int]:
        """(tenant, priority) of one request, read from the first
        frame's meta — the identity rides the ordinary JSON meta blob,
        so it crosses both transports unchanged.  Absent keys degrade
        to the pre-tenancy semantics (unnamed tenant, priority 3)."""
        meta = frames[0].meta if frames else {}
        tenant = str(meta.get(TENANT_META, "") or "")
        priority = clamp_priority(meta.get(PRIORITY_META, PRIORITY_MAX))
        return tenant, priority

    def _admit(self, frames: List[TensorFrame]) -> str:
        """Tenant-aware admission for one request (both transports,
        unary + stream).  Raises :class:`ServerBusyError` carrying the
        per-tenant retry-after on any shed; returns the tenant to hand
        back to :meth:`_release`."""
        tenant, priority = self.request_identity(frames)
        adm = self.admission
        if isinstance(adm, TenantAdmissionController):
            adm.admit(tenant=tenant, priority=priority,
                      retry_after=self.busy_retry_after)
        elif not adm.try_admit():
            # a plain AdmissionController swapped in by tests/tools
            raise ServerBusyError(retry_after=self.busy_retry_after)
        return tenant

    def _release(self, tenant: str) -> None:
        adm = self.admission
        if isinstance(adm, TenantAdmissionController):
            adm.release(tenant=tenant)
        else:
            adm.release()

    @staticmethod
    def _stamp_server_spans(answers: List[TensorFrame]) -> None:
        """Fold the host-local stamps riding each answer's meta into the
        wire-safe duration dict ``SRV_SPAN_META`` ({"queue", "dispatch",
        "compute", "total"}, seconds — summing exactly to "total" so the
        client's end-to-end decomposition is additive).  Answers that
        never saw the stamps (meta-dropping elements, legacy peers) are
        left alone — the client then reports the whole round trip as
        wire time."""
        now = time.perf_counter()
        for a in answers:
            rx = a.meta.pop(TL_RX_META, None)
            inv = a.meta.pop(TL_INVOKE_META, None)
            if rx is None:
                continue
            total = max(0.0, now - rx)
            dispatch, compute = (inv if inv else (0.0, 0.0))
            # clamp into the measured window so queue (the remainder)
            # can never go negative and the sum stays exact
            compute = min(max(0.0, float(compute)), total)
            dispatch = min(max(0.0, float(dispatch)), total - compute)
            a.meta[SRV_SPAN_META] = {
                "queue": total - dispatch - compute,
                "dispatch": dispatch,
                "compute": compute,
                "total": total,
            }

    def client_live(self, client_id: int) -> bool:
        """True while the client's RPC/connection still waits for
        answers (the serversink's client-gone feedback probes this
        before cancelling a generation stream upstream)."""
        with self._pending_lock:
            return client_id in self._pending

    def process_stream(self, frame: TensorFrame, timeout: float):
        """One STREAMING request (transport-shared: gRPC ``InvokeStream``
        and the raw-TCP 'S' message): admit, inject the prompt, then
        yield answer frames as the server pipeline produces them until
        one carries ``meta["final"] is True`` (the tensor_generator
        chunk contract; an answer with NO ``final`` key — a plain 1:1
        graph — closes the stream after one message).

        Raises :class:`ServerGoawayError` / :class:`ServerBusyError`
        BEFORE any ingest (resend-safe refusals) and ``TimeoutError``
        when the pipeline goes silent mid-stream.  The request frame is
        deadline-stamped from the client's remaining budget (PR-2
        plumbing), so a continuous-batching generator can EVICT the
        stream with a typed expiry instead of decoding past the budget.
        Cleanup (pending slot, admission release) runs on ANY exit,
        including the transport abandoning the generator mid-yield."""
        if self.draining:
            self.goaway_sent += 1
            raise ServerGoawayError()
        tenant = self._admit([frame])
        try:
            # the CLIENT's deadline governs the whole stream (a long
            # generation is the point); hard backstop only against
            # deadline-less channels
            budget = min(timeout, 3600.0)
            rx = time.perf_counter()
            stamp_deadline(frame, budget)
            frame.meta[TL_RX_META] = rx
            with self._pending_client([frame]) as answer_q:
                deadline = time.monotonic() + budget
                while True:
                    ans = self._await_answer(answer_q, deadline)
                    # per-chunk span decomposition (each chunk's meta is
                    # a fresh copy of the request's, so "total" reads as
                    # time-since-request at that chunk)
                    self._stamp_server_spans([ans])
                    yield ans
                    if ans.meta.get("final", True):
                        if "final" not in ans.meta:
                            cid = ans.meta.get("client_id")
                            if cid is not None:
                                with self._pending_lock:
                                    self._heuristic_closed.append(cid)
                        return
        finally:
            self._release(tenant)

    def _ingress_items(self, frames: List[TensorFrame]) -> List[TensorFrame]:
        """block_ingress: a wire micro-batch becomes ONE BatchFrame so the
        server pipeline pays per-frame Python costs once per batch; falls
        back to per-frame injection when the batch is not uniform (mixed
        shapes/dtypes cannot share a batch axis)."""
        if not getattr(self, "block_ingress", False) or len(frames) <= 1:
            return frames
        import numpy as np

        # EXPLICIT uniformity check — np.stack would silently promote
        # mixed dtypes (and a count mismatch only raises one way), turning
        # the promised per-frame fallback into wrong batched inputs
        arrs = [[np.asarray(t) for t in f.tensors] for f in frames]
        sig0 = [(a.shape, a.dtype) for a in arrs[0]]
        for row in arrs[1:]:
            if [(a.shape, a.dtype) for a in row] != sig0:
                return frames
        stacked = [
            np.stack([row[i] for row in arrs]) for i in range(len(sig0))
        ]
        return [BatchFrame.from_frames(stacked, frames)]

    def _invoke(self, request: bytes, context) -> bytes:
        # wire micro-batch envelope: N frames ride one RPC (amortizes the
        # per-RPC transport cost); the server pipeline still sees N
        # ordinary frames, answers are collected back in stream order
        batched = is_batch_payload(request)
        try:
            frames = (decode_frames(request, verify=self.verify_checksum)
                      if batched
                      else [decode_frame(request,
                                         verify=self.verify_checksum)])
        except WireError as e:
            # corrupt/malformed request: refused before any execution —
            # DATA_LOSS ≙ the raw-TCP 'C' reply (the client transport
            # maps it back to WireCorruptionError, resend-safe)
            self.corrupt_requests += 1
            log.warning("corrupt request refused (DATA_LOSS): %s", e)
            context.abort(grpc.StatusCode.DATA_LOSS, f"corrupt request: {e}")
        try:
            answers = self.process(
                frames, float(context.time_remaining() or 30.0))
        except ServerGoawayError as e:
            # UNAVAILABLE + goaway detail ≙ the raw-TCP 'G' reply; the
            # client transport maps it back to ServerGoawayError —
            # immediate resend-safe failover, never a breaker event
            context.abort(grpc.StatusCode.UNAVAILABLE, f"goaway: {e}")
        except ServerBusyError as e:
            # RESOURCE_EXHAUSTED ≙ the raw-TCP BUSY reply; the client
            # transport maps it back to ServerBusyError (backpressure,
            # not ill-health — see resilience.is_remote_application_error)
            context.abort(
                grpc.StatusCode.RESOURCE_EXHAUSTED,
                f"server busy; retry_after={e.retry_after:.6f}",
            )
        except TimeoutError as e:
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(e))
        if batched:
            return encode_frames(answers, version=self.wire_version)
        return encode_frame(answers[0], version=self.wire_version)

    def _invoke_stream(self, request: bytes, context):
        """Server-streaming invoke: ONE request frame in, answer frames
        streamed out as the server pipeline produces them, until an
        answer carries ``meta["final"] is True`` (the tensor_generator
        chunk contract) — remote interactive serving: tokens reach the
        client while later chunks are still decoding.

        Non-streaming server graphs work too: a plain 1:1 pipeline's
        single answer has no ``final`` meta, so exactly one message is
        streamed and the stream closes via the sentinel check below."""
        try:
            frame = decode_frame(request, verify=self.verify_checksum)
        except WireError as e:
            self.corrupt_requests += 1
            log.warning("corrupt stream request refused (DATA_LOSS): %s", e)
            context.abort(grpc.StatusCode.DATA_LOSS, f"corrupt request: {e}")
        gen = self.process_stream(
            frame, float(context.time_remaining() or 30.0))
        try:
            for ans in gen:
                yield encode_frame(ans, version=self.wire_version)
        except ServerGoawayError:
            context.abort(grpc.StatusCode.UNAVAILABLE,
                          "goaway: server draining")
        except ServerBusyError as e:
            context.abort(
                grpc.StatusCode.RESOURCE_EXHAUSTED,
                f"server busy; retry_after={e.retry_after:.6f}",
            )
        except TimeoutError as e:
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(e))
        finally:
            # a cancelled RPC abandons this handler mid-yield: closing
            # the shared generator runs its cleanup (pending slot freed
            # -> the serversink's next chunk delivery sees client-gone
            # and cancels the stream upstream; admission released)
            gen.close()

    def resolve(self, client_id: int, frame: TensorFrame,
                limit: int = 0) -> bool:
        """serversink delivers an answer to the waiting client RPC.
        ``limit`` > 0 bounds queued answers per client (≙ serversink
        `limit` prop); excess answers are dropped with a warning."""
        with self._pending_lock:
            # membership check, limit check, AND the put share the lock:
            # a client timing out concurrently pops its queue in
            # _pending_client's finally (also under this lock), so an
            # answer can never land in an abandoned queue and report
            # success, and concurrent resolvers cannot overshoot `limit`
            q = self._pending.get(client_id)
            heuristic = q is None and client_id in self._heuristic_closed
            if q is not None:
                if limit > 0 and q.qsize() >= limit:
                    log.warning(
                        "client %s answer queue at limit %d (answer "
                        "dropped)", client_id, limit,
                    )
                    return False
                # never a blocking put: a timed-out client abandons its
                # queue with no consumer — a blocked put would wedge the
                # serversink thread forever (drop + warn instead)
                try:
                    q.put_nowait(frame)
                    return True
                except queue.Full:
                    log.warning(
                        "client %s answer queue full (answer dropped)",
                        client_id,
                    )
                    return False
        if q is None:
            if heuristic:
                log.warning(
                    "no pending client %s (answer dropped): its stream was "
                    "closed because an earlier answer carried no 'final' "
                    "meta key — multi-answer server graphs must stamp "
                    "meta['final']=False on intermediate answers",
                    client_id,
                )
            else:
                log.warning(
                    "no pending client %s (answer dropped)", client_id
                )
        return False

    def liveness_snapshot(self) -> Dict[str, Any]:
        """Load-shed / admission counters for ``Pipeline.health()`` (the
        serversrc merges this via ``health_info``)."""
        snap = self.admission.snapshot()
        return {
            "inflight": snap["inflight"],
            "admitted": snap["admitted"],
            "load_shed": snap["shed"],
            "shedding": snap["shedding"],
            "admission_high": snap["high"],
            "admission_low": snap["low"],
            # exact per-tenant {inflight, admitted, shed, quota} rows —
            # the fleet-chaos accounting contract (empty for a plain
            # AdmissionController swapped in by tests); tenants_evicted
            # counts idle ledgers dropped by the cardinality bound, so
            # a truncated tenant table is never silent
            "tenants": snap.get("tenants", {}),
            "tenants_evicted": snap.get("tenants_evicted", 0),
            # memory-watermark sheds (reason="memory"): requests refused
            # because the chip was near HBM exhaustion — the "shed BUSY
            # before the OOM" contract, counted exactly
            "memory_shed": snap.get("memory_shed", 0),
            "ingress_depth": self.ingress.qsize(),
            "corrupt_requests": self.corrupt_requests,
            "draining": self.draining,
            "goaway_sent": self.goaway_sent,
        }

    # -- rolling restart (core/lifecycle.py) --------------------------------
    def begin_drain(self) -> None:
        """Enter the draining state: every transport starts refusing NEW
        requests with GOAWAY; requests already admitted finish
        normally."""
        if not self.draining:
            self.draining = True
            log.info("query server :%d draining (GOAWAY to new requests)",
                     self.port)

    @property
    def drain_complete(self) -> bool:
        """True once no admitted request is still in flight and nothing
        remains queued for the server pipeline."""
        return self.admission.inflight == 0 and self.ingress.empty()

    def close_listeners(self) -> None:
        """Stop accepting entirely (listeners closed) without cutting
        in-flight replies: the raw-TCP path keeps connection readers
        serving until the last reply is out, and the gRPC stop() grace
        gives an RPC that outlived ``drain-deadline`` the same courtesy
        (new RPCs are refused immediately either way; stop() returns
        without blocking)."""
        if self._server is not None:
            self._server.stop(grace=30.0)
            self._server = None
        if self._tcp is not None:
            self._tcp.close_listener()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        self.closed = False
        if self._server is not None:
            return
        handlers = {
            "Handshake": grpc.unary_unary_rpc_method_handler(
                self._handshake, request_deserializer=_ident, response_serializer=_ident
            ),
            "Invoke": grpc.unary_unary_rpc_method_handler(
                self._invoke, request_deserializer=_ident, response_serializer=_ident
            ),
            "InvokeStream": grpc.unary_stream_rpc_method_handler(
                self._invoke_stream,
                request_deserializer=_ident, response_serializer=_ident,
            ),
        }
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=32),
            options=[("grpc.max_receive_message_length", 512 * 1024 * 1024),
                     ("grpc.max_send_message_length", 512 * 1024 * 1024)],
        )
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler("nns.Query", handlers),)
        )
        bound = self._server.add_insecure_port(f"{self.host}:{self.port}")
        if bound == 0:
            raise RuntimeError(f"cannot bind query server on port {self.port}")
        self.port = bound
        self._server.start()
        log.info("query server on :%d", self.port)

    def start_tcp(self) -> None:
        """Serve over the raw-TCP zero-copy transport instead of gRPC
        (connect-type=tcp; ≙ the reference's nns-edge TCP default).
        Re-entrant: a listener closed by a drain re-opens on the same
        port (rolling restart of the serversrc element)."""
        self.closed = False
        if self._tcp is not None:
            self._tcp.start()  # no-op when the listener is already live
            return
        from .tcp_query import TcpQueryServer

        self._tcp = TcpQueryServer(
            self, port=self.port,
            wire_version=self.wire_version,
            verify_checksum=self.verify_checksum,
        )
        self._tcp.start()
        self.port = self._tcp.port

    def stop(self) -> None:
        self.closed = True  # unwedge handler threads parked on answers
        if self._server is not None:
            self._server.stop(grace=0.5)
            self._server = None
        if self._tcp is not None:
            self._tcp.stop()
            self._tcp = None


_servers_lock = threading.Lock()
_servers: Dict[int, QueryServerCore] = {}


def get_query_server(server_id: int, port: int = 0) -> QueryServerCore:
    """Process-global serversrc/serversink pairing by id."""
    with _servers_lock:
        core = _servers.get(server_id)
        if core is None:
            core = QueryServerCore(port)
            _servers[server_id] = core
        elif port and core._server is None and core.port == 0:
            # the paired serversink may have created the core first (element
            # start order is textual); honor the serversrc's configured port
            core.port = port
        core.refs += 1
        return core


def release_query_server(server_id: int) -> None:
    with _servers_lock:
        core = _servers.get(server_id)
        if core is None:
            return
        core.refs -= 1
        if core.refs <= 0:
            core.stop()
            del _servers[server_id]


# ---------------------------------------------------------------------------
# Query client
# ---------------------------------------------------------------------------
class QueryConnection:
    """Client side of /nns.Query (≙ nns_edge client handle)."""

    def __init__(self, host: str, port: int, timeout: float = 10.0,
                 verify_checksum: bool = True):
        self.addr = f"{host}:{port}"
        self.timeout = timeout
        self._verify = bool(verify_checksum)
        self._channel = grpc.insecure_channel(
            self.addr,
            options=[("grpc.max_receive_message_length", 512 * 1024 * 1024),
                     ("grpc.max_send_message_length", 512 * 1024 * 1024)],
        )
        self._invoke = self._channel.unary_unary(
            "/nns.Query/Invoke", request_serializer=_ident, response_deserializer=_ident
        )
        self._handshake = self._channel.unary_unary(
            "/nns.Query/Handshake", request_serializer=_ident, response_deserializer=_ident
        )
        self._invoke_stream_rpc = self._channel.unary_stream(
            "/nns.Query/InvokeStream",
            request_serializer=_ident, response_deserializer=_ident,
        )

    @staticmethod
    def _map_busy(err: grpc.RpcError) -> None:
        """Translate server status codes both transports share onto one
        client-side vocabulary: RESOURCE_EXHAUSTED (admission refusal)
        -> :class:`ServerBusyError` (≙ the raw-TCP BUSY reply),
        DATA_LOSS (corrupt request refused before execution) ->
        :class:`WireCorruptionError` (≙ the raw-TCP 'C' reply,
        resend-safe), and UNAVAILABLE carrying the goaway detail (the
        server DECIDED to refuse — it is draining) ->
        :class:`ServerGoawayError` (≙ the raw-TCP 'G' reply; a bare
        UNAVAILABLE stays a transport fault and keeps counting against
        the remote's health)."""
        code = getattr(err, "code", lambda: None)()
        if code == grpc.StatusCode.DATA_LOSS:
            raise WireCorruptionError(
                str(getattr(err, "details", lambda: "")() or "corrupt request")
            ) from err
        if code == grpc.StatusCode.UNAVAILABLE:
            detail = str(getattr(err, "details", lambda: "")() or "")
            # exact-prefix match on OUR server's reply format: gRPC's own
            # transport errors can mention "GOAWAY" mid-detail (HTTP/2
            # GOAWAY frame on abrupt termination) and those are real
            # faults — they must keep counting against the remote
            if detail.startswith("goaway"):
                raise ServerGoawayError(detail) from err
            return
        if code != grpc.StatusCode.RESOURCE_EXHAUSTED:
            return
        retry_after = 0.05
        detail = str(getattr(err, "details", lambda: "")() or "")
        marker = "retry_after="
        if marker in detail:
            try:
                retry_after = float(detail.split(marker, 1)[1].split()[0])
            except ValueError:
                pass
        raise ServerBusyError(retry_after=retry_after) from err

    def handshake(self, caps: str) -> str:
        return self._handshake(caps.encode(), timeout=self.timeout).decode()

    def invoke(self, frame: TensorFrame, timeout: Optional[float] = None) -> TensorFrame:
        try:
            data = self._invoke(
                encode_frame(frame), timeout=timeout or self.timeout
            )
        except grpc.RpcError as e:
            self._map_busy(e)
            raise
        return decode_frame(data, verify=self._verify)

    def invoke_stream(self, frame: TensorFrame,
                      timeout: Optional[float] = None):
        """Server-streaming invoke: yields answer frames as they arrive
        (the last one is final-flagged or has no ``final`` meta).
        ``timeout`` bounds the WHOLE stream."""
        try:
            for data in self._invoke_stream_rpc(
                encode_frame(frame), timeout=timeout or self.timeout
            ):
                yield decode_frame(data, verify=self._verify)
        except grpc.RpcError as e:
            self._map_busy(e)
            raise

    def invoke_batch(self, frames: List[TensorFrame],
                     timeout: Optional[float] = None) -> List[TensorFrame]:
        """N frames in one RPC (wire micro-batch); answers in order."""
        try:
            data = self._invoke(
                encode_frames(frames), timeout=timeout or self.timeout
            )
        except grpc.RpcError as e:
            self._map_busy(e)
            raise
        return decode_frames(data, verify=self._verify)

    def close(self) -> None:
        self._channel.close()


# ---------------------------------------------------------------------------
# Edge pub/sub broker (≙ nnstreamer-edge pub/sub + MQTT broker role)
# ---------------------------------------------------------------------------
class EdgeBroker:
    """In-process topic broker served over gRPC: publishers push frames,
    subscribers hold a server-streaming RPC per topic."""

    def __init__(self, port: int, host: str = "[::]"):
        self.port = port
        self.host = host
        self._subs: Dict[str, List[queue.Queue]] = {}
        self._lock = threading.Lock()
        self._server: Optional[grpc.Server] = None
        self.refs = 0

    def publish_local(self, topic: str, data: bytes) -> int:
        with self._lock:
            subs = list(self._subs.get(topic, ()))
        for q in subs:
            try:
                q.put_nowait(data)
            except queue.Full:
                pass  # slow subscriber drops (pub/sub semantics)
        return len(subs)

    def _publish(self, request: bytes, context) -> bytes:
        topic_len = request[0]
        topic = request[1 : 1 + topic_len].decode()
        self.publish_local(topic, request[1 + topic_len :])
        return b""

    def _subscribe(self, request: bytes, context):
        topic = request.decode()
        q: "queue.Queue[bytes]" = queue.Queue(64)
        with self._lock:
            self._subs.setdefault(topic, []).append(q)
        try:
            while context.is_active():
                try:
                    yield q.get(timeout=0.2)
                except queue.Empty:
                    continue
        finally:
            with self._lock:
                if q in self._subs.get(topic, ()):
                    self._subs[topic].remove(q)

    def start(self) -> None:
        if self._server is not None:
            return
        handlers = {
            "Publish": grpc.unary_unary_rpc_method_handler(
                self._publish, request_deserializer=_ident, response_serializer=_ident
            ),
            "Subscribe": grpc.unary_stream_rpc_method_handler(
                self._subscribe, request_deserializer=_ident, response_serializer=_ident
            ),
        }
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=16))
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler("nns.Edge", handlers),)
        )
        bound = self._server.add_insecure_port(f"{self.host}:{self.port}")
        if bound == 0:
            raise RuntimeError(f"cannot bind edge broker on port {self.port}")
        self.port = bound
        # ephemeral binds (port=0) enter the registry only now, under the
        # real port, so release-by-bound-port always finds them
        with _brokers_lock:
            _brokers.setdefault(self.port, self)
        self._server.start()
        log.info("edge broker on :%d", self.port)

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=0.5)
            self._server = None


_brokers_lock = threading.Lock()
_brokers: Dict[int, EdgeBroker] = {}


def get_edge_broker(port: int) -> EdgeBroker:
    with _brokers_lock:
        broker = _brokers.get(port) if port else None
        if broker is None:
            broker = EdgeBroker(port)
            if port:
                _brokers[port] = broker
        broker.refs += 1
        return broker


def release_edge_broker(port: int) -> None:
    with _brokers_lock:
        broker = _brokers.get(port)
        if broker is None:
            return
        broker.refs -= 1
        if broker.refs <= 0:
            broker.stop()
            del _brokers[port]


class EdgePublisher:
    """Client publishing frames to a (possibly remote) broker."""

    def __init__(self, host: str, port: int, topic: str):
        self.topic = topic.encode()
        if len(self.topic) > 255:
            raise ValueError(
                f"edge topic exceeds 255 bytes ({len(self.topic)}): {topic[:40]!r}…"
            )
        self._channel = grpc.insecure_channel(f"{host}:{port}")
        self._publish = self._channel.unary_unary(
            "/nns.Edge/Publish", request_serializer=_ident, response_deserializer=_ident
        )

    def publish(self, frame: TensorFrame) -> None:
        payload = bytes([len(self.topic)]) + self.topic + encode_frame(frame)
        self._publish(payload, timeout=10.0)

    def close(self) -> None:
        self._channel.close()


class EdgeSubscriber:
    """Client holding a Subscribe stream; yields TensorFrames."""

    def __init__(self, host: str, port: int, topic: str,
                 verify_checksum: bool = True):
        self.topic = topic
        self._verify = bool(verify_checksum)
        #: frames dropped because they failed decode/integrity checks —
        #: one bad transmission must degrade to a gap, not end the stream
        self.corrupt_dropped = 0
        self._channel = grpc.insecure_channel(f"{host}:{port}")
        self._subscribe = self._channel.unary_stream(
            "/nns.Edge/Subscribe", request_serializer=_ident, response_deserializer=_ident
        )
        self._stream = None

    def frames(self):
        self._stream = self._subscribe(self.topic.encode())
        for data in self._stream:
            try:
                yield decode_frame(data, verify=self._verify)
            except WireError as e:
                self.corrupt_dropped += 1
                log.warning("undecodable edge frame dropped: %s", e)

    def close(self) -> None:
        if self._stream is not None:
            self._stream.cancel()
        self._channel.close()
